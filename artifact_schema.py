"""Provenance stamping shared by bench.py and the tools/ artifact writers.

Side-effect-free on import (no jax, no env-gated config mutation) — tools
that must control backend initialisation order (tools/calibrate_tpu.py)
can import this before touching jax.

Schema (see the note at the top of bench.py): every committed artifact
carries ``git_sha`` (HEAD when the number was MEASURED), ``workload`` (the
knobs that define the metric — canonical; no loose duplicates elsewhere in
the artifact) and ``workload_hash`` (sha256[:12] of the canonical workload
JSON).  Artifacts whose own schema already exposes the knobs top-level for
programmatic consumers (flash_ab's resume check) embed only the hash.

``artifacts/host_overhead.json`` (``bench.py --config overhead`` /
``tools/host_overhead_bench.py``) records the executor dispatch-path
evidence: ``raw_jit_us`` (bare trivial-jit dispatch — the floor),
``step_jit_us`` (the executor's OWN jitted step dispatched bare: the
program's compute/thunk floor a zero-overhead executor would still
pay), ``device_feed_us``/``numpy_feed_us``/``pipelined_feed_us``
(``ex.run`` / ``ex.run_steps(sync=False)`` wall per step),
``dispatch_overhead_us`` (the executor's per-step host Python, measured
directly as loop wall minus in-jit time under synchronous dispatch),
``plan_cache`` (run-plan hit/miss counters over the steady-schema loop)
and ``async_bitwise_equal`` (sync=False vs sync loss/state parity).
``overhead_multiple_vs_raw_jit`` = (overhead_pair_raw_us +
dispatch_overhead_us) / overhead_pair_raw_us, each quantity the MINIMUM
over many short interleaved rounds (shared-host contention only ever
inflates a round, so min is the least-noise estimate of each; the raw
per-round pairs ride in ``overhead_pairs``; a minimum-RATIO pick would
be floor-seeking) — the ISSUE 9 ≤ 2.0 gate; pre-ISSUE-9 artifacts
computed
``device_feed_us / raw_jit_us`` (kept as ``wall_multiple_vs_raw_jit``),
which folded ``step_jit_us`` into "overhead".

ISSUE 10 added the telemetry fields: ``host_overhead.json`` records the
span-tracing tax (``traced_dispatch_overhead_us``, ``trace_overhead_us``
= traced minus untraced per-step host Python over interleaved toggled
rounds, ``trace_overhead_pct`` against the untraced dispatch path,
gated <= ``trace_gate_pct`` 25%); step-timed configs carry
``step_time_hist_ms`` ({sub: count/mean/p50/p99}) from the obs
registry's log-bucketed ``step_time_us`` histogram — percentiles, not
just means; ``--config serve`` adds ``latency_hist_ms`` /
``chaos_latency_hist_ms`` ({queue_wait, batch} per run) from
``serve_latency_us``; ``--config trace`` commits
``artifacts/trace_step.json``, a Chrome/Perfetto trace (the
``traceEvents`` schema, NOT the provenance schema) of a 5-step wdl-PS
run with a mid-run primary kill — step spans, per-opcode RPC spans,
fault point events, serving + feed-pipeline tracks.

``artifacts/decode_bench.json`` (``bench.py --config decode``, ISSUE 16
schema v2 per ISSUE 18) compares continuous (chunked-prefill),
token-by-token and request-level decoding of one seeded zipf stream in
interleaved best-of rounds: per-leg ``tokens_per_s``/``p50_ms``/
``p99_ms`` + decode counters, ``streams_bitwise_equal`` across all
three, ``compile_once`` (``bucket_keys`` now counts ``(batch, len)``
pairs PLUS chunked ``(batch, chunk, len)`` triples against
``bucket_key_bound``), ``prefill`` (chunked steps, steps saved vs
token-by-token, skipped logits fetches), ``ttft_vs_token_by_token``
(per-prompt-length chunked vs token-by-token time-to-first-token,
measured directly on engines, min over reps; ``ttft_wins_every_length``
gates it), ``ttft_histogram`` (the ``ttft`` label of
``decode_latency_us`` — one observation per stream,
``ttft_counted_per_stream``), ``prefix_cache`` (pool-stream hit/miss/
eviction counts, ``hit_rate``, ``prefill_rows_cold`` vs ``_warm``, and
the warm run's bitwise parity with its cold reference), the ISSUE 16
``kv_cache_vs_reprefill`` per-length leg, and the ISSUE 19
``recovery`` leg (schema v3): a 2-replica decode FrontDoor under a
``kill:replica@0:tok<n>`` chaos fault on the engine's token clock —
``kill_spec``, ``failed_streams`` (must be 0), ``restarts`` (must be
0), ``streams_bitwise_equal_to_unkilled``, the ``decode_recovery_*``
``counters`` + fleet counters, ``reseat_latency_us`` (the ``recovery``
label of ``decode_latency_us`` — one observation per reseated
stream), and ``zero_survivor`` (killing a 1-replica door's only
replica: every in-flight stream fails loudly with
``recovery_exhausted`` and ``partials_attached``).

``artifacts/fleet_bench.json`` (``bench.py --config fleet``, ISSUE 17)
is the fleet-tier acceptance: ``slo`` (interactive p99 vs target, both
runs), ``scaling`` (the autoscaler's resize timeline on the admission
clock — ``{admitted, kind, from_replicas, to_replicas, p99_ms,
load_factor}`` — plus ``replicas_hw``), ``rejections`` /
``per_class_rejections`` (structured ``serve_rejection_reason`` counts;
the family counts at ServeRejected CONSTRUCTION, so internal dispatch
retries against a freshly killed replica can appear as ``draining``
entries that were absorbed, never user-visible — the per-class dict is
the door-visible truth), ``bounded_queues`` (max per-replica pending vs
``queue_limit``; a chaos-run survivor may briefly hold up to 2x while
ADOPTING a dead replica's rescued queue), ``spin_up`` (scale-out's
``step_cache_serve_hit`` vs ``serve_bucket_compiles`` deltas) and
``chaos`` (the ``kill:replica`` run: restarts=0, failed futures,
bitwise response parity on requests admitted in both runs).

Chaos/robustness artifacts (``chaos``, ``failover``, ``serve``,
``partition``, ``fleet``) additionally follow a shared convention in
``extra``:
``restarts``/``resumes`` (must be 0 for the transparent-recovery
configs), ``fault_counters`` (the chaos run's evidence),
``clean_run_counters`` (must be ``{}``), and loss/response parity flags
against the clean run.

``artifacts/protocol_verify.json`` (``tools/verify_protocols.py
--deep --out ...``, ISSUE 20) is the protocol model checker's verdict:
per-model ``models.<name>`` blocks (``states``/``transitions``/
``depth`` of the exhaustive BFS, ``complete`` — False means the budget
truncated exploration and the verdict is NOT exhaustive — and
``violations`` with rendered shortest counterexample traces, empty at
HEAD), ``mutations.<name>`` (each seeded historical bug class with the
``expected`` vs ``violated`` invariant name and counterexample length —
all must be CAUGHT), and ``conformance_selftest`` (the trace monitors
accept a canned well-formed run and flag each canned bad trace by
rule).  The chaos artifacts above additionally carry
``protocol_conformance`` blocks in ``extra``: the recorded kill-run
event trace replayed against the same models' transition relations
(``ok`` gates the leg; a non-empty ``divergences`` list names the
violated rule per event).  ``--config partition``
(``artifacts/partition_smoke.json``) adds the fencing-epoch evidence:
``fsck_serving_ranks``/``fsck_epochs`` (exactly one serving epoch per
shard post-heal), ``noheal_lineage_violations`` (the unhealed split
brain fsck detects), and ``two_cell`` (per-cell admitted/answered/
rejections through the cross-cell cut plus post-heal fsck convergence).
"""
import hashlib
import json
import os
import subprocess

_ROOT = os.path.dirname(os.path.abspath(__file__))


def git_sha():
    """HEAD sha at measurement time (12 hex), or None outside a checkout."""
    try:
        proc = subprocess.run(
            ["git", "-C", _ROOT, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10)
        return proc.stdout.strip()[:12] or None if proc.returncode == 0 \
            else None
    except (OSError, subprocess.TimeoutExpired):
        return None


def workload_hash(workload):
    blob = json.dumps(workload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def provenance(workload, embed_workload=True):
    """Uniform provenance block: the sha ties the number to the code that
    produced it, the hash to the exact workload.  ``embed_workload=False``
    for artifacts whose own schema already carries the knobs top-level."""
    out = {"git_sha": git_sha(), "workload_hash": workload_hash(workload)}
    if embed_workload:
        out["workload"] = dict(workload)
    return out
