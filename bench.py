"""Benchmark driver — prints ONE JSON line for the round harness.

Primary config (BASELINE.json): BERT-base MLM pretraining, samples/sec/chip
and MFU vs the 45%-MFU north-star target.  ``--config resnet18`` covers the
CIFAR10 step-time config.
"""
import argparse
import json
import sys
import time

import numpy as np


def _sync(outs):
    """Force completion: remote platforms (axon tunnel) do not honor
    block_until_ready/wait, so read one element back to host — training
    steps chain through the params, so this syncs every dispatched step."""
    for o in outs:
        if o is None:
            continue
        arr = o.jax() if hasattr(o, "jax") else o
        if getattr(arr, "ndim", 0):
            arr = arr.ravel()[0]
        np.asarray(arr)


def _params_count(ex):
    return int(sum(np.prod(v.shape) for n, v in ex.var_values.items()
                   if n.trainable))


def bench_bert(batch_size=192, seq_len=128, steps=20, warmup=3):
    import jax
    import hetu_tpu as ht
    from hetu_tpu.models.bert import (BertConfig, bert_pretrain_graph,
                                      synthetic_mlm_batch)

    cfg = BertConfig.base(batch_size=batch_size, seq_len=seq_len)
    feeds, loss, logits = bert_pretrain_graph(cfg)
    opt = ht.optim.AdamOptimizer(1e-4)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]}, seed=0,
                     compute_dtype="bfloat16")
    ids, tt, labels = synthetic_mlm_batch(cfg)
    import jax as _jax  # pre-place feeds on device once: the bench measures
    fd = {feeds["input_ids"]: _jax.device_put(np.asarray(ids, np.float32)),
          feeds["token_type_ids"]: _jax.device_put(np.asarray(tt, np.float32)),
          feeds["masked_lm_labels"]: _jax.device_put(np.asarray(labels, np.float32))}

    for _ in range(warmup):
        out = ex.run("train", feed_dict=fd)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = ex.run("train", feed_dict=fd)
    _sync(out)
    dt = (time.perf_counter() - t0) / steps

    n_params = _params_count(ex)
    tokens = batch_size * seq_len
    # training FLOPs/token: 6N for matmul params + attention score/value terms
    flops_per_token = 6 * n_params + 12 * cfg.num_hidden_layers \
        * cfg.hidden_size * seq_len
    flops_per_step = flops_per_token * tokens
    n_dev = len(jax.devices())
    peak = {"tpu": 197e12}.get(jax.default_backend(), 50e12)  # v5e bf16 peak
    mfu = flops_per_step / dt / (peak * n_dev)
    samples_per_sec_chip = batch_size / dt / n_dev
    return {
        "metric": "bert_base_pretrain_samples_per_sec_per_chip",
        "value": round(samples_per_sec_chip, 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),  # fraction of 45%-MFU north star
        "extra": {
            "mfu": round(mfu, 4),
            "step_time_ms": round(dt * 1e3, 2),
            "batch_size": batch_size, "seq_len": seq_len,
            "params": n_params, "backend": jax.default_backend(),
            "devices": n_dev,
        },
    }


def bench_resnet18(batch_size=128, steps=20, warmup=3):
    import jax
    import hetu_tpu as ht
    sys.path.insert(0, "examples/cnn")
    import models

    x = ht.placeholder_op("x", shape=(batch_size, 3, 32, 32))
    y_ = ht.placeholder_op("y", shape=(batch_size, 10))
    loss, y = models.resnet18(x, y_)
    ex = ht.Executor({"train": [loss, ht.optim.MomentumOptimizer(0.1).minimize(loss)]},
                     compute_dtype="bfloat16")
    rng = np.random.RandomState(0)
    xv = rng.rand(batch_size, 3, 32, 32).astype(np.float32)
    yv = np.eye(10, dtype=np.float32)[rng.randint(0, 10, batch_size)]
    fd = {x: jax.device_put(xv), y_: jax.device_put(yv)}  # on-device feeds
    for _ in range(warmup):
        out = ex.run("train", feed_dict=fd)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = ex.run("train", feed_dict=fd)
    _sync(out)
    dt = (time.perf_counter() - t0) / steps
    return {
        "metric": "resnet18_cifar10_step_time",
        "value": round(dt * 1e3, 2),
        "unit": "ms/step",
        "vs_baseline": 0.0,
        "extra": {"batch_size": batch_size,
                  "backend": jax.default_backend()},
    }


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="bert", choices=["bert", "resnet18"])
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args()
    if args.config == "bert":
        res = bench_bert(batch_size=args.batch_size or 192, steps=args.steps)
    else:
        res = bench_resnet18(batch_size=args.batch_size or 128,
                             steps=args.steps)
    print(json.dumps(res))
