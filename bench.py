"""Benchmark driver — prints ONE JSON line for the round harness.

Primary config (BASELINE.json): BERT-base MLM pretraining, samples/sec/chip
and MFU vs the 45%-MFU north-star target.  ``--config resnet18`` covers the
CIFAR10 step-time config.

Artifact schema (uniform across every config and every tool artifact):
  value / unit        the headline number for this config
  vs_baseline         achieved ÷ declared baseline — >1.0 beats the
                      baseline, 1.0 matches it.  The baseline itself is
                      named in extra.baseline_def: the 45%-MFU north star
                      for bert (BASELINE.md), the committed same-workload
                      torch-CPU measurement for the rest.  0.0 ONLY when
                      the declared baseline is unavailable (baseline_def
                      then says why) — never as a euphemism for "slow".
  extra.git_sha       repo HEAD when the number was MEASURED (cached TPU
                      artifacts keep the sha of the measuring commit)
  extra.workload      the workload knobs that define the metric
  extra.workload_hash sha256[:12] of the canonical workload JSON — lets a
                      reviewer tie any artifact to the exact workload
                      without diffing dicts

Hardened against a flaky TPU backend (the round-1 artifact died with
"Unable to initialize backend 'axon'" and a >9-min hang): the parent process
runs the measurement in a child with a hard wall-clock budget and bounded
retries, and ALWAYS prints exactly one JSON line — with an ``error`` field
instead of a traceback/hang on failure.  Probe attempts retry with
decorrelated-jitter backoff under a bounded attempt budget
(``HETU_BENCH_PROBE_ATTEMPTS``), and every attempt's outcome is appended
to ``artifacts/tpu_probe_log.jsonl`` (the same log tools/tpu_watch.py
writes) so a wedged round leaves a per-attempt audit trail instead of a
silent near-timeout.
"""
import argparse
import json
import os
import random
import subprocess
import sys
import time

if os.environ.get("_HETU_BENCH_FORCE_CPU"):
    # fallback attempt after a wedged TPU backend: the sitecustomize pins
    # JAX_PLATFORMS, so the backend must be forced via jax.config BEFORE
    # anything imports hetu_tpu/jax-consumers
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

CHILD_ENV_FLAG = "_HETU_BENCH_CHILD"
DEFAULT_STEPS = 20
CHILD_TIMEOUT_S = int(os.environ.get("HETU_BENCH_CHILD_TIMEOUT", "420"))
TOTAL_BUDGET_S = int(os.environ.get("HETU_BENCH_BUDGET", "900"))
# a wedged axon tunnel hangs INSIDE jax.devices(), so backend liveness is
# probed in a disposable child with a short timeout before committing a
# full measurement attempt to it (the tunnel wedges and recovers on a
# scale of minutes — observed during rounds 1 and 2)
PROBE_TIMEOUT_S = int(os.environ.get("HETU_BENCH_PROBE_TIMEOUT", "90"))
# wall clock reserved at the end of the budget for the reduced-size CPU
# fallback measurement (an honest artifact beats no artifact)
CPU_RESERVE_S = int(os.environ.get("HETU_BENCH_CPU_RESERVE", "300"))


def _free_ports(n):
    """``n`` OS-assigned free localhost ports (bind, record, release) —
    shared by every in-process multi-rank chaos/serving config."""
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _sync(outs):
    """Force completion: remote platforms (axon tunnel) do not honor
    block_until_ready/wait, so read one element back to host — training
    steps chain through the params, so this syncs every dispatched step.
    Delegates to the ONE shared discipline in graph.executor."""
    from hetu_tpu.graph.executor import _sync_outs
    _sync_outs(outs)


def _timed(run_step, steps, warmup):
    """Shared timing harness: warmup, sync, timed loop, sync → s/step.
    ONE copy of the remote-platform sync discipline (see _sync).  The
    timed loop runs with the per-step wall-time histogram recording
    (``metrics.step_time_us`` on the obs registry), so every config
    that uses this harness gets p50/p99 step-time percentiles
    (``_step_percentiles``) alongside the mean — not just means."""
    from hetu_tpu import metrics as ht_metrics
    out = None
    for i in range(warmup):
        out = run_step(i)
    _sync(out)
    prev = ht_metrics.step_timing
    ht_metrics.reset_step_times()
    ht_metrics.enable_step_timing(True)
    try:
        t0 = time.perf_counter()
        for i in range(steps):
            out = run_step(i)
        _sync(out)
        return (time.perf_counter() - t0) / steps
    finally:
        ht_metrics.enable_step_timing(prev)


def _step_percentiles():
    """{sub: {p50_ms, p99_ms, count}} from the step-time histogram the
    last ``_timed`` loop recorded (obs registry; per-step dispatch wall
    — under sync=False stepping this measures dispatch, not device
    completion, same caveat as ``timing=True``)."""
    from hetu_tpu.metrics import step_time_stats
    return _hist_ms(step_time_stats())


def _hist_ms(snap):
    """Compress a microsecond histogram snapshot (obs registry) to
    artifact-friendly ms percentiles: {label: {count, mean_ms, p50_ms,
    p99_ms}} — empty labels dropped."""
    out = {}
    for label, h in (snap or {}).items():
        if not h.get("count"):
            continue
        out[label] = {"count": int(h["count"]),
                      "mean_ms": round(h["mean"] / 1e3, 3),
                      "p50_ms": round(h["p50"] / 1e3, 3),
                      "p99_ms": round(h["p99"] / 1e3, 3)}
    return out


def _params_count(ex):
    return int(sum(np.prod(v.shape) for n, v in ex.var_values.items()
                   if n.trainable))


def _device_peak_flops():
    """(peak_flops_per_chip, device_kind) — the shared per-device-kind
    table in ``hetu_tpu.obs`` (one table for bench AND the autoparallel
    measurement loop; hardcoding one generation's peak misreports MFU
    the moment the tunnel fronts a different chip — round-3 verdict)."""
    from hetu_tpu.obs import device_peak_flops
    return device_peak_flops()


from artifact_schema import provenance as _provenance  # noqa: E402


def _torch_bench_baseline(config, workload):
    """Committed same-workload torch-CPU baseline (reference methodology:
    every example family ships comparison scripts — tf_main.py etc.).
    Returns (value, label) or (None, None) when absent or workload-
    mismatched."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "artifacts", "torch_baselines_bench.json")
    try:
        with open(path) as f:
            row = json.load(f)[config]
        value = row["value"]
    except (OSError, KeyError, json.JSONDecodeError):
        return None, None
    extra = row.get("extra", {})
    if any(extra.get(k) != v for k, v in workload.items()):
        return None, None
    return value, f"{extra.get('framework', 'torch')}-cpu same-workload"


def _flash_in_hlo(ex, fd, name="train"):
    """True iff the compiled step's HLO contains the Pallas kernel's
    custom-call (evidence the flash kernel is in the MEASURED path)."""
    try:
        from hetu_tpu.profiler import HetuProfiler
        text = HetuProfiler(ex, name=name).hlo_text(fd)
        return any(t in text for t in ("tpu_custom_call", "mosaic"))
    except Exception:
        return None


def _compute_dtype():
    """bf16 on TPU (the real mixed-precision config); f32 on the CPU
    fallback — XLA-CPU EMULATES bf16 (measured 1.54x slower on resnet18)
    and the committed torch baselines run f32, so a CPU-side comparison
    must be f32 vs f32 to mean anything."""
    import jax
    return "bfloat16" if jax.default_backend() == "tpu" else None


def _load_example_models(family):
    """Load ``examples/<family>``'s models under a unique module name.

    Both cnn and ctr call their module ``models``; a plain ``import
    models`` serves whichever loaded first to the second caller when one
    process builds several configs (tools/hlo_audit.py --config all), and
    the old relative ``sys.path.insert(0, "examples/cnn")`` broke when
    invoked from outside the repo root."""
    import importlib.util
    root = os.path.dirname(os.path.abspath(__file__))
    base = os.path.join(root, "examples", family)
    path = os.path.join(base, "models", "__init__.py")
    if not os.path.exists(path):
        path = os.path.join(base, "models.py")
    name = f"_bench_{family}_models"
    if name in sys.modules:
        return sys.modules[name]
    kw = {}
    if path.endswith("__init__.py"):   # package: enable relative imports
        kw["submodule_search_locations"] = [os.path.dirname(path)]
    spec = importlib.util.spec_from_file_location(name, path, **kw)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        # never leave a half-initialized module for the next caller's
        # fast path to silently reuse
        sys.modules.pop(name, None)
        raise
    return mod


# -- shared graph builders ---------------------------------------------------
# Each bench_* measures the graph its build_*_graph builds, and
# tools/hlo_audit.py audits the SAME builders — the audited program and the
# measured program cannot drift apart.

def build_bert_graph(batch_size=64, seq_len=512,
                     compute_dtype="__bench_default__",
                     size="base", dp=None, zero=None, remat=None):
    """The flagship training step: BERT-base padded MLM (see bench_bert).
    Returns (cfg, ex, fd).

    ``dp``: build on a data-parallel mesh of that many devices;
    ``zero``: ZeRO weight-update-sharding stage on that mesh (bench_zero
    measures it); ``size``: 'base' | 'tiny' (the dp>=4 CPU-mesh memory
    bench uses tiny — same graph family, host-feasible state size);
    ``remat``: selective-remat policy (``off|dots|full|offload|auto`` —
    ``parallel/remat.py``; bench_remat sweeps it)."""
    import jax
    import hetu_tpu as ht
    from hetu_tpu.models.bert import (BertConfig, bert_pretrain_graph,
                                      synthetic_mlm_batch)

    if compute_dtype == "__bench_default__":
        compute_dtype = _compute_dtype()
    cfg = getattr(BertConfig, size)(batch_size=batch_size, seq_len=seq_len)
    feeds, loss, logits = bert_pretrain_graph(cfg)
    opt = ht.optim.AdamOptimizer(1e-4)
    strategy = ht.dist.DataParallel(num_devices=dp) if dp else None
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]}, seed=0,
                     compute_dtype=compute_dtype,
                     dist_strategy=strategy, zero=zero, remat=remat)
    ids, tt, labels, attn = synthetic_mlm_batch(cfg)
    # ids/labels/mask stay int32 end-to-end: integer feeds are exempt from
    # the bf16 compute_dtype cast (bf16 is exact only up to 256)
    fd = {feeds["input_ids"]: jax.device_put(np.asarray(ids, np.int32)),
          feeds["token_type_ids"]: jax.device_put(np.asarray(tt, np.int32)),
          feeds["masked_lm_labels"]: jax.device_put(np.asarray(labels, np.int32)),
          feeds["attention_mask"]: jax.device_put(np.asarray(attn, np.int32))}
    return cfg, ex, fd


def build_resnet18_graph(batch_size=128, data_format=None,
                         compute_dtype="__bench_default__"):
    """resnet18/CIFAR10 Momentum step (see bench_resnet18); data_format
    None → per-backend pick (measured: NHWC wins on TPU lane mapping,
    loses 1.5x on XLA-CPU — artifacts/resnet_cpu_root_cause.json).
    Returns (None, ex, fd)."""
    import jax
    import hetu_tpu as ht
    models = _load_example_models("cnn")

    if compute_dtype == "__bench_default__":
        compute_dtype = _compute_dtype()
    x = ht.placeholder_op("x", shape=(batch_size, 3, 32, 32))
    y_ = ht.placeholder_op("y", shape=(batch_size, 10))
    if data_format is None:
        data_format = "NHWC" if jax.default_backend() == "tpu" else "NCHW"
    loss, y = models.resnet18(x, y_, data_format=data_format)
    ex = ht.Executor(
        {"train": [loss,
                   ht.optim.MomentumOptimizer(0.1).minimize(loss)]},
        seed=0, compute_dtype=compute_dtype)
    rng = np.random.RandomState(0)
    xv = rng.rand(batch_size, 3, 32, 32).astype(np.float32)
    yv = np.eye(10, dtype=np.float32)[rng.randint(0, 10, batch_size)]
    fd = {x: jax.device_put(xv), y_: jax.device_put(yv)}
    return None, ex, fd


def build_wdl_graph(batch_size=2048, policy="lru"):
    """Wide&Deep CTR SGD step (see bench_wdl) — f32 end-to-end by design:
    the workload is embedding-lookup bound; bf16 would round 100k-row
    id-gradients for no MXU win.  Returns (None, ex, fd) plus the
    placeholder nodes for multi-batch feeding: (dense, sparse, y_)."""
    import hetu_tpu as ht
    ctr = _load_example_models("ctr")

    dense = ht.placeholder_op("dense")
    # ids must stay integral: float32 is exact only below 2^24, real
    # Criteo vocabs exceed it (the bench_bert int32-feed lesson)
    sparse = ht.placeholder_op("sparse", dtype=np.int64)
    y_ = ht.placeholder_op("y")
    loss, prob = ctr.wdl_criteo(dense, sparse, y_, batch_size,
                                vocab=100000, dim=16, embed_mode=policy,
                                lr=0.01)
    opt = ht.optim.SGDOptimizer(0.01)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]}, seed=0)
    d, s, y = ctr.synthetic_criteo(batch_size, vocab=100000)
    return None, ex, {dense: d, sparse: s, y_: y}, (dense, sparse, y_)


def build_moe_graph(batch_tokens=8192, compute_dtype="__bench_default__"):
    """GShard top-2 16-expert MoE Adam step (see bench_moe).
    Returns ({"d":..., "experts":...}, ex, fd) — the dims dict keeps
    bench_moe's reported metadata tied to the graph actually built."""
    import jax
    import hetu_tpu as ht

    if compute_dtype == "__bench_default__":
        compute_dtype = _compute_dtype()
    d, experts = 512, 16
    x = ht.placeholder_op("x", shape=(batch_tokens, d))
    y_ = ht.placeholder_op("y", shape=(batch_tokens, d))
    gate = ht.layers.TopKGate(d, batch_tokens, experts, k=2,
                              capacity_factor=1.25)
    moe = ht.layers.MoELayer(gate, ht.layers.Expert(experts, d, 4 * d))
    h, aux = moe(x)
    loss = ht.reduce_mean_op(ht.ops.mul_op(h - y_, h - y_), [0, 1]) \
        + aux * 0.01
    opt = ht.optim.AdamOptimizer(1e-3)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]}, seed=0,
                     compute_dtype=compute_dtype)
    rng = np.random.RandomState(0)
    fd = {x: jax.device_put(rng.randn(batch_tokens, d).astype(np.float32)),
          y_: jax.device_put(rng.randn(batch_tokens, d).astype(np.float32))}
    return {"d": d, "experts": experts}, ex, fd


def bench_bert(batch_size=None, seq_len=512, steps=20, warmup=3,
               remat=None):
    """Flagship config: BERT-base padded MLM pretraining.

    seq 512 (the flash-gated regime) with a real attention_mask input —
    the kernel's key-mask strip path is the measured path, per the round-3
    verdict (seq 128 dense never reached the kernel).

    The headline ``step_time_ms`` is the PIPELINED run (ISSUE 9):
    numpy-ingested feeds double-buffered to the device by
    ``Executor.run_steps`` + non-blocking (``sync=False``) stepping, at
    the backend's default compute dtype (bf16 on TPU).  The same-dtype
    unpipelined loop and (on TPU) the fp32 unpipelined reference ride in
    ``extra`` so the pipelining and bf16 wins are separable."""
    import jax
    from hetu_tpu.metrics import reset_run_plan_counts, run_plan_counts

    if batch_size is None:
        batch_size = 64 if seq_len >= 512 else 192
    cfg, ex, fd = build_bert_graph(batch_size=batch_size, seq_len=seq_len,
                                   remat=remat)

    # numpy ingest: the realistic feed path (a dataloader hands the
    # executor host arrays) — exactly what the feed pipeline overlaps
    fd_np = {node: np.asarray(v) for node, v in fd.items()}

    dt_unpip = _timed(lambda i: ex.run("train", feed_dict=fd_np),
                      steps, warmup)
    reset_run_plan_counts()
    from hetu_tpu import metrics as ht_metrics
    ht_metrics.reset_step_times()
    prev_timing = ht_metrics.step_timing
    ht_metrics.enable_step_timing(True)
    try:
        t0 = time.perf_counter()
        rs = ex.run_steps(lambda i: fd_np, steps, name="train",
                          sync=False)
        _sync(rs[-1])
        dt = (time.perf_counter() - t0) / steps
    finally:
        # restore, don't clobber: HETU_STEP_TIMING=1 processes keep
        # recording after the bench (the _timed harness's discipline)
        ht_metrics.enable_step_timing(prev_timing)
    # per-step dispatch-wall percentiles of the headline (pipelined,
    # sync=False) loop — the p99 tail the mean hides
    step_hist = _step_percentiles()
    plan_counters = run_plan_counts()
    if _compute_dtype():
        # TPU: the fp32 unpipelined reference the ISSUE 9 acceptance
        # compares against (same batch/seq/environment)
        _, ex32, fd32 = build_bert_graph(batch_size=batch_size,
                                         seq_len=seq_len,
                                         compute_dtype=None, remat=remat)
        fd32_np = {node: np.asarray(v) for node, v in fd32.items()}
        dt_fp32 = _timed(lambda i: ex32.run("train", feed_dict=fd32_np),
                         max(steps // 2, 1), warmup)
        del ex32, fd32
    else:
        # CPU fallback runs f32 either way (XLA-CPU emulates bf16; the
        # committed torch baselines are f32) — the reference IS dt_unpip
        dt_fp32 = dt_unpip
    out = ex.run("train", feed_dict=fd)

    n_params = _params_count(ex)
    # MFU counts only matmul-active params: the input embedding tables
    # (word/position/token-type) are lookups, not matmuls — counting them
    # inflated MFU ~20% (round-3 verdict).  The MLM decoder (hidden×vocab)
    # IS a matmul and stays in.
    embed_params = (cfg.vocab_size + cfg.max_position_embeddings
                    + cfg.type_vocab_size) * cfg.hidden_size
    n_matmul = n_params - embed_params
    tokens = batch_size * seq_len
    # training FLOPs/token: 6N (fwd+bwd matmuls) + attention score/value
    # terms 12·L·h·s (computed on padded shapes — that is what the MXU
    # executes; padding waste shows up as lower MFU, not hidden FLOPs)
    flops_per_token = 6 * n_matmul + 12 * cfg.num_hidden_layers \
        * cfg.hidden_size * seq_len
    flops_per_step = flops_per_token * tokens
    n_dev = len(jax.devices())
    peak, device_kind = _device_peak_flops()
    mfu = flops_per_step / dt / (peak * n_dev)
    # publish the per-run gauges on the obs registry: metrics_dump()
    # and tools/metricsd.py expose the same numbers this artifact embeds
    ht_metrics.record_run_gauges("bert", dt * 1e3, mfu)
    samples_per_sec_chip = batch_size / dt / n_dev
    final_loss = float(np.asarray(out[0].jax() if hasattr(out[0], "jax")
                                  else out[0]))
    try:
        st = jax.devices()[0].memory_stats() or {}
        hbm_gb = round(st.get("peak_bytes_in_use", 0) / 2**30, 2) or None
    except Exception:
        hbm_gb = None
    return {
        "metric": "bert_base_pretrain_samples_per_sec_per_chip",
        "value": round(samples_per_sec_chip, 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "extra": {
            "baseline_def": "achieved MFU / 0.45 north-star MFU (BASELINE.md)",
            **_provenance({"batch_size": batch_size, "seq_len": seq_len,
                           **({"remat": remat} if remat else {})}),
            **({"remat": remat,
                "remat_plan": ex.remat_plan("train")} if remat else {}),
            "mfu": round(mfu, 4),
            "step_time_ms": round(dt * 1e3, 2),
            "step_time_hist_ms": step_hist,
            "pipelined": True,
            "step_time_ms_unpipelined": round(dt_unpip * 1e3, 2),
            "step_time_ms_fp32_unpipelined": round(dt_fp32 * 1e3, 2),
            "vs_fp32_unpipelined": round(dt_fp32 / max(dt, 1e-9), 3),
            "run_plan_counters": {k: int(v)
                                  for k, v in plan_counters.items()},
            # the active auto-parallel plan (or the naive data-parallel
            # default): lets the BENCH trajectory attribute step-time
            # moves to plan changes (ISSUE 15)
            "plan": (ex.plan.tag() if getattr(ex, "plan", None) is not None
                     else "naive-dp"),
            "params": n_params, "matmul_params": n_matmul,
            "flops_per_step": flops_per_step,
            "peak_flops": peak, "device_kind": device_kind,
            "flash_in_hlo": _flash_in_hlo(ex, fd),
            "peak_hbm_gb": hbm_gb,
            # per-device param/grad/opt-state bytes + live-buffer total:
            # the memory-side evidence peak_hbm_gb cannot give on CPU
            "memory": ex.memory_accounting(),
            "compute_dtype": _compute_dtype() or "float32",
            "backend": jax.default_backend(),
            "devices": n_dev, "loss": round(final_loss, 4),
        },
    }


def bench_zero(dp=4, steps=12, warmup=2, batch_size=8, seq_len=128,
               size="tiny"):
    """ISSUE 6 acceptance: ZeRO weight-update sharding vs replicated Adam
    at dp>=4 on the bert graph family.

    Three executors over the SAME graph + feeds — zero=0 (replicated
    baseline), zero=2 (reduce-scattered update, replicated params),
    zero=3 (sharded master params) — each run ``steps`` >= 10 steps.
    Records per-device param/grad/opt-state bytes, the live-buffer peak
    across steps, mean step time, and the full loss trajectory as raw
    float bits (the parity claim is BITWISE, not approximate).  On a CPU
    host-device mesh the state-memory ratio is the headline; 'tiny'
    keeps the replicated baseline host-feasible (same graph family as
    the flagship).  Writes ``artifacts/zero_bench.json``."""
    import gc
    import jax
    from hetu_tpu.graph import step_cache
    from hetu_tpu.metrics import reset_zero_counts, zero_counts

    if len(jax.devices()) < dp:
        raise RuntimeError(
            f"bench_zero needs >= {dp} devices — run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count={dp} (bench.py "
            f"--config zero sets this for its child automatically)")

    runs = {}
    for stage in (0, 2, 3):
        # the compiled-step cache pins its builder executor (and that
        # executor's state) alive — clear it so each run's live-buffer
        # numbers describe ONE executor
        step_cache.clear()
        gc.collect()
        reset_zero_counts()
        _, ex, fd = build_bert_graph(batch_size=batch_size,
                                     seq_len=seq_len, compute_dtype=None,
                                     size=size, dp=dp, zero=stage)
        losses, live_peak = [], 0
        for i in range(steps):
            out = ex.run("train", feed_dict=fd)
            losses.append(np.asarray(
                out[0].jax() if hasattr(out[0], "jax") else out[0],
                np.float32))
            if i in (0, steps // 2, steps - 1):  # sampling is not free
                mem = ex.memory_accounting()
                live_peak = max(live_peak,
                                mem["live_buffer_bytes_per_device"] or 0)
        dt = _timed(lambda i: ex.run("train", feed_dict=fd), steps, warmup)
        mem = ex.memory_accounting()
        runs[f"zero{stage}"] = {
            "zero_stage": stage,
            "loss_bits": [v.tobytes().hex() for v in losses],
            "final_loss": float(losses[-1]),
            "step_time_ms": round(dt * 1e3, 2),
            "live_buffer_peak_bytes_per_device": live_peak,
            "zero_counters": zero_counts(),
            **{k: mem[k] for k in
               ("param_bytes_per_device", "zero_slab_bytes_per_device",
                "opt_state_bytes_per_device", "grad_bytes_per_device")},
        }
        del ex, fd
    step_cache.clear()
    gc.collect()

    base, z2, z3 = runs["zero0"], runs["zero2"], runs["zero3"]
    bitwise2 = base["loss_bits"] == z2["loss_bits"]
    bitwise3 = base["loss_bits"] == z3["loss_bits"]
    opt_ratio = base["opt_state_bytes_per_device"] \
        / max(1, z2["opt_state_bytes_per_device"])
    state3 = z3["param_bytes_per_device"] \
        + z3["zero_slab_bytes_per_device"] \
        + z3["opt_state_bytes_per_device"]
    state0 = base["param_bytes_per_device"] \
        + base["opt_state_bytes_per_device"]
    # the step-time gate judges stage 3 — the full tentpole mode, whose
    # param all-gather sits at the top of the next step where XLA's async
    # scheduler overlaps it with early compute (stage 2's reduce-scatter
    # is emulated as all-reduce+slice on XLA-CPU and pays a CPU-only tax;
    # its ratio stays in extra)
    step_ratio = base["step_time_ms"] / max(1e-9, z3["step_time_ms"])
    res = {
        "metric": "zero_opt_state_shrink_vs_replicated",
        "value": round(opt_ratio, 2),
        "unit": "x",
        # >= ~0.95 = step-time parity or better (the acceptance gate)
        "vs_baseline": round(step_ratio, 3),
        "extra": {
            "baseline_def": "value = replicated per-device optimizer-"
                            "state bytes / zero-2 bytes (target ~dp); "
                            "vs_baseline = replicated step time / zero-3 "
                            "step time (>=0.95 = parity)",
            "step_ratio_zero2": round(
                base["step_time_ms"] / max(1e-9, z2["step_time_ms"]), 3),
            **_provenance({"dp": dp, "batch_size": batch_size,
                           "seq_len": seq_len, "size": size,
                           "steps": steps}),
            "loss_bitwise_equal": {"zero2": bitwise2, "zero3": bitwise3},
            "training_state_bytes_per_device":
                {"zero0": state0, "zero3": state3,
                 "ratio": round(state0 / max(1, state3), 2)},
            "runs": runs,
            "backend": jax.default_backend(),
        },
    }
    if not (bitwise2 and bitwise3):
        res["error"] = "loss NOT bitwise-equal to replicated Adam"
    try:
        from artifact_schema import provenance as _prov
        out = {**res, **_prov({"dp": dp, "steps": steps})}
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "artifacts", "zero_bench.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        os.replace(path + ".tmp", path)
    except Exception:
        pass    # the printed result is the bench contract; file is extra
    return res


REMAT_SWEEP_POLICIES = ("off", "dots", "full", "auto")


def _remat_artifact_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "artifacts", "remat_bench.json")


def _write_remat_partial(path, payload):
    """Atomic write of the (possibly partial) remat-sweep artifact —
    the cell store a wedged/killed attempt resumes from."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path + ".tmp", "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    os.replace(path + ".tmp", path)


def bench_remat(steps=8, warmup=2, batch_size=32, seq_len=256,
                size="tiny", parity_steps=3, artifact_path=None,
                probe_log_path=None, overlap_gate=True,
                policies=REMAT_SWEEP_POLICIES):
    """ISSUE 13 acceptance: the selective-remat policy sweep on the bert
    graph family, with PARTIAL-RUNWAY CHECKPOINTED measurement.

    One cell per policy (off / dots / full / auto), each a fresh
    executor over the same graph + feeds: bitwise loss bits
    (``parity_steps`` steps — remat replays the same ops, so parity is
    EXACT), mean + p50/p99 step time, the ``memory_accounting()``
    live-buffer peak (live arrays + the compiled step's XLA
    buffer-assignment temp — the in-step activation peak remat trades),
    a projected max-fitting batch size against the HBM budget, the MFU
    gauge, and — for the segmented policies — the resolved plan.
    ``auto``'s budget is derived from the measured ``full`` plan
    (persistent + half the priced activation bytes), so the greedy
    planner must land STRICTLY BETWEEN off and full on both peak and
    step time.

    Every completed cell is PERSISTED into the artifact immediately
    (workload-fingerprinted), and every attempt appends to
    ``artifacts/tpu_probe_log.jsonl`` — a wedged TPU tunnel that kills
    the sweep mid-cell (the BENCH_r02→r05 failure mode) resumes from
    the persisted cells on the next attempt instead of re-measuring
    finished ones (``_HETU_REMAT_WEDGE_AFTER=n`` simulates the kill
    after ``n`` fresh cells, for the resume test).  The dp=4 zero=3
    overlap audit (``tools/overlap_audit.py``) gates the same artifact:
    an audit failure is a bench ``error``, never a silent pass."""
    import gc
    import jax
    from hetu_tpu.graph import step_cache
    from hetu_tpu import metrics as ht_metrics
    from hetu_tpu.parallel import remat as remat_mod

    path = artifact_path or _remat_artifact_path()
    plog = probe_log_path or PROBE_LOG_PATH
    compute_dtype = _compute_dtype() or "float32"
    n_dev = len(jax.devices())
    workload = {"batch_size": batch_size, "seq_len": seq_len,
                "size": size, "steps": steps,
                "parity_steps": parity_steps,
                "backend": jax.default_backend(),
                "compute_dtype": compute_dtype}

    # resume: reuse completed cells iff the workload fingerprint matches
    prior_cells = {}
    try:
        with open(path) as f:
            prev = json.load(f)
        if prev.get("extra", {}).get("workload") == workload:
            prior_cells = {k: v for k, v in
                           prev.get("extra", {}).get("cells", {}).items()
                           if v.get("complete")}
    except (OSError, json.JSONDecodeError):
        pass

    try:
        wedge_after = int(os.environ.get("_HETU_REMAT_WEDGE_AFTER", "0"))
    except ValueError:
        wedge_after = 0

    peak_flops, device_kind = _device_peak_flops()
    budget_bytes, budget_source = remat_mod.resolve_budget()
    if budget_bytes is None:
        # the projection denominator when nothing is resolvable: the
        # 16G v5e the flagship is sized for (recorded, not hidden)
        budget_bytes, budget_source = int(16e9), "v5e-default"
    # attempt token: wall clocks from DIFFERENT attempts (a resumed
    # sweep) are not comparable on a shared box — the time gate below
    # re-gauges cross-attempt cells in this process
    attempt_id = f"{os.getpid()}-{int(time.time())}"

    from contextlib import contextmanager

    @contextmanager
    def _cell_build(pol, budget_mb):
        """One cell's build discipline, shared by measure_cell and the
        cross-attempt retime pass so the two can never measure under
        different conditions: cleared step cache, scoped
        HETU_HBM_BUDGET_MB, fresh executor+feeds."""
        step_cache.clear()
        gc.collect()
        prev_budget = os.environ.get("HETU_HBM_BUDGET_MB")
        if budget_mb is not None:
            os.environ["HETU_HBM_BUDGET_MB"] = str(budget_mb)
        try:
            cfg, ex, fd = build_bert_graph(
                batch_size=batch_size, seq_len=seq_len, size=size,
                remat=pol)
            yield cfg, ex, fd
        finally:
            if budget_mb is not None:
                if prev_budget is None:
                    os.environ.pop("HETU_HBM_BUDGET_MB", None)
                else:
                    os.environ["HETU_HBM_BUDGET_MB"] = prev_budget

    def measure_cell(pol, budget_mb=None):
        with _cell_build(pol, budget_mb) as (cfg, ex, fd):
            losses = []
            for _ in range(parity_steps):
                out = ex.run("train", feed_dict=fd)
                losses.append(np.asarray(
                    out[0].jax() if hasattr(out[0], "jax") else out[0],
                    np.float32))
            dt = _timed(lambda i: ex.run("train", feed_dict=fd),
                        steps, warmup)
            hist = _step_percentiles().get("train", {})
            from hetu_tpu.metrics import step_time_stats
            h_raw = step_time_stats().get("train", {})
            mem = ex.memory_accounting(feed_dict=fd, name="train")
            persistent = (mem["param_bytes_per_device"]
                          + mem["zero_slab_bytes_per_device"]
                          + mem["opt_state_bytes_per_device"]
                          + mem["grad_bytes_per_device"])
            temp = mem["step_temp_bytes_per_device"]
            peak = mem["live_buffer_peak_bytes_per_device"]
            # projected max-fitting batch: temp scales ~linearly with
            # batch rows; persistent does not
            max_batch = None
            if temp:
                max_batch = int(batch_size
                                * max(0, budget_bytes - persistent)
                                // temp)
            n_params = _params_count(ex)
            embed = (cfg.vocab_size + cfg.max_position_embeddings
                     + cfg.type_vocab_size) * cfg.hidden_size
            flops_per_step = (6 * (n_params - embed)
                              + 12 * cfg.num_hidden_layers
                              * cfg.hidden_size * seq_len) \
                * batch_size * seq_len
            # the cell's program jits onto ONE device (no mesh), so the
            # MFU denominator is one chip even in the 8-device child
            mfu = flops_per_step / dt / peak_flops
            ht_metrics.record_run_gauges(f"remat_{pol}", dt * 1e3, mfu)
            cell = {
                "policy": pol,
                "complete": True,
                "attempt": attempt_id,
                "loss_bits": [v.tobytes().hex() for v in losses],
                "final_loss": float(losses[-1]),
                "step_time_ms": round(dt * 1e3, 2),
                "step_time_p50_ms": hist.get("p50_ms"),
                "step_time_p99_ms": hist.get("p99_ms"),
                # exact per-step floor from the histogram: the noise-
                # robust ordering statistic on a shared box (the PR 9
                # min-discipline — contention only ever inflates)
                "step_time_min_ms": round(h_raw["min"] / 1e3, 3)
                if h_raw.get("min") is not None else None,
                "live_buffer_peak_bytes": peak,
                "step_temp_bytes": temp,
                "persistent_bytes": int(persistent),
                "max_batch_projected": max_batch,
                "mfu": round(mfu, 6),
                "remat_plan": ex.remat_plan("train"),
                "remat_counters": dict(ht_metrics.remat_counts()),
            }
            if budget_mb is not None:
                cell["auto_budget_mb"] = budget_mb
            del ex, fd
            return cell

    cells = {}
    measured = 0
    for pol in policies:
        if pol in prior_cells:
            cells[pol] = {**prior_cells[pol], "resumed": True}
            _append_probe_log({"source": "remat_bench", "ok": True,
                               "cell": pol, "reused": True},
                              path=plog)
            continue
        if wedge_after and measured >= wedge_after:
            _append_probe_log({"source": "remat_bench", "ok": False,
                               "cell": pol,
                               "err": "simulated wedged probe "
                                      "(_HETU_REMAT_WEDGE_AFTER)"},
                              path=plog)
            raise RuntimeError(
                f"simulated wedged probe after {measured} cells — "
                f"completed cells persisted at {path}; rerun resumes")
        budget_mb = None
        if pol == "auto":
            # budget from the measured full plan: persistent + half the
            # priced activation bytes -> the greedy planner must pick a
            # strict subset of segments
            fp = (cells.get("full") or {}).get("remat_plan") or {}
            act = fp.get("activation_bytes_total") or 0
            pers = fp.get("persistent_bytes") \
                or (cells.get("full") or {}).get("persistent_bytes", 0)
            if act:
                budget_mb = round((pers + act * 0.5) / 2**20, 2)
        ht_metrics.reset_remat_counts()
        cells[pol] = measure_cell(pol, budget_mb=budget_mb)
        measured += 1
        _append_probe_log({"source": "remat_bench", "ok": True,
                           "cell": pol, "reused": False}, path=plog)
        _write_remat_partial(path, {
            "metric": "remat_full_peak_reduction_vs_off",
            "value": None, "unit": "fraction", "vs_baseline": 0.0,
            "error": "sweep incomplete (partial-runway checkpoint)",
            "extra": {"workload": workload, "cells": cells,
                      **_provenance(workload)},
        })

    off, full = cells.get("off"), cells.get("full")
    auto = cells.get("auto")
    # parity baseline: 'off' when swept, else the first cell — a policy
    # SUBSET run (tests, a single-policy re-measure) must not crash or
    # record spurious gate errors about cells it never requested
    base_cell = off or next(iter(cells.values()))
    parity = all(c["loss_bits"] == base_cell["loss_bits"]
                 for c in cells.values())

    def _peak(c):
        return c.get("live_buffer_peak_bytes") if c else None

    reduction = None
    if _peak(off) and _peak(full):
        reduction = 1.0 - _peak(full) / _peak(off)
    # peaks may all be None where the backend/tunnel answers no AOT
    # memory analysis — that is a recorded gate FAILURE below, never a
    # TypeError crash that loses the artifact
    auto_between_peak = bool(
        _peak(off) and _peak(full) and _peak(auto)
        and _peak(full) < _peak(auto) < _peak(off))
    # time gate: wall clocks are comparable only within ONE attempt — a
    # resumed sweep re-gauges the three gating cells' step time in THIS
    # process (parity/memory evidence stays from the persisted cells)
    gate_cells = [c for c in (off, full, auto) if c]
    attempts = {c.get("attempt") for c in gate_cells}
    retimed = {}
    if (len(attempts) > 1 or None in attempts) and len(gate_cells) > 1:
        for pol in ("off", "full", "auto"):
            if pol not in cells:
                continue
            with _cell_build(pol, cells[pol].get("auto_budget_mb")) \
                    as (_cfg, ex, fd):
                _timed(lambda i: ex.run("train", feed_dict=fd),
                       steps, warmup)
                from hetu_tpu.metrics import step_time_stats
                h = step_time_stats().get("train", {})
                retimed[pol] = round(h["min"] / 1e3, 3) \
                    if h.get("min") is not None else None
                del ex, fd

    def t_floor(pol):
        c = cells[pol]
        return retimed.get(pol) or c.get("step_time_min_ms") \
            or c["step_time_p50_ms"]

    # 'between' gates on the per-step FLOOR (exact histogram min):
    # contention on a shared box only ever inflates a step, so the min
    # is the noise-robust statistic (the PR 9 min-discipline).  The
    # band is DIRECTION-AGNOSTIC with 5% tolerance: on the MXU-bound
    # TPU leg recompute strictly costs (off < auto < full); on XLA-CPU
    # remat is measured time-NEUTRAL-TO-FASTER (less activation
    # materialization beats the replay on a cache-bound core — dots'
    # floor lands ~15% under off), so 'between' means auto inside the
    # off/full envelope within tolerance, raw floors recorded per cell
    auto_between_time = False
    if off and full and auto and t_floor("auto"):
        lo = min(t_floor("off"), t_floor("full"))
        hi = max(t_floor("off"), t_floor("full"))
        auto_between_time = lo * 0.95 <= t_floor("auto") <= hi * 1.05

    overlap = {"checks": {}, "detail": {"skipped": "overlap gate off"}}
    if overlap_gate:
        try:
            from tools import overlap_audit
        except ImportError:
            import overlap_audit
        overlap = overlap_audit.run_overlap_audit()
    overlap_ok = (not overlap_gate) or (
        bool(overlap["checks"]) and all(overlap["checks"].values()))

    errors = []
    if not parity:
        errors.append("losses NOT bitwise-equal across policies")
    if off and full and (reduction is None or reduction < 0.30):
        errors.append(f"remat=full peak reduction "
                      f"{None if reduction is None else round(reduction, 3)}"
                      f" < 0.30 vs off")
    if off and full and auto \
            and not (auto_between_peak and auto_between_time):
        errors.append(f"auto not between off and full "
                      f"(peak {auto_between_peak}, "
                      f"time {auto_between_time})")
    if not overlap_ok:
        errors.append(f"overlap audit failed: {overlap['checks']}")

    res = {
        "metric": "remat_full_peak_reduction_vs_off",
        "value": round(reduction, 4) if reduction is not None else None,
        "unit": "fraction",
        # 1.0 = every policy's losses bitwise-equal to off
        "vs_baseline": 1.0 if parity else 0.0,
        "extra": {
            "baseline_def": "value = 1 - full/off live-buffer peak "
                            "(live arrays + compiled-step temp, "
                            "memory_accounting); vs_baseline 1.0 = all "
                            "policies' losses bitwise-equal to off; "
                            "auto_between.time = auto's step-time floor "
                            "inside the off/full envelope +-5% (strict "
                            "ordering is the TPU claim; XLA-CPU remat "
                            "measures time-neutral-to-faster)",
            **_provenance(workload),
            "workload": workload,
            "cells": cells,
            "loss_bitwise_equal": parity,
            "full_peak_reduction": round(reduction, 4)
            if reduction is not None else None,
            "auto_between": {"peak": auto_between_peak,
                             "time": auto_between_time},
            **({"retimed_min_ms": retimed,
                "retime_note": "cells resumed across attempts: step-"
                               "time floors re-gauged in one process "
                               "for the between gate"} if retimed
               else {}),
            "budget": {"bytes": budget_bytes, "source": budget_source},
            "overlap_audit": {"mode": overlap.get("mode"),
                              "checks": overlap["checks"],
                              **overlap["detail"]},
            "device_kind": device_kind,
            "devices": n_dev,
            "backend": jax.default_backend(),
        },
    }
    if jax.default_backend() != "tpu":
        res["extra"]["device_note"] = (
            "TPU unavailable — measured on the CPU backend at tiny "
            "size; peaks are XLA buffer-assignment bytes (backend-"
            "agnostic program evidence), step times are CPU wall")
    if errors:
        res["error"] = "; ".join(errors)
    _write_remat_partial(path, {**res, **_provenance(workload)})
    return res


def bench_overhead(smoke=False, steps=None, write_artifact=None,
                   gate_only=False):
    """See :func:`_bench_overhead_impl` — this wrapper only guarantees
    the process-global telemetry toggles (span tracing, step timing)
    are restored even when a measurement raises: the bench runs
    in-process under pytest, and leaking an inverted HETU_TRACE state
    into later tests would silently distort them."""
    from hetu_tpu import metrics as ht_metrics, obs
    prev_trace = obs.enabled()
    prev_step_timing = ht_metrics.step_timing
    try:
        return _bench_overhead_impl(smoke, steps, write_artifact,
                                    gate_only)
    finally:
        obs.enable(prev_trace)
        ht_metrics.enable_step_timing(prev_step_timing)


def _bench_overhead_impl(smoke, steps, write_artifact, gate_only):
    """ISSUE 9 acceptance: the executor's dispatch-gap evidence.

    One tiny graph (8x8 matmul + SGD — the XLA program is ~free, so
    per-step wall is dispatch + host Python) measured five ways:

    * ``raw_jit_us`` — dispatching a bare ``jax.jit`` fn (the floor)
    * ``step_jit_us`` — dispatching the executor's own jitted step
      directly (the program's floor: forward+backward+update is ~4x the
      raw program's thunks, so this is what a ZERO-overhead executor
      would cost)
    * ``device_feed_us`` / ``numpy_feed_us`` — ``ex.run`` wall per step
    * ``pipelined_feed_us`` — ``ex.run_steps(..., sync=False)`` wall per
      step with numpy feeds placed on the background feed pipeline
    * ``dispatch_overhead_us`` — the executor's per-step host Python
      measured DIRECTLY: total loop wall minus time inside the jit call
      (on CPU the loop runs under synchronous dispatch so XLA's compute
      threads cannot steal the timing core mid-Python-section), minus
      the instrumentation's own calibrated cost.

    ``overhead_multiple_vs_raw_jit`` = (overhead_pair_raw_us +
    dispatch_overhead_us) / overhead_pair_raw_us — the executor's host
    tax expressed against a raw dispatch, each quantity the minimum
    over short interleaved rounds (the ≤ 2.0 acceptance gate;
    ``raw_jit_us`` additionally folds in the standalone raw rounds, so
    recompute the gate from the pair fields).  Earlier artifacts computed
    ``device_feed_us / raw_jit_us``, which conflated the step program's
    own compute/thunk floor (now recorded as ``step_jit_us``) with host
    overhead — once the Python residue is ~1x a raw dispatch, wall time
    is compute-dominated and the tax must be measured directly.

    CI gates (``--smoke``, tier-1): plan-cache hits >= steps-1 on a
    steady feed schema, and async (``sync=False``) vs sync stepping
    bitwise-equal losses + final weights — parity, not wall clock, so
    CI stays deterministic.  ``gate_only`` measures ONLY the gate
    quantities (raw-jit floor, interleaved overhead pairs, the ISSUE 10
    tracing-tax pairs) and skips the wall/step-jit/parity measurements
    — the tier-1 subprocess guard's budget-friendly mode (parity is
    covered in-process by ``test_overhead_bench_smoke``)."""
    import gc
    import jax
    if write_artifact is None:
        write_artifact = not smoke
    # synchronous CPU dispatch for the overhead attribution: under async
    # dispatch XLA-CPU's compute threads contend with the timing thread,
    # inflating the measured Python sections 2-3x.  MUST land before ANY
    # backend query — even jax.default_backend() initializes the client,
    # after which the flag is a silent no-op (a live non-CPU backend
    # ignores it; the flag is CPU-client-specific).
    try:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    except Exception:
        pass
    import hetu_tpu as ht
    from hetu_tpu import metrics as ht_metrics, obs
    from hetu_tpu.metrics import (reset_run_plan_counts, run_plan_counts)

    # the untraced gate must measure the HETU_TRACE=0 path even when the
    # surrounding process (a test, an inherited env) enabled telemetry —
    # the bench_overhead wrapper restores both toggles on every exit;
    # the traced rounds below flip tracing explicitly
    obs.enable(False)
    ht_metrics.enable_step_timing(False)

    n = steps or (200 if smoke else 2000)
    rounds = 2 if smoke else 5
    # smoke pays 6 short pair rounds (not 3): the min-of-rounds gate
    # quantities (incl. the ISSUE 10 tracing-tax pairs) want more draws
    # on a noisy CI box, and a round is ~5ms
    pair_rounds = 6 if smoke else 12
    # the gate pairs use SHORT windows (~50ms): shared-host contention
    # arrives in bursts, and a short window has far better odds of
    # landing entirely inside a quiet slice
    pair_n = min(n, 600)

    def build():
        x = ht.placeholder_op("x", shape=(8, 8))
        w = ht.init.zeros(shape=(8, 8), name="w")
        loss = ht.reduce_mean_op(ht.ops.matmul_op(x, w), [0, 1])
        opt = ht.optim.SGDOptimizer(0.1)
        ex = ht.Executor({"train": [loss, opt.minimize(loss)]}, seed=0)
        return ex, x

    xv = np.ones((8, 8), np.float32)
    xd = jax.device_put(xv)

    def loop_us(fn, count=n):
        t0 = time.perf_counter()
        for i in range(count):
            fn(i)
        return (time.perf_counter() - t0) / count * 1e6

    def best(fn, count=n):
        return min(loop_us(fn, count) for _ in range(rounds))

    # raw jit floor (re-measured interleaved with the overhead rounds
    # below — this standalone min feeds the wall ratios)
    f = jax.jit(lambda a, b: (a @ b).mean())
    f(xd, xd).block_until_ready()
    raw = best(lambda i: f(xd, xd))

    # dispatch overhead, measured directly and FIRST (the wall
    # measurements below leave dead executors / lingering pool threads
    # behind — the gate pairs deserve the cleanest process state): a
    # fresh executor whose jit is wrapped BEFORE any plan binds it, so
    # total - in_jit is exactly the executor's per-step Python
    # (instrumentation cost calibrated out)
    reset_run_plan_counts()
    ex2, x2 = build()
    sub2 = ex2.subexecutors["train"]
    ex2.run("train", feed_dict={x2: xd})
    real_jit = sub2._jit
    sync_cpu = jax.default_backend() == "cpu"
    in_jit = [0.0]

    def timing_jit(*a):
        t0 = time.perf_counter()
        out = real_jit(*a)
        if not sync_cpu:    # async backends: compute must not leak into
            jax.block_until_ready(out)   # the Python sections
        in_jit[0] += time.perf_counter() - t0
        return out
    sub2._jit = timing_jit
    sub2._plan_cache = None     # plans must capture the wrapped jit
    fd2 = {x2: xd}

    def overhead_round(count):
        in_jit[0] = 0.0
        t0 = time.perf_counter()
        for i in range(count):
            ex2.run("train", feed_dict=fd2)
        return (time.perf_counter() - t0 - in_jit[0]) / count * 1e6
    # calibrate the instrumentation's own cost: the timing wrapper adds
    # a Python frame, *args packing of the 7 step inputs and two
    # perf_counter reads per call — measured around a no-op with the
    # SAME call shape, so subtracting it cannot eat real overhead
    def fake(*a):
        return None
    cal_in = [0.0]

    def cal_wrap(*a):
        t0 = time.perf_counter()
        fake(*a)
        cal_in[0] += time.perf_counter() - t0
        return None
    cal_args = (0, 1, 2, 3, 4, 5, 6)

    def cal(i):
        cal_wrap(*cal_args)
    wrap_cost = min(loop_us(cal, 20000) for _ in range(3))
    # the gate multiple takes the MINIMUM of each quantity over many
    # short interleaved rounds: shared-host contention only ever
    # INFLATES a round, so the min is the least-noise estimate of each
    # true value (standard microbenchmark practice).  Selecting a
    # minimum-RATIO pair instead would be floor-seeking (a noise-
    # inflated raw round makes any overhead look small); the raw pairs
    # are recorded in the artifact for transparency.
    overhead_round(pair_n)      # warm: plan + fast lane rebuilt
    pairs = []
    for _ in range(pair_rounds):
        r = loop_us(lambda i: f(xd, xd), pair_n)
        o = max(0.0, overhead_round(pair_n) - wrap_cost)
        pairs.append((r, o))
    raw_best = min(p[0] for p in pairs)
    overhead = min(p[1] for p in pairs)
    raw = min(raw, raw_best)
    multiple = (raw_best + overhead) / max(raw_best, 1e-9)

    # the tracing tax (ISSUE 10 acceptance): the SAME instrumented
    # executor and interleaved-min discipline, with the obs span tracer
    # toggled per round — a traced step pays the ring-buffer spans (step
    # span + plan-lookup + feeds/dispatch stamps) on every dispatch.
    # Gate: the added host Python must stay <= 25% of the UNTRACED
    # dispatch path (raw dispatch + untraced overhead).
    trace_pairs = []
    for _ in range(pair_rounds):
        u = max(0.0, overhead_round(pair_n) - wrap_cost)
        obs.enable(True)
        t = max(0.0, overhead_round(pair_n) - wrap_cost)
        obs.enable(False)
        trace_pairs.append((u, t))
    obs.clear_trace()
    untraced_best = min(p[0] for p in trace_pairs)
    traced_best = min(p[1] for p in trace_pairs)
    trace_overhead_us = max(0.0, traced_best - untraced_best)
    trace_overhead_pct = trace_overhead_us \
        / max(raw_best + untraced_best, 1e-9) * 100.0
    # really free the instrumented executor: sub2/real_jit still point
    # into it, and the compiled-step cache pins its builder — clear all
    # three so the wall measurements below run without the extra state
    from hetu_tpu.graph import step_cache
    gate_counters = run_plan_counts()
    del ex2, fd2, sub2, real_jit
    step_cache.clear()
    gc.collect()

    if gate_only:
        # tier-1 guard mode: the gate quantities only — no wall /
        # step-jit / parity measurements (those cost two more executor
        # builds and are covered in-process by the run-plan smoke test)
        res = {
            "metric": "executor_host_overhead_multiple",
            "value": round(multiple, 2),
            "unit": "x",
            "vs_baseline": round(2.0 / max(multiple, 1e-9), 3),
            "extra": {
                "gate_only": True,
                "backend": jax.default_backend(),
                "raw_jit_us": round(raw, 1),
                "dispatch_overhead_us": round(overhead, 1),
                "overhead_pair_raw_us": round(raw_best, 1),
                "overhead_pairs": [[round(r, 1), round(o, 1)]
                                   for r, o in pairs],
                "overhead_multiple_vs_raw_jit": round(multiple, 2),
                "traced_dispatch_overhead_us": round(traced_best, 1),
                "trace_overhead_us": round(trace_overhead_us, 1),
                "trace_overhead_pct": round(trace_overhead_pct, 1),
                "trace_gate_pct": 25.0,
                "trace_pairs": [[round(u, 1), round(t, 1)]
                                for u, t in trace_pairs],
                "plan_cache": {k: int(v)
                               for k, v in gate_counters.items()},
            },
        }
        if trace_overhead_pct > 25.0:
            res["error"] = (
                f"HETU_TRACE=1 span tracing costs "
                f"{trace_overhead_pct:.1f}% of the untraced dispatch "
                f"path (gate: 25%)")
        return res

    # the executor's own step program, dispatched bare (donated state
    # threaded back through the loop — the zero-overhead executor)
    ex, x = build()
    ex.run("train", feed_dict={x: xd})
    sub = ex.subexecutors["train"]
    feeds = {ex._k(x): xd}
    key, lrs = ex.master_key, sub._host_lrs(0)

    def bare_round(count):
        tp, sp = sub._pack_state()
        os_ = {k: ex.opt_states[op] for k, op in sub._opt_items}
        t0 = time.perf_counter()
        for i in range(count):
            outs, tp, upd, os_, _sd = sub._jit(tp, sp, os_, feeds, key,
                                               np.int32(i), lrs)
        dt = (time.perf_counter() - t0) / count * 1e6
        for n_, k_ in sub._writeback_pairs:
            ex.var_values[n_] = tp[k_]
        for k_, op in sub._opt_items:
            ex.opt_states[op] = os_[k_]
        return dt
    step_jit = min(bare_round(n) for _ in range(rounds))

    # executor wall: device-committed and numpy feeds
    fd_dev, fd_np = {x: xd}, {x: xv}
    reset_run_plan_counts()
    dev = best(lambda i: ex.run("train", feed_dict=fd_dev))
    counters_steady = run_plan_counts()
    npf = best(lambda i: ex.run("train", feed_dict=fd_np))

    # pipelined: numpy feeds placed ahead by the run_steps driver
    def pipelined_round(count):
        t0 = time.perf_counter()
        ex.run_steps(lambda i: {x: xv}, count, name="train", sync=False)
        return (time.perf_counter() - t0) / count * 1e6
    pipelined = min(pipelined_round(n) for _ in range(rounds))

    # -- CI gates: plan-cache reuse + async/sync bitwise parity ----------
    hits = counters_steady.get("plan_cache_hit", 0)
    plan_reuse_ok = hits >= n - 1

    def losses(sync, nsteps=12):
        exp, xp = build()
        out = []
        if sync:
            for i in range(nsteps):
                r = exp.run("train", feed_dict={xp: xv})
                out.append(np.asarray(r[0].jax(), np.float32))
        else:
            rs = exp.run_steps(lambda i: {xp: xv}, nsteps, name="train",
                               sync=False)
            out = [np.asarray(r[0].jax(), np.float32) for r in rs]
        final_w = {k: np.asarray(v) for k, v in
                   exp.return_tensor_values().items()}
        del exp
        gc.collect()
        return out, final_w
    s_loss, s_w = losses(sync=True)
    a_loss, a_w = losses(sync=False)
    async_bitwise = (
        [v.tobytes() for v in s_loss] == [v.tobytes() for v in a_loss]
        and set(s_w) == set(a_w)
        and all(s_w[k].tobytes() == a_w[k].tobytes() for k in s_w))

    workload = {"graph": "8x8 matmul + SGD", "steps_timed": n}
    artifact = {
        "metric": "executor_host_overhead",
        "unit": "us/step",
        "backend": jax.default_backend(),
        "raw_jit_us": round(raw, 1),
        "step_jit_us": round(step_jit, 1),
        "device_feed_us": round(dev, 1),
        "numpy_feed_us": round(npf, 1),
        "pipelined_feed_us": round(pipelined, 1),
        "dispatch_overhead_us": round(overhead, 1),
        "overhead_pair_raw_us": round(raw_best, 1),
        "overhead_pairs": [[round(r, 1), round(o, 1)] for r, o in pairs],
        "overhead_multiple_vs_raw_jit": round(multiple, 2),
        # ISSUE 10: per-step span-tracing tax (HETU_TRACE=1) against the
        # untraced dispatch path, min over interleaved toggled rounds
        "traced_dispatch_overhead_us": round(traced_best, 1),
        "trace_overhead_us": round(trace_overhead_us, 1),
        "trace_overhead_pct": round(trace_overhead_pct, 1),
        "trace_gate_pct": 25.0,
        "trace_pairs": [[round(u, 1), round(t, 1)]
                        for u, t in trace_pairs],
        "wall_multiple_vs_raw_jit": round(dev / max(raw, 1e-9), 1),
        "plan_cache": {k: int(v) for k, v in counters_steady.items()},
        "async_bitwise_equal": bool(async_bitwise),
        "schema_note": (
            "overhead_multiple_vs_raw_jit = (overhead_pair_raw_us + "
            "dispatch_overhead_us) / overhead_pair_raw_us: the "
            "executor's per-step host Python (loop wall minus in-jit "
            "time under synchronous dispatch) over a raw jit dispatch, "
            "each the MINIMUM over many short interleaved rounds "
            "(contention only inflates a round, so min is the least-"
            "noise estimate; the per-round pairs are recorded in "
            "overhead_pairs — a minimum-RATIO pick would be floor-"
            "seeking).  Pre-ISSUE-9 artifacts used "
            "device_feed_us / raw_jit_us, which folded the step "
            "program's own compute floor (step_jit_us) into "
            "'overhead'."),
        **_provenance(workload),
    }
    if write_artifact:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "artifacts", "host_overhead.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "w") as fh:
            json.dump(artifact, fh, indent=1, sort_keys=True)
        os.replace(path + ".tmp", path)
    res = {
        "metric": "executor_host_overhead_multiple",
        "value": round(multiple, 2),
        "unit": "x",
        # >1.0 = beats the <=2.0 host-tax acceptance gate
        "vs_baseline": round(2.0 / max(multiple, 1e-9), 3),
        "extra": {
            "baseline_def": "2.0 / overhead_multiple_vs_raw_jit — the "
                            "ISSUE 9 host-tax gate (>=1.0 passes)",
            **artifact,
        },
    }
    errors = []
    if not plan_reuse_ok:
        errors.append(f"plan cache missed on a steady schema: "
                      f"{counters_steady}")
    if not async_bitwise:
        errors.append("async (sync=False) stepping NOT bitwise-equal "
                      "to sync stepping")
    if trace_overhead_pct > 25.0:
        errors.append(
            f"HETU_TRACE=1 span tracing costs {trace_overhead_pct:.1f}% "
            f"of the untraced dispatch path (gate: 25%)")
    if errors:
        res["error"] = " | ".join(errors)
    return res


def bench_resnet18(batch_size=128, steps=20, warmup=3):
    import jax

    _, ex, fd = build_resnet18_graph(batch_size=batch_size)
    dt = _timed(lambda i: ex.run("train", feed_dict=fd), steps, warmup)
    base_ms, label = _torch_bench_baseline("resnet18",
                                           {"batch_size": batch_size})
    return {
        "metric": "resnet18_cifar10_step_time",
        "value": round(dt * 1e3, 2),
        "unit": "ms/step",
        # ms/step inverts the achieved/baseline ratio (>1 = faster)
        "vs_baseline": round(base_ms / (dt * 1e3), 3) if base_ms else 0.0,
        "extra": {"baseline_def": f"baseline step time / achieved "
                                  f"({label})" if base_ms else
                                  "unavailable: no committed same-workload "
                                  "torch baseline",
                  **_provenance({"batch_size": batch_size}),
                  "step_time_hist_ms": _step_percentiles(),
                  "compute_dtype": _compute_dtype() or "float32",
                  "backend": jax.default_backend()},
    }


def _rss_kb():
    """Current VmRSS in kB from /proc (0 where unavailable)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    return 0


class _RssWatch:
    """Sample VmRSS on a background thread; ``peak_delta_mb`` is the
    high-water mark above the RSS at entry — the bounded-save/load
    evidence (a full in-memory table copy would show up here)."""

    def __init__(self, interval_s=0.002):
        import threading
        self._iv = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self.base_kb = self.peak_kb = 0

    def _run(self):
        while not self._stop.wait(self._iv):
            self.peak_kb = max(self.peak_kb, _rss_kb())

    def __enter__(self):
        self.base_kb = self.peak_kb = _rss_kb()
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5)
        self.peak_kb = max(self.peak_kb, _rss_kb())
        return False

    @property
    def peak_delta_mb(self):
        return round(max(0, self.peak_kb - self.base_kb) / 1024.0, 1)


def bench_emb(smoke=False, steps=None, seed=0):
    """ISSUE 3 scale proof: the vectorized HET cache + batched sparse RPC
    path under a zipf(1.05) id stream over a 10^7x64 embedding table
    (``--smoke``: 10^5 rows, seconds on CPU — the CI trajectory config).

    Measures (1) lookup+update rows/s through the vectorized
    ``DistCacheTable`` vs the per-key reference model
    (``refcache.PerKeyCacheTable`` — the pre-PR cost shape) on the SAME
    trace prefix, (2) steady-state throughput + HET hit rate over the full
    stream, (3) redundant rows/bytes eliminated by ``np.unique`` dedup
    before the shard fanout on the raw
    (uncached) pull/push path, and (4) peak RSS above baseline during
    save/load of the full table — bounded far below one table copy.
    Host-side metric: everything runs on the host whatever the
    accelerator is."""
    import tempfile
    import shutil

    from hetu_tpu import metrics as hmetrics
    from hetu_tpu.ps.dist_store import DistributedStore, DistCacheTable
    from hetu_tpu.ps.refcache import PerKeyCacheTable

    if smoke:
        rows, width, batch, limit = 100_000, 64, 8192, 20_000
        n_steps = steps or 6
        warm_steps, base_steps, direct_steps = 2, 2, 2
    else:
        rows, width, batch, limit = 10_000_000, 64, 2048 * 26, 1_000_000
        n_steps = steps or 40
        warm_steps, base_steps, direct_steps = 4, 7, 3
    # bounds are in USE counts (HET contract) and the zipf head key shows
    # up thousands of times per batch, so they scale with the batch: the
    # head key stays fresh for a few batches (pull staleness) and syncs
    # its accumulated grad about every ~10 batches (push staleness)
    pull_bound, push_bound, lr = max(10, batch // 2), max(4, batch), 0.05
    # the warm phase always runs (cold misses + lazy imports must not
    # pollute the steady-state number), so a tiny --steps is bumped to
    # leave at least one timed step rather than going negative
    n_steps = max(n_steps, warm_steps + 1)
    base_steps = min(base_steps, n_steps - warm_steps)
    hmetrics.reset_cache_counts()

    # zipf(1.05) over a permuted id space (head ids scattered like a real
    # hash-bucketed vocab, not contiguous)
    rng = np.random.RandomState(seed)
    p = 1.0 / np.arange(1, rows + 1, dtype=np.float64) ** 1.05
    cdf = np.cumsum(p)
    cdf /= cdf[-1]
    perm = rng.permutation(rows).astype(np.int64)

    def draw(n):
        return perm[np.searchsorted(cdf, rng.random_sample(n))]

    def run_cache(cache, trace):
        """(lookup_s, update_s) replaying lookup+update over the trace.
        Wall-clock totals: GC pauses stay attributed to the side whose
        allocations caused them (the per-key model's per-row array churn
        is a real cost of that design), with a collect() up front so one
        side never pays the other's garbage."""
        import gc
        gc.collect()
        grng = np.random.RandomState(seed + 1)
        t_lk = t_up = 0.0
        for ids in trace:
            g = grng.standard_normal((ids.size, width)).astype(np.float32) \
                * 0.01
            t0 = time.perf_counter()
            cache.lookup(ids)
            t1 = time.perf_counter()
            cache.update(ids, g)
            t_lk += t1 - t0
            t_up += time.perf_counter() - t1
        return t_lk, t_up

    t0 = time.perf_counter()
    store = DistributedStore(0, 1)
    tid = store.init_table(rows, width, opt="sgd", lr=lr, init_scale=0.01)
    init_s = time.perf_counter() - t0
    ref_store = DistributedStore(0, 1)
    ref_tid = ref_store.init_table(rows, width, opt="sgd", lr=lr,
                                   init_scale=0.01)
    try:
        warm = [draw(batch) for _ in range(warm_steps)]
        prefix = [draw(batch) for _ in range(base_steps)]

        # pre-PR per-key baseline: same zipf trace, warmed cache (a cold
        # ratio only measures the shared store-pull cost of the misses)
        ref = PerKeyCacheTable(ref_store, ref_tid, limit=limit,
                               pull_bound=pull_bound,
                               push_bound=push_bound)
        run_cache(ref, warm)
        ref_s = sum(run_cache(ref, prefix))
        ref_rows_s = base_steps * batch * 2 / ref_s

        # vectorized cache: same warm + prefix (for the like-for-like
        # ratio), then the rest of the stream for steady-state throughput
        cache = DistCacheTable(store, tid, limit=limit,
                               pull_bound=pull_bound,
                               push_bound=push_bound)
        run_cache(cache, warm)      # warm-up: cold misses + lazy imports
        pre_lk, pre_up = run_cache(cache, prefix)
        vec_prefix_rows_s = base_steps * batch * 2 / (pre_lk + pre_up)
        rest = [draw(batch) for _ in
                range(max(0, n_steps - base_steps - warm_steps))]
        lk_s, up_s = run_cache(cache, rest)
        lk_s += pre_lk
        up_s += pre_up
        t0 = time.perf_counter()
        cache.flush()
        up_s += time.perf_counter() - t0
        total_rows = (n_steps - warm_steps) * batch
        vec_rows_s = total_rows * 2 / (lk_s + up_s)
        perf = cache.perf()

        # raw (uncached) pull/push on dup-heavy zipf batches: the wire-
        # dedup path
        hmetrics.reset_cache_counts()
        t0 = time.perf_counter()
        grng = np.random.RandomState(seed + 2)
        for _ in range(direct_steps):
            ids = draw(batch)
            store.pull(tid, ids)
            store.push(tid, ids,
                       grng.standard_normal((batch, width)).astype(
                           np.float32) * 0.01, lr)
        direct_s = time.perf_counter() - t0
        direct_rows_s = direct_steps * batch * 2 / direct_s
        dedup = hmetrics.cache_counts()
        wire_rows = 2 * direct_steps * batch
        saved_rows = (dedup.get("ps_dedup_pull_rows_saved", 0)
                      + dedup.get("ps_dedup_push_rows_saved", 0))

        # bounded-RSS streamed save/load of the full table
        tmp = tempfile.mkdtemp(prefix="hetu_emb_bench_")
        path = os.path.join(tmp, "table.bin")
        try:
            with _RssWatch() as w_save:
                t0 = time.perf_counter()
                store.save(tid, path)
                save_s = time.perf_counter() - t0
            with _RssWatch() as w_load:
                t0 = time.perf_counter()
                store.load(tid, path)
                load_s = time.perf_counter() - t0
            ckpt_mb = round(os.path.getsize(f"{path}.shard0") / 2**20, 1)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    finally:
        store.close()
        ref_store.close()

    table_mb = round(rows * width * 4 / 2**20, 1)
    speedup = vec_prefix_rows_s / ref_rows_s if ref_rows_s else 0.0
    return {
        "metric": "emb_cache_rows_per_sec",
        "value": round(vec_rows_s, 1),
        "unit": "rows/s",
        # >=10x is the acceptance bar: vectorized vs per-key on the SAME
        # cold zipf trace prefix, same table, same bounds
        "vs_baseline": round(speedup, 2),
        "extra": {
            "baseline_def": "vectorized lookup+update rows/s ÷ per-key "
                            "reference (pre-PR DistCacheTable cost shape) "
                            "on the same warm zipf trace prefix",
            **_provenance({"rows": rows, "width": width, "batch": batch,
                           "steps": n_steps, "limit": limit,
                           "zipf_a": 1.05, "pull_bound": pull_bound,
                           "push_bound": push_bound, "smoke": bool(smoke)}),
            "init_s": round(init_s, 2),
            "lookup_rows_per_s": round(total_rows / lk_s, 1),
            "update_rows_per_s": round(total_rows / up_s, 1),
            "vec_prefix_rows_per_s": round(vec_prefix_rows_s, 1),
            "ref_rows_per_s": round(ref_rows_s, 1),
            "hit_rate": round(perf["hit_rate"], 4),
            "cache_stats": {k: int(v) for k, v in perf.items()
                            if k != "hit_rate"},
            "per_key_push_rpcs_ref": ref.stats["push_rpcs"],
            "batched_push_rpcs_vec": perf["push_rpcs"],
            "direct_rows_per_s": round(direct_rows_s, 1),
            "dedup": {
                "pull_rows_saved": int(dedup.get(
                    "ps_dedup_pull_rows_saved", 0)),
                "push_rows_saved": int(dedup.get(
                    "ps_dedup_push_rows_saved", 0)),
                "bytes_saved": int(
                    dedup.get("ps_dedup_pull_bytes_saved", 0)
                    + dedup.get("ps_dedup_push_bytes_saved", 0)),
                "rows_saved_frac": round(saved_rows / wire_rows, 4),
            },
            "table_mb": table_mb,
            "checkpoint_mb": ckpt_mb,
            "save": {"seconds": round(save_s, 2),
                     "peak_rss_delta_mb": w_save.peak_delta_mb},
            "load": {"seconds": round(load_s, 2),
                     "peak_rss_delta_mb": w_load.peak_delta_mb},
            "backend": "host",
        },
    }


def _child_main(args):
    cpu_fallback = bool(os.environ.get("_HETU_BENCH_FORCE_CPU"))

    if args.config == "chaos":
        # host-side fault-injection smoke: the dist-store transport and
        # the recovery loop run on the host either way, so CPU is the
        # intended backend here — no fallback annotation
        print(json.dumps(bench_chaos(steps=args.steps or 8)))
        return
    if args.config == "failover":
        # host-side replication smoke: double-kill a replicated PS shard,
        # prove zero-restart bitwise-equal recovery (ISSUE 4 acceptance)
        print(json.dumps(bench_failover(steps=args.steps or 10,
                                        smoke=args.smoke)))
        return
    if args.config == "emb":
        # host-side sparse-path scale bench: numpy cache + native store,
        # no accelerator in the measured path
        print(json.dumps(bench_emb(smoke=args.smoke, steps=args.steps)))
        return
    if args.config == "zero":
        # CPU host-device mesh (the parent's child env forces >=8
        # devices): the memory/parity acceptance run of ISSUE 6
        print(json.dumps(bench_zero(
            dp=args.dp, steps=args.steps or 12,
            batch_size=args.batch_size or 8,
            seq_len=args.seq_len or 128)))
        return
    if args.config == "serve":
        # host-side serving acceptance: router + batcher + PS transport
        # run on the host; the jitted forward is tiny (ISSUE 7)
        print(json.dumps(bench_serve(smoke=args.smoke,
                                     n_requests=args.steps)))
        return
    if args.config == "decode":
        # host-side decode-serving acceptance: continuous batching vs
        # request-level scheduling over the same jitted step (ISSUE 16)
        print(json.dumps(bench_decode(smoke=args.smoke,
                                      n_requests=args.steps)))
        return
    if args.config == "fleet":
        # host-side fleet-tier acceptance: replica-set admission,
        # SLO autoscaling and chaos replica-kill rescue (ISSUE 17)
        print(json.dumps(bench_fleet(smoke=args.smoke,
                                     n_requests=args.steps)))
        return
    if args.config == "partition":
        # host-side partition-tolerance acceptance: chaos partition DSL,
        # fencing epochs, 2-cell geo-replicated serving (ISSUE 8)
        print(json.dumps(bench_partition(steps=args.steps or 10,
                                         smoke=args.smoke)))
        return
    if args.config == "overhead":
        # host-side dispatch-gap microbench: the XLA program is ~free by
        # construction, so any backend measures the same host tax
        print(json.dumps(bench_overhead(smoke=args.smoke,
                                        steps=args.steps)))
        return
    if args.config == "trace":
        # host-side telemetry demo: chaos failover + serving + feed
        # pipeline captured in one Chrome trace (ISSUE 10)
        print(json.dumps(bench_trace(steps=args.steps or 5,
                                     smoke=args.smoke,
                                     write_artifact=True)))
        return
    if args.config == "elastic":
        # CPU host-device mesh (the parent's child env forces >=8
        # devices): the elastic resize acceptance run of ISSUE 12 —
        # chaos step-clock kill, shrink to dp-1, rejoin, grow back
        print(json.dumps(bench_elastic(steps=args.steps or 10,
                                       dp=args.dp, smoke=args.smoke)))
        return
    if args.config == "remat":
        # CPU host-device mesh (>=8 devices so the dp=4 zero=3 overlap
        # audit gates inside the same child): the ISSUE 13 policy sweep
        # with partial-runway checkpointed cells
        print(json.dumps(bench_remat(steps=args.steps or 8)))
        return

    def _steps(cpu_cap):
        # explicit --steps is honored verbatim (comparison harnesses need
        # BOTH frameworks on the same workload); only the implicit default
        # shrinks on the CPU fallback
        if args.steps is not None:
            return args.steps
        return cpu_cap if cpu_fallback else DEFAULT_STEPS

    if args.config == "bert":
        # the CPU fallback shrinks the workload (seq 128, bs 4) — the
        # artifact is marked with an error field either way
        sl = args.seq_len or (128 if cpu_fallback else 512)
        # resolve the default ONCE (bench_bert applies the same rule when
        # handed None; passing it explicitly keeps the OOM provenance and
        # the retry size from drifting against bench_bert's constants)
        attempted = args.batch_size or (4 if cpu_fallback
                                        else (64 if sl >= 512 else 192))
        oom = False
        try:
            res = bench_bert(batch_size=attempted, seq_len=sl,
                             steps=_steps(1),
                             warmup=1 if cpu_fallback else 3,
                             remat=args.remat)
        except Exception as e:
            # the seq-512 flagship config is sized for a 16G v5e; if the
            # tunnel fronts a smaller chip, halve the batch once rather
            # than waste the healthy window (the artifact records it).
            # NB: retry OUTSIDE the except block — e.__traceback__ pins the
            # failed attempt's frames (and their HBM buffers) until exit
            if "RESOURCE_EXHAUSTED" not in str(e) or args.batch_size:
                raise
            oom = True
        if oom:
            res = bench_bert(batch_size=attempted // 2, seq_len=sl,
                             steps=_steps(1),
                             warmup=1 if cpu_fallback else 3,
                             remat=args.remat)
            res.setdefault("extra", {})["oom_fallback"] = \
                f"bs {attempted} OOM; measured at bs {attempted // 2}"
    elif args.config == "wdl":
        bs = args.batch_size or (256 if cpu_fallback else 2048)
        # --emb-policy routes the CTR embedding through the NEW vectorized
        # cache path (direct PS store / vectorized LRU / vectorized LFU);
        # --wdl-embed keeps selecting the native C++ cache or dense
        policy = args.wdl_embed
        if args.emb_policy:
            policy = {"direct": "ps", "lru": "vlru",
                      "lfu": "vlfu"}[args.emb_policy]
        res = bench_wdl(batch_size=bs, steps=_steps(3),
                        warmup=1 if cpu_fallback else 3,
                        policy=policy,
                        emb_device=args.emb_device or "host")
    elif args.config == "moe":
        bs = args.batch_size or (1024 if cpu_fallback else 8192)
        res = bench_moe(batch_tokens=bs, steps=_steps(3),
                        warmup=1 if cpu_fallback else 3)
    elif args.config == "attn":
        res = bench_attention(steps=_steps(3),
                              warmup=1 if cpu_fallback else 2,
                              cpu_fallback=cpu_fallback)
    else:
        bs = args.batch_size or (16 if cpu_fallback else 128)
        res = bench_resnet18(batch_size=bs, steps=_steps(2),
                             warmup=1 if cpu_fallback else 3)
    if cpu_fallback:
        # an honest artifact: the number exists but is NOT the TPU metric
        import jax
        res["error"] = (f"TPU backend unavailable; measured on the "
                        f"{jax.default_backend()} backend at reduced size")
    print(json.dumps(res))


def _error_result(args, msg):
    names = {"bert": ("bert_base_pretrain_samples_per_sec_per_chip",
                      "samples/s/chip"),
             "resnet18": ("resnet18_cifar10_step_time", "ms/step"),
             "wdl": ("wdl_criteo_cache_samples_per_sec", "samples/s"),
             "moe": ("moe_ep_tokens_per_sec", "tokens/s"),
             "attn": ("attn_flash_sweep_tokens_per_sec", "tokens/s"),
             "chaos": ("chaos_recovery_ms", "ms"),
             "failover": ("failover_recovery_ms", "ms"),
             "partition": ("partition_recovery_ms", "ms"),
             "emb": ("emb_cache_rows_per_sec", "rows/s"),
             "serve": ("serve_qps", "requests/s"),
             "decode": ("decode_tokens_per_s", "tokens/s"),
             "fleet": ("fleet_spike_interactive_p99_ms", "ms"),
             "zero": ("zero_opt_state_shrink_vs_replicated", "x"),
             "overhead": ("executor_host_overhead_multiple", "x"),
             "trace": ("trace_step_events", "events"),
             "remat": ("remat_full_peak_reduction_vs_off", "fraction"),
             "elastic": ("elastic_resize_recovery_ms", "ms")}
    metric, unit = names[args.config]
    return {"metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "error": msg[-2000:]}


def _parse_child_json(stdout, attempt):
    """Last valid {"metric": ...} JSON line from a child's stdout, or None."""
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in parsed:
                if attempt > 0:
                    parsed.setdefault("extra", {})["attempt"] = attempt
                return parsed
    return None


# ---- /proc contention scan (shared with tools/tpu_watch.py) -------------

def _iter_procs():
    import glob
    for p in glob.glob("/proc/[0-9]*/cmdline"):
        try:
            pid = int(p.split("/")[2])
            with open(p, "rb") as f:
                argv = f.read().split(b"\0")
        except (OSError, ValueError):
            continue
        yield pid, argv


def _is_pytest_argv(argv):
    """A real pytest process.  Exact-element matching — a substring grep
    would false-positive on any command line that merely MENTIONS pytest
    (e.g. an agent driver carrying instructions)."""
    if b"pytest" in argv:                           # python -m pytest ...
        return True
    return any(a.endswith(b"/pytest") or a == b"pytest"
               for a in argv[:2])                   # direct pytest binary


def _is_bench_argv(argv):
    """A bench.py EXECUTION ('python [-u] bench.py ...').  Editors/pagers
    holding the file open are not executions."""
    interp = argv[0].rsplit(b"/", 1)[-1] if argv and argv[0] else b""
    return interp.startswith(b"python") and any(
        a == b"bench.py" or a.endswith(b"/bench.py") for a in argv[1:4])


def _pytest_live():
    return any(_is_pytest_argv(argv) for _, argv in _iter_procs())


def _foreign_bench_running():
    """A bench.py MEASUREMENT owned by another process tree holds the
    chip.  Only child-flagged processes count — a foreign bench PARENT may
    itself be idle/deferring, and matching it would mutually deadlock two
    concurrent invocations.  Deferring is a handoff, not a loss: the other
    measurement persists its result to the TPU cache we can serve."""
    me = os.getpid()
    for pid, argv in _iter_procs():
        if pid == me or not _is_bench_argv(argv):
            continue
        try:
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().split(")")[-1].split()[1])
            if ppid == me:
                continue    # our own measurement child
            with open(f"/proc/{pid}/environ", "rb") as f:
                env = f.read()
        except (OSError, ValueError, IndexError):
            continue
        if CHILD_ENV_FLAG.encode() + b"=1" in env:
            return True
    return False


PROBE_LOG_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "artifacts", "tpu_probe_log.jsonl")
# the wedged tunnel recovers on a minutes scale: a bounded number of
# probe attempts with decorrelated-jitter backoff replaces both the old
# fixed 15s cadence (fleet-synchronized hammering) and the unbounded
# "probe until the budget drains" loop (a clean diagnostic beats a
# near-timeout wedge)
MAX_PROBE_ATTEMPTS = int(os.environ.get("HETU_BENCH_PROBE_ATTEMPTS", "8"))
PROBE_BACKOFF_BASE_S = 5.0
PROBE_BACKOFF_CAP_S = 60.0


def _next_probe_backoff(prev, rng, base=PROBE_BACKOFF_BASE_S,
                        cap=PROBE_BACKOFF_CAP_S):
    """Decorrelated-jitter probe retry delay (the ``dist_store.
    _next_backoff`` formula: ``min(cap, uniform(base, 3*prev))``) — no
    two bench invocations hammer a recovering tunnel on the same
    schedule.  Split out so the schedule is unit-testable."""
    return min(cap, rng.uniform(base, 3.0 * max(base, prev)))


#: rotation bound for the committed probe log; tools/tpu_watch.py
#: delegates its writes here, so this is the ONE append-and-rotate
#: discipline for artifacts/tpu_probe_log.jsonl
PROBE_LOG_CAP = 2000


def _append_probe_log(entry, path=PROBE_LOG_PATH):
    """One JSONL line per probe attempt — the same log
    ``tools/tpu_watch.py`` writes, so the committed
    ``artifacts/tpu_probe_log.jsonl`` is the single wedge history
    BENCH rounds are judged on.  Rotated at PROBE_LOG_CAP lines
    (oldest dropped, header note kept — the watcher's discipline: a
    wedged quarter cannot bloat the repo).  Best-effort: a read-only
    checkout must not fail the measurement."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        entry = {"at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                 **entry}
        with open(path, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        return
    try:
        with open(path) as f:
            lines = f.readlines()
        if len(lines) > PROBE_LOG_CAP + 200:
            head = lines[:1] if lines and "note" in lines[0] else []
            kept = head + [json.dumps(
                {"note": f"rotated: {len(lines) - len(head) - PROBE_LOG_CAP}"
                         f" older probes dropped"}) + "\n"] \
                + lines[-PROBE_LOG_CAP:]
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.writelines(kept)
            os.replace(tmp, path)
    except OSError:
        pass


def _probe_backend(timeout_s):
    """(ok, err) — ok iff jax backend init answers within timeout_s AND the
    default backend is an accelerator AND a tiny computation actually
    executes on it (a disposable child, so a hang inside jax cannot wedge
    the parent).  The compute check matters: the axon tunnel has been
    observed half-wedged — ``jax.devices()`` answers (control plane) while
    any dispatched program hangs forever (data plane) — and a
    metadata-only probe would green-light a window in which every bench
    child burns its full timeout.  ``err`` carries the real cause
    (timeout vs init failure vs silent-CPU) for the final JSON artifact."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; d = jax.devices(); "
             "v = jnp.arange(8.0).sum().block_until_ready(); "
             "print('LIVE', jax.default_backend(), d[0].device_kind, "
             "float(v))"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s:.0f}s (tunnel wedged)"
    if proc.returncode != 0:
        return False, f"probe rc={proc.returncode}: {proc.stderr[-400:]}"
    fields = proc.stdout.split()
    if "LIVE" not in fields:
        return False, f"probe produced no LIVE line: {proc.stdout[-200:]}"
    platform = fields[fields.index("LIVE") + 1] if \
        len(fields) > fields.index("LIVE") + 1 else "?"
    # JAX_PLATFORMS is normally pinned to the TPU tunnel by sitecustomize;
    # if that pin is absent a healthy-looking probe may be a silent CPU
    # fallback — each full-size attempt would then burn the whole child
    # timeout on CPU, so refuse it here
    if platform == "cpu" and not os.environ.get("_HETU_BENCH_ALLOW_CPU"):
        return False, f"probe found only the cpu backend ({proc.stdout!r})"
    return True, None


# a measurement child needs compile + warmup + timed steps; spawning one
# with less runway than this guarantees a wasted attempt
MIN_MEASURE_S = int(os.environ.get("HETU_BENCH_MIN_MEASURE", "120"))
# identical deterministic child failures (rc!=0, e.g. an OOM or a model
# bug) are not worth retrying across the whole budget window
MAX_RC_FAILURES = 3

TPU_CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_TPU_LATEST.json")

# the default workload per config — a cached artifact measured under OLD
# defaults (e.g. the pre-round-4 seq-128 dense bert) must not be relabeled
# as the current flagship workload's result
DEFAULT_WORKLOAD = {
    "bert": {"batch_size": 64, "seq_len": 512},
    "resnet18": {"batch_size": 128},
    "wdl": {"batch_size": 2048, "embed": "lru"},
    "moe": {"tokens": 8192},
    "attn": {"batch_size": 4, "heads": 8, "head_dim": 64,
             "seq_aligned": 512, "seq_ragged": 420},
}


def _cached_tpu_result(config):
    """Last known-good on-TPU measurement for ``config`` persisted by
    tools/tpu_watch.py while the tunnel was healthy (it wedges for hours at
    a time — a dated real-TPU artifact beats a live CPU fallback)."""
    try:
        with open(TPU_CACHE_PATH) as f:
            cache = json.load(f)
        res = cache["configs"][config]
    except (OSError, KeyError, json.JSONDecodeError):
        return None
    if res.get("extra", {}).get("backend") != "tpu" or "error" in res:
        return None
    extra = res.get("extra", {})
    # the provenance block is canonical; pre-schema caches carried the
    # workload knobs as loose extra keys
    measured = extra.get("workload", extra)
    if any(measured.get(k) != v
           for k, v in DEFAULT_WORKLOAD.get(config, {}).items()):
        return None    # measured at a different workload — not this metric
    return res


def _parent_main(args):
    """Run the bench in a child process under a hard time budget.

    Probe-first: a wedged tunnel is detected in ~PROBE_TIMEOUT_S, not by
    burning a CHILD_TIMEOUT_S measurement attempt; the probe retries across
    the budget window (the tunnel recovers on a minutes scale) with
    CPU_RESERVE_S always kept for the fallback path (cached TPU artifact if
    one exists, else a reduced-size CPU measurement)."""
    deadline = time.monotonic() + TOTAL_BUDGET_S
    last_err = "no attempts made"
    attempt = 0
    rc_failures = 0
    probe_failures = 0
    backoff = PROBE_BACKOFF_BASE_S
    rng = random.Random()       # jitter wants entropy, not repeatability
    while True:
        remaining = deadline - time.monotonic()
        if remaining - CPU_RESERVE_S <= MIN_MEASURE_S:
            # too little runway for compile+warmup+steps: probing further
            # only delays the fallback artifact
            last_err += " | stopped (insufficient runway for a measurement)"
            break
        if probe_failures >= MAX_PROBE_ATTEMPTS:
            # bounded attempt budget: a tunnel that failed this many
            # probes is wedged for longer than this invocation can wait —
            # hand a clean diagnostic to the fallback path instead of
            # burning the rest of the window on more probes
            last_err = (f"tunnel wedged: {probe_failures} probe attempts "
                        f"failed with decorrelated-jitter backoff (last: "
                        f"{last_err}); see artifacts/tpu_probe_log.jsonl")
            break
        if _foreign_bench_running() or _pytest_live():
            # another measurement (the watcher's) or a test run owns the
            # chip; contended children blow their compile budget (the
            # bench-contention pitfall) — wait it out
            last_err = f"attempt {attempt}: deferred to a concurrent " \
                       f"bench measurement or pytest run"
            attempt += 1
            time.sleep(20)
            continue
        ok, probe_err = _probe_backend(min(PROBE_TIMEOUT_S,
                                           remaining - CPU_RESERVE_S))
        _append_probe_log({"ok": bool(ok), "err": probe_err,
                           "source": "bench", "attempt": attempt,
                           "config": args.config})
        if not ok:
            last_err = f"attempt {attempt}: {probe_err}"
            attempt += 1
            probe_failures += 1
            # decorrelated jitter: spread recovering-tunnel retries out
            # instead of the old lockstep 15s cadence
            backoff = _next_probe_backoff(backoff, rng)
            time.sleep(min(backoff,
                           max(0.0, deadline - time.monotonic())))
            continue
        probe_failures = 0
        backoff = PROBE_BACKOFF_BASE_S
        remaining = deadline - time.monotonic()
        if remaining - CPU_RESERVE_S <= MIN_MEASURE_S:
            continue    # probe ate the runway; top-of-loop break explains
        env = dict(os.environ, **{CHILD_ENV_FLAG: "1"})
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                env=env, capture_output=True, text=True,
                timeout=min(CHILD_TIMEOUT_S, remaining - CPU_RESERVE_S))
        except subprocess.TimeoutExpired:
            last_err = f"attempt {attempt}: child exceeded wall clock " \
                       f"(backend wedged mid-run)"
            attempt += 1
            continue
        parsed = _parse_child_json(proc.stdout, attempt)
        if parsed is not None:
            print(json.dumps(parsed))
            return
        last_err = f"attempt {attempt}: rc={proc.returncode} " \
                   f"stderr: {proc.stderr[-1500:]}"
        attempt += 1
        rc_failures += 1
        if rc_failures >= MAX_RC_FAILURES:
            last_err += f" | giving up after {rc_failures} child failures"
            break
        time.sleep(min(10.0, max(0.0, deadline - time.monotonic()) / 10))
    # fallback 1: a persisted on-TPU artifact from tools/tpu_watch.py —
    # the real metric, measured earlier in the round while the tunnel was up
    # the watcher cache is measured at each config's DEFAULT workload size;
    # serving it for an overridden --batch-size/--steps would mislabel a
    # different workload as this invocation's result
    cached = _cached_tpu_result(args.config) \
        if args.batch_size is None and args.seq_len is None \
        and args.steps in (None, DEFAULT_STEPS) \
        and getattr(args, "wdl_embed", "lru") == "lru" \
        and getattr(args, "emb_policy", None) is None \
        and getattr(args, "remat", None) is None \
        and getattr(args, "emb_device", None) in (None, "host") else None
    if cached is not None:
        # top-level marker: a real on-TPU number, but NOT measured by this
        # invocation — consumers must not read it as a live success
        cached["stale"] = True
        cached.setdefault("extra", {})["cached"] = True
        cached["extra"]["live_attempt_err"] = last_err[-500:]
        print(json.dumps(cached))
        return
    # fallback 2: reduced-size CPU measurement (forced via jax.config in
    # the child; env alone is pinned by the site customization), marked
    # with an error field — an honest artifact beats no artifact
    remaining = deadline - time.monotonic()
    if remaining > 30:
        env = dict(os.environ, **{CHILD_ENV_FLAG: "1",
                                  "_HETU_BENCH_FORCE_CPU": "1"})
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                env=env, capture_output=True, text=True,
                timeout=remaining - 10)
            parsed = _parse_child_json(proc.stdout, attempt)
            if parsed is not None:
                parsed.setdefault("error", "TPU backend unavailable")
                parsed["error"] += f" | last TPU {last_err}"
                # attach the committed HLO-audit projection so even a
                # CPU-fallback artifact states what THIS program projects
                # to on a v5e (compute-leg floor + north-star step time;
                # artifacts/hlo_audit_cpu.json carries the full audit)
                try:
                    audit_path = os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "artifacts", "hlo_audit_cpu.json")
                    with open(audit_path) as f:
                        proj = json.load(f)["configs"][args.config][
                            "detail"]["v5e_projection"]
                    parsed.setdefault("extra", {})["v5e_projection"] = proj
                except (OSError, KeyError, json.JSONDecodeError):
                    pass
                print(json.dumps(parsed))
                return
            last_err += f" | cpu fallback rc={proc.returncode} " \
                        f"stderr: {proc.stderr[-500:]}"
        except subprocess.TimeoutExpired:
            last_err += " | cpu fallback exceeded wall clock"
    print(json.dumps(_error_result(args, last_err)))


def bench_wdl(batch_size=2048, steps=20, warmup=3, policy="lru",
              emb_device="host"):
    """BASELINE config 4: Wide&Deep CTR with the HET embedding cache —
    rows pulled through the bounded-staleness cache around each jitted
    step (reference run_hetu.py:121-126 cache flags).

    ``emb_device="device"`` (ISSUE 11) routes the embedding through the
    DEVICE-RESIDENT cache slab (``--emb-device device`` requires a
    vectorized-cache policy: vlru/vlfu): hit rows are gathered on-device
    by slot index, only miss rows cross the host boundary (overlapped
    with the forward on the feed-pipeline thread), and the grad
    segment-sum runs on device.  The artifact then ALSO measures the
    host-mode cache on the SAME warm zipf trace and records
    ``vs_host_cache`` — the acceptance comparison — plus the
    ``emb_pallas_fallback_reason`` counters (empty = the Pallas kernels
    were the measured path; ``{gather,scatter_add}:backend_cpu`` = an
    off-TPU run measured the counted ``jnp.take``/``segment_sum``
    fallbacks)."""
    import jax
    from hetu_tpu import metrics as hmetrics

    if emb_device not in ("host", "device"):
        raise ValueError(f"emb_device must be host|device, got "
                         f"{emb_device!r}")
    if emb_device == "device":
        if policy not in ("vlru", "vlfu"):
            # the device slab belongs to DistCacheTable; map the native
            # cache names onto their vectorized twins
            policy = {"lru": "vlru", "lfu": "vlfu"}.get(policy)
            if policy is None:
                raise ValueError(
                    "--emb-device device needs a DistCacheTable policy "
                    "(--emb-policy lru|lfu)")
        policy = policy + "_dev"

    ctr = _load_example_models("ctr")
    # Zipf-skewed ids: the HET cache's hit pattern (and therefore the
    # measured step time) is only meaningful under Criteo-like skew
    d_all, s_all, y_all = ctr.synthetic_criteo_skewed(8 * batch_size,
                                                      vocab=100000)
    batches = [(d_all[i * batch_size:(i + 1) * batch_size],
                s_all[i * batch_size:(i + 1) * batch_size],
                y_all[i * batch_size:(i + 1) * batch_size])
               for i in range(8)]

    def _measure(pol):
        _, ex, _fd0, (dense, sparse, y_) = build_wdl_graph(
            batch_size=batch_size, policy=pol)

        def run_step(i):
            dv, sv, yv = batches[i % len(batches)]
            return ex.run("train",
                          feed_dict={dense: dv, sparse: sv, y_: yv})

        dt = _timed(run_step, steps, warmup)   # resets step times itself
        hist = _step_percentiles()
        perf = {}
        for node in ex.subexecutors["train"].ps_nodes:
            c = getattr(node, "cache", None)
            if c is not None and hasattr(c, "perf"):
                perf = c.perf() or {}
            if c is not None and hasattr(c, "flush"):
                # flush BEFORE the executor is dropped: a pending-grad
                # flush deferred to GC-time __del__ runs with the store
                # graph half-collected (pre-existing teardown hazard)
                c.flush()
        return dt, perf, hist

    hmetrics.reset_emb_pallas_fallbacks()
    dt, cache_perf, step_hist = _measure(policy)
    fallbacks = dict(hmetrics.emb_pallas_fallback_counts())
    host_dt = host_hist = None
    h2d_rows = None
    if emb_device == "device" and cache_perf.get("lookups"):
        # the backend-independent evidence: rows crossing the host
        # boundary per step.  Device mode H2D-transfers only the PULLED
        # (miss/refresh) rows; host mode materializes + transfers every
        # looked-up occurrence, every step
        n_steps = steps + warmup
        h2d_rows = {
            "device_miss_rows_per_step":
                round(cache_perf["fetches"] / n_steps, 1),
            "host_all_rows_per_step":
                round(cache_perf["lookups"] / n_steps, 1)}
    if emb_device == "device":
        # the acceptance twin: the HOST-mode cache on the same trace
        host_dt, _, host_hist = _measure(policy[:-4])
    base, label = _torch_bench_baseline("wdl", {"batch_size": batch_size})
    # NB: the torch baseline is a PLAIN device embedding — it implements
    # no bounded-staleness cache.  vs_baseline is only a same-semantics
    # number when policy="dense" (plain vs plain); the cache policies are
    # the richer-functionality headline (BASELINE config 4) and measure
    # the cache machinery's cost on ONE process, where it cannot pay off
    same_semantics = policy == "dense"
    return {
        # the metric NAME carries the mode: a plain-embedding run is not
        # the cache metric and must not key-collide with it downstream
        "metric": "wdl_criteo_dense_samples_per_sec" if same_semantics
        else "wdl_criteo_cache_samples_per_sec",
        "value": round(batch_size / dt, 1),
        "unit": "samples/s",
        "vs_baseline": round(batch_size / dt / base, 3)
        if base and same_semantics else 0.0,
        "extra": {"baseline_def": f"achieved / baseline samples/s "
                                  f"({label}, plain-embedding both sides)"
                  if base and same_semantics else
                  ("n/a: HET-cache path vs torch plain embedding is not "
                   "same-semantics — run --wdl-embed dense for the "
                   "comparable number" if base else
                   "unavailable: no committed same-workload torch "
                   "baseline"),
                  **_provenance({"batch_size": batch_size,
                                 "embed": policy}),
                  "cache": policy,
                  "cache_mode": emb_device,
                  "cache_hit_rate": round(cache_perf["hit_rate"], 4)
                  if "hit_rate" in cache_perf else None,
                  "emb_pallas_fallback_reason": fallbacks,
                  **({"device_note":
                      "off-TPU measurement: the gather/scatter-add ran "
                      "the COUNTED jnp fallbacks on the host CPU (see "
                      "emb_pallas_fallback_reason), so the 'device' ops "
                      "compete with host Python for the same cores — "
                      "the h2d_rows_per_step ratio is the backend-"
                      "independent win (only miss rows cross the "
                      "boundary); the wall-clock win requires a real "
                      "TPU, where an empty fallback dict certifies the "
                      "Pallas kernels as the measured path"}
                     if emb_device == "device"
                     and jax.default_backend() != "tpu" else {}),
                  **({"host_step_time_ms": round(host_dt * 1e3, 2),
                      # wall ratio (includes the device path's one-time
                      # per-bucket fill compiles inside the timed
                      # window) ...
                      "vs_host_cache": round(host_dt / dt, 3),
                      # ... and the steady-state ratio: p50-vs-p50 from
                      # the step-time histograms, which is the number a
                      # long-running job converges to
                      "vs_host_cache_p50": _p50_ratio(host_hist,
                                                      step_hist),
                      "host_step_time_hist_ms": host_hist}
                     if host_dt is not None else {}),
                  **({"h2d_rows_per_step": h2d_rows}
                     if h2d_rows is not None else {}),
                  "step_time_ms": round(dt * 1e3, 2),
                  "step_time_hist_ms": step_hist,
                  "backend": jax.default_backend()},
    }


def _p50_ratio(host_hist, dev_hist):
    """host p50 / measured p50 from two ``_step_percentiles`` snapshots
    (>1 = the measured mode is faster at steady state)."""
    try:
        return round(host_hist["train"]["p50_ms"]
                     / dev_hist["train"]["p50_ms"], 3)
    except (KeyError, TypeError, ZeroDivisionError):
        return None


def bench_attention(steps=10, warmup=2, cpu_fallback=False):
    """Attention microbench: the {bias, no-bias} × {aligned, ragged} ×
    {cp=1, cp>1} sweep behind the universal flash fast path (additive
    bias in the ring-flash kernel + ragged-length bucketing).  Each cell
    times a jitted fwd+bwd step and records whether the Pallas custom-
    call is in ITS compiled HLO plus any ``flash_fallback_reason``
    counters its trace recorded — the evidence `flash_in_hlo: true`
    claims need, per cell rather than per flagship run."""
    import jax
    import jax.numpy as jnp
    import hetu_tpu as ht
    from hetu_tpu import metrics as hmetrics
    from hetu_tpu.ops.attention import dispatch_sdpa, dispatch_sdpa_bias
    from hetu_tpu.parallel.ring_attention import ring_attention

    # ragged is even so the cp>1 cells can shard S over the ring; it is
    # NOT 128-divisible (420 % 128 == 36), which is the whole point
    if cpu_fallback:
        B, H, D, aligned, ragged = 2, 4, 32, 256, 200
    else:
        B, H, D, aligned, ragged = 4, 8, 64, 512, 420
    rng = np.random.RandomState(0)
    n_dev = len(jax.devices())
    cp_sizes = [1] + ([2] if n_dev >= 2 else [])

    def _cell(s, with_bias, cp):
        q, k, v = (jnp.asarray(rng.randn(B, H, s, D).astype(np.float32)
                               * 0.3) for _ in range(3))
        bias = jnp.asarray(rng.randn(1, H, s, s).astype(np.float32) * 0.5) \
            if with_bias else None
        mesh = ht.make_mesh({"cp": cp}, jax.devices()[:cp]) if cp > 1 \
            else None

        def attn(q, k, v, b):
            if cp > 1:
                return ring_attention(q, k, v, mesh, bias=b)
            if b is not None:
                return dispatch_sdpa_bias(q, k, v, b)
            return dispatch_sdpa(q, k, v)

        if with_bias:
            def loss(q, k, v, b):
                return (attn(q, k, v, b) ** 2).sum()
            step = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))
            args = (q, k, v, bias)
        else:
            def loss(q, k, v):
                return (attn(q, k, v, None) ** 2).sum()
            step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            args = (q, k, v)

        # trace+compile ONCE, bracketed by the fallback counters so the
        # cell's reasons are ITS OWN (dispatch records at trace time);
        # the same AOT executable serves both the HLO inspection and the
        # timed loop (calling `step` again would recompile from a cold
        # jit cache — doubling XLA compile time across the sweep)
        hmetrics.reset_flash_fallbacks()
        compiled = step.lower(*args).compile()
        fallbacks = hmetrics.flash_fallback_counts()
        hlo = compiled.as_text()
        flash = any(t in hlo for t in ("tpu_custom_call", "mosaic"))

        dt = _timed(lambda i: compiled(*args), steps, warmup)
        return {"step_ms": round(dt * 1e3, 3),
                "tokens_per_sec": round(B * s / dt, 1),
                "flash_in_hlo": flash,
                "flash_fallbacks": fallbacks or None}

    cells = {}
    for cp in cp_sizes:
        for kind, s in (("aligned", aligned), ("ragged", ragged)):
            for with_bias in (False, True):
                key = (f"{'bias' if with_bias else 'nobias'}"
                       f"_{kind}_cp{cp}")
                try:
                    cells[key] = _cell(s, with_bias, cp)
                except Exception as e:     # a broken cell must not kill
                    cells[key] = {"error": repr(e)[:300]}  # the sweep
    if n_dev < 2:
        cells["cp2"] = {"skipped": f"needs >=2 devices, have {n_dev}"}

    headline = cells.get("bias_ragged_cp1", {})
    ideal = cells.get("nobias_aligned_cp1", {})
    value = headline.get("tokens_per_sec", 0.0)
    ideal_tps = ideal.get("tokens_per_sec", 0.0)
    return {
        "metric": "attn_flash_sweep_tokens_per_sec",
        "value": value,
        "unit": "tokens/s",
        # how close the newly-unlocked cell (bias+ragged) runs to the
        # ideal dense aligned fast path on the same chip
        "vs_baseline": round(value / ideal_tps, 3) if ideal_tps else 0.0,
        "extra": {
            "baseline_def": "bias+ragged cp=1 tokens/s ÷ nobias+aligned "
                            "cp=1 tokens/s (same run, same chip)",
            **_provenance({"batch_size": B, "heads": H, "head_dim": D,
                           "seq_aligned": aligned, "seq_ragged": ragged}),
            "cells": cells,
            "backend": jax.default_backend(),
            "devices": n_dev,
        },
    }


def bench_moe(batch_tokens=8192, steps=20, warmup=3):
    """BASELINE config 5: MoE transformer expert-parallel step (GShard
    top-2 gate, 16 experts; on one chip the a2a is local, on an 'ep'
    mesh XLA shards the expert dim)."""
    import jax

    dims, ex, fd = build_moe_graph(batch_tokens=batch_tokens)
    experts = dims["experts"]
    dt = _timed(lambda i: ex.run("train", feed_dict=fd), steps, warmup)
    base, label = _torch_bench_baseline("moe", {"tokens": batch_tokens})
    return {
        "metric": "moe_ep_tokens_per_sec",
        "value": round(batch_tokens / dt, 1),
        "unit": "tokens/s",
        "vs_baseline": round(batch_tokens / dt / base, 3) if base else 0.0,
        "extra": {"baseline_def": f"achieved / baseline tokens/s "
                                  f"({label})" if base else
                                  "unavailable: no committed same-workload "
                                  "torch baseline",
                  **_provenance({"tokens": batch_tokens}),
                  "experts": experts,
                  "step_time_ms": round(dt * 1e3, 2),
                  "step_time_hist_ms": _step_percentiles(),
                  "compute_dtype": _compute_dtype() or "float32",
                  "backend": jax.default_backend()},
    }


def bench_chaos(steps=8, kill_step=3):
    """Fault-injection smoke (ISSUE 2 CI satellite): a short PS training
    loop under a FIXED chaos schedule — the rank-1 PS server is killed
    after step ``kill_step`` — measuring detection+recovery wall time and
    restart count, with loss parity against the uninterrupted run as the
    correctness gate.  Host-side metric: the dist-store transport and the
    retry/resume path run on the host whatever the accelerator is."""
    import glob as _glob
    import shutil
    import tempfile

    import jax
    import hetu_tpu as ht
    from hetu_tpu import chaos as chaos_mod
    from hetu_tpu.graph.executor import Executor
    from hetu_tpu.metrics import fault_counts, reset_faults
    from hetu_tpu.ps.dist_store import DistributedStore

    def store_pair(ports):
        endpoints = [("127.0.0.1", p) for p in ports]
        stores = [DistributedStore(r, 2, endpoints, port=ports[r],
                                   rpc_timeout=5.0, rpc_retries=2,
                                   connect_timeout=2.0) for r in range(2)]
        table = np.random.RandomState(42).normal(
            0, 0.01, (64, 8)).astype(np.float32)
        tid = None
        for r, s in enumerate(stores):
            tid = s.init_table(64, 8, opt="sgd", lr=0.1, init_scale=0.0)
            s.local.set_data(tid, table[np.arange(32) * 2 + r])
        return stores[0], stores[1], tid

    def build(store, tid, **kw):
        rng = np.random.RandomState(1)
        ids = ht.placeholder_op("ids")
        y_ = ht.placeholder_op("y")
        h = ht.ps_embedding_lookup_op((store, tid), ids, width=8)
        w = ht.Variable("w", value=rng.randn(8, 2).astype(np.float32) * .3)
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.matmul_op(h, w), y_), [0])
        ex = ht.Executor(
            {"train": [loss, ht.optim.AdamOptimizer(0.01).minimize(loss)]},
            seed=0, install_signal_handlers=False, **kw)
        return ex, ids, y_

    def save_shard1(s1, tid, save_dir, step):
        # in a real deployment every rank's executor saves its own PS
        # shard; this single-process smoke mirrors rank 1's shard save
        ck = os.path.join(save_dir, f"ckpt-{step:08d}")
        if os.path.isdir(ck):
            s1.save(tid, os.path.join(ck, "ps0.bin"))

    rng = np.random.RandomState(0)
    feeds = [(rng.randint(0, 64, 32),
              np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)])
             for _ in range(steps)]

    # the smoke measures ITS OWN fixed schedule: an inherited HETU_CHAOS
    # must not inject into the baseline (the stores' install_from_env
    # would resurrect it) or contaminate the clean-run counters
    env_chaos = os.environ.pop("HETU_CHAOS", None)
    chaos_mod.uninstall()

    # uninterrupted baseline (also proves a clean run records NO faults)
    reset_faults()
    s0, s1, tid = store_pair(_free_ports(2))
    ex, ids, y_ = build(s0, tid)
    base = [float(ex.run("train", feed_dict={ids: f[0], y_: f[1]}
                         )[0].asnumpy()) for f in feeds]
    s0.close()
    s1.close()
    clean_counters = fault_counts()

    save_dir = tempfile.mkdtemp(prefix="hetu_chaos_bench_")
    schedule = f"11:kill:ps@rank1:step{kill_step}"
    reset_faults()
    prev = chaos_mod.install(chaos_mod.ChaosInjector.from_spec(schedule))
    ports = _free_ports(2)
    s0, s1, tid = store_pair(ports)
    recovery_ms, restarts = 0.0, 0
    losses = [None] * steps
    t_run0 = time.monotonic()
    try:
        ex, ids, y_ = build(s0, tid, auto_save_dir=save_dir,
                            auto_save_every=1)
        step = 0
        while step < steps:
            try:
                losses[step] = float(
                    ex.run("train", feed_dict={ids: feeds[step][0],
                                               y_: feeds[step][1]}
                           )[0].asnumpy())
                step += 1
                save_shard1(s1, tid, save_dir, step)
            except RuntimeError:
                t_fail = time.monotonic()
                restarts += 1
                if restarts > 3:
                    raise
                cands = [c for c in sorted(
                    _glob.glob(os.path.join(save_dir, "ckpt-*")),
                    reverse=True) if Executor._checkpoint_complete(c)]
                if not cands:
                    raise RuntimeError(
                        "chaos recovery: no complete checkpoint to "
                        "restore from (kill landed before the first "
                        "auto-save?)")
                newest = cands[0]
                endpoints = [("127.0.0.1", p) for p in ports]
                s1 = DistributedStore(1, 2, endpoints, port=ports[1],
                                      rpc_timeout=5.0, rpc_retries=2,
                                      connect_timeout=2.0)
                s1.init_table(64, 8, opt="sgd", lr=0.1, init_scale=0.0)
                s1.load(tid, os.path.join(newest, "ps0.bin"))
                ex, ids, y_ = build(s0, tid, auto_save_dir=save_dir,
                                    auto_save_every=1)
                step = ex.resume(save_dir)
                if step is None:
                    raise RuntimeError(
                        "chaos recovery: resume found no loadable "
                        "checkpoint under " + save_dir)
                # recovery-time clock stops at the END of the first post-
                # resume step: detect → restore → prove training moves
                losses[step] = float(
                    ex.run("train", feed_dict={ids: feeds[step][0],
                                               y_: feeds[step][1]}
                           )[0].asnumpy())
                step += 1
                save_shard1(s1, tid, save_dir, step)
                recovery_ms += (time.monotonic() - t_fail) * 1e3
        parity = losses == base
        counters = fault_counts()
    finally:
        chaos_mod.install(prev)
        if env_chaos is not None:
            os.environ["HETU_CHAOS"] = env_chaos
        for s in (s0, s1):
            try:
                s.close()
            except Exception:
                pass
        shutil.rmtree(save_dir, ignore_errors=True)
    total_ms = (time.monotonic() - t_run0) * 1e3
    return {
        "metric": "chaos_recovery_ms",
        "value": round(recovery_ms, 1),
        "unit": "ms",
        "vs_baseline": 1.0 if parity and restarts else 0.0,
        "extra": {
            "baseline_def": "1.0 iff the chaos run's loss trajectory is "
                            "exactly equal to the uninterrupted run's "
                            "(and at least one injected failure + "
                            "recovery actually happened)",
            **_provenance({"steps": steps, "kill_step": kill_step,
                           "schedule": schedule}),
            "restarts": restarts,
            "total_wall_ms": round(total_ms, 1),
            "loss_parity": parity,
            "fault_counters": counters,
            "clean_run_counters": clean_counters,
            "backend": jax.default_backend(),
        },
    }


def bench_failover(steps=10, kill_step=3, smoke=True):
    """ISSUE 4 acceptance: live PS shard replication under chaos.  A
    3-rank replicated (``replication=2``) store cluster trains while the
    schedule kills the shard-1 PRIMARY after step ``kill_step``; the
    shard router promotes the live backup inside the failing RPC — ZERO
    supervisor restarts, ZERO lost steps, per-step losses bitwise equal
    to the uninterrupted run.  A standby rank then relaunches, the
    executor's re-replication tick re-attaches it (checksum-verified by
    tools/ps_fsck), and a SECOND kill of the promoted ex-backup proves
    the restored redundancy is real.  ``recovery_ms`` is the total wall
    time of the steps that absorbed a failover — the bound to beat is
    one rpc_timeout + heartbeat deadline (vs PR 2's kill-everything
    recovery measured in checkpoint-resume minutes).  Host-side metric:
    transport + failover run on the host whatever the accelerator is."""

    import jax
    import hetu_tpu as ht
    from hetu_tpu import chaos as chaos_mod
    from hetu_tpu.analysis.protocol import PROTO, check_conformance
    from hetu_tpu.metrics import fault_counts, reset_faults
    from hetu_tpu.ps.dist_store import DistributedStore
    from tools.ps_fsck import fsck

    world, rows, width = 3, 48, 8
    rpc_timeout, hb_deadline_ms = 5.0, 1500.0
    second_kill = steps - 3
    assert second_kill > kill_step + 2, "need room to re-replicate"

    def make_store(rank, ports, standby=False):
        return DistributedStore(
            rank, world, [("127.0.0.1", p) for p in ports],
            port=ports[rank], rpc_timeout=rpc_timeout, rpc_retries=2,
            connect_timeout=2.0, replication=2, standby=standby)

    def make_cluster(ports):
        stores = [make_store(r, ports) for r in range(world)]
        tid = None
        for s in stores:
            tid = s.init_table(rows, width, opt="sgd", lr=0.1,
                               init_scale=0.0)
        table = np.random.RandomState(42).normal(
            0, 0.01, (rows, width)).astype(np.float32)
        # through the REPLICATED set_data path: primaries and backups
        # start bitwise identical
        stores[0].set_data(tid, table)
        return stores, tid

    def build(store, tid):
        rng = np.random.RandomState(1)
        ids = ht.placeholder_op("ids")
        y_ = ht.placeholder_op("y")
        h = ht.ps_embedding_lookup_op((store, tid), ids, width=width)
        w = ht.Variable("w", value=rng.randn(width, 2).astype(np.float32)
                        * .3)
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.matmul_op(h, w), y_), [0])
        ex = ht.Executor(
            {"train": [loss, ht.optim.AdamOptimizer(0.01).minimize(loss)]},
            seed=0, install_signal_handlers=False)
        return ex, ids, y_

    rng = np.random.RandomState(0)
    feeds = [(rng.randint(0, rows, 32),
              np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)])
             for _ in range(steps)]

    # an inherited HETU_CHAOS must not contaminate the baseline, and the
    # re-replication tick is this bench's own knob
    env_chaos = os.environ.pop("HETU_CHAOS", None)
    env_tick = os.environ.pop("HETU_PS_REREPLICATE_EVERY", None)
    chaos_mod.uninstall()

    # --- uninterrupted replicated baseline: ZERO fault counters ----------
    reset_faults()
    stores, tid = make_cluster(_free_ports(world))
    try:
        ex, ids, y_ = build(stores[0], tid)
        base = [float(ex.run("train", feed_dict={ids: f[0], y_: f[1]}
                             )[0].asnumpy()) for f in feeds]
    finally:
        for s in stores:
            s.close()
    clean_counters = fault_counts()

    # --- chaos run: kill the shard-1 primary TWICE -----------------------
    schedule = (f"11:kill:primary@shard1:step{kill_step},"
                f"kill:primary@shard1:step{second_kill}")
    reset_faults()
    os.environ["HETU_PS_REREPLICATE_EVERY"] = "1"
    prev = chaos_mod.install(chaos_mod.ChaosInjector.from_spec(schedule))
    ports = _free_ports(world)
    stores, tid = make_cluster(ports)
    standby = None
    losses = [None] * steps
    step_ms = [0.0] * steps
    failover_steps, fsck_report = [], None
    t_run0 = time.monotonic()
    # the chaos run is also a RECORDED protocol trace: every promote /
    # fence / apply transition is replayed against the replication
    # model's transition relation (ISSUE 20) and conformance gates ok
    PROTO.start()
    try:
        ex, ids, y_ = build(stores[0], tid)
        for step in range(steps):
            before = fault_counts().get("ps_failover_promoted", 0)
            t0 = time.monotonic()
            # NO try/except, NO resume: a killed primary is transparent
            losses[step] = float(
                ex.run("train", feed_dict={ids: feeds[step][0],
                                           y_: feeds[step][1]}
                       )[0].asnumpy())
            step_ms[step] = (time.monotonic() - t0) * 1e3
            if fault_counts().get("ps_failover_promoted", 0) > before:
                failover_steps.append(step)
            if step == kill_step + 1 and standby is None:
                # ops relaunch a standby at the dead rank's endpoint; the
                # executor's next re-replication tick re-attaches it
                standby = make_store(1, ports, standby=True)
            if step == second_kill - 2:
                # the kill fires inside step second_kill-1's post-step
                # hook (step_counter is 1-based), so this is the last
                # step with the whole cluster up:
                # redundancy must be BACK before the second kill
                fsck_report = fsck([("127.0.0.1", p) for p in ports],
                                   n_tables=1, replication=2)
        parity = losses == base
        counters = fault_counts()
    finally:
        proto_events = PROTO.stop()   # before teardown closes fire
        chaos_mod.install(prev)
        if env_chaos is not None:
            os.environ["HETU_CHAOS"] = env_chaos
        os.environ.pop("HETU_PS_REREPLICATE_EVERY", None)
        if env_tick is not None:
            os.environ["HETU_PS_REREPLICATE_EVERY"] = env_tick
        for s in stores + ([standby] if standby else []):
            try:
                s.close()
            except Exception:
                pass
    total_ms = (time.monotonic() - t_run0) * 1e3
    recovery_ms = sum(step_ms[s] for s in failover_steps)
    bound_ms = rpc_timeout * 1e3 + hb_deadline_ms
    proto_conf = check_conformance(proto_events)
    ok = (parity and len(failover_steps) == 2 and recovery_ms < bound_ms
          and bool(fsck_report and fsck_report["ok"])
          and proto_conf["ok"]
          and not clean_counters)
    return {
        "metric": "failover_recovery_ms",
        "value": round(recovery_ms, 1),
        "unit": "ms",
        "vs_baseline": 1.0 if ok else 0.0,
        "extra": {
            "baseline_def": "1.0 iff the double-kill run's loss "
                            "trajectory is bitwise equal to the "
                            "uninterrupted replicated run's, both kills "
                            "were absorbed by failover (restarts=0, no "
                            "resume), recovery stayed under one "
                            "rpc_timeout + heartbeat deadline, fsck "
                            "verified the re-replicated backup, the "
                            "recorded protocol trace conformed to the "
                            "replication model, and the clean run "
                            "recorded zero fault counters",
            **_provenance({"steps": steps, "kill_step": kill_step,
                           "second_kill_step": second_kill,
                           "world": world, "replication": 2,
                           "schedule": schedule, "smoke": bool(smoke)}),
            "restarts": 0,
            "resumes": 0,
            "failover_steps": failover_steps,
            "recovery_bound_ms": bound_ms,
            "step_ms": [round(m, 1) for m in step_ms],
            "total_wall_ms": round(total_ms, 1),
            "loss_parity": parity,
            "redundancy_restored": bool(fsck_report
                                        and fsck_report["ok"]),
            "fsck_mismatches": (fsck_report or {}).get("mismatches"),
            "protocol_conformance": proto_conf,
            "fault_counters": counters,
            "clean_run_counters": clean_counters,
            "backend": jax.default_backend(),
        },
    }


def bench_serve(smoke=True, n_requests=None, seed=0):
    """ISSUE 7 acceptance: online inference serving under chaos.  A
    wdl-style CTR model (26 zipf(1.05)-skewed categorical fields through
    a PS embedding, dense tower, sigmoid click prob) is served by the
    new ``hetu_tpu.serving`` stack — InferenceExecutor (compile-once per
    batch bucket) + ServingRouter (bounded queue, adaptive micro-batch)
    — with the embedding pulled READ-ONLY through ``DistCacheTable``
    from a 3-rank ``replication=2`` DistributedStore.  The same seeded
    request stream runs twice: clean, and with a chaos schedule that
    kills the shard-1 PRIMARY mid-load (``kill:primary@shard1:req<n>``,
    fired on the router's admission clock).  The kill must be absorbed
    by client-transparent failover: restarts=0, every request answered,
    responses BITWISE equal to the clean run, p99 degradation bounded by
    one rpc_timeout + heartbeat deadline.  Host-side metric: routing,
    batching and the PS transport run on the host whatever the
    accelerator is."""

    import jax
    import hetu_tpu as ht
    from hetu_tpu import chaos as chaos_mod
    from hetu_tpu.metrics import (fault_counts, reset_faults,
                                  reset_serve_counts, serve_counts,
                                  serve_latency_stats)
    from hetu_tpu.ps.dist_store import DistCacheTable, DistributedStore
    from hetu_tpu.serving import InferenceExecutor, ServingRouter

    n_requests = int(n_requests or (300 if smoke else 2000))
    world, dim, n_fields = 3, 8, 26
    vocab = 26 * 80 if smoke else 26 * 2000       # per-field 80 / 2000
    rpc_timeout, hb_deadline_ms = 2.0, 1500.0
    # max_wait_ms is the partial-wave ship deadline AND the packing-
    # determinism margin (see the wave comment below): full waves ship
    # on count, so only the two trailing partial waves ever pay it —
    # 150ms is ~150x the ~1ms wave-submission window a stall would have
    # to outlast to split a wave, without drowning p99 in deadline time
    max_batch, max_wait_ms = 64, 150.0
    kill_req = n_requests // 2

    def make_cluster(ports):
        stores = [DistributedStore(
            r, world, [("127.0.0.1", p) for p in ports], port=ports[r],
            rpc_timeout=rpc_timeout, rpc_retries=2, connect_timeout=2.0,
            replication=2) for r in range(world)]
        tid = None
        for s in stores:
            tid = s.init_table(vocab, dim, opt="sgd", lr=0.1,
                               init_scale=0.0)
        table = np.random.RandomState(42).normal(
            0, 0.01, (vocab, dim)).astype(np.float32)
        stores[0].set_data(tid, table)   # replicated path: primaries and
        return stores, tid               # backups bitwise identical

    def build_serving(store, tid):
        """wdl-style serving graph over a READ-ONLY embedding cache."""
        dense = ht.placeholder_op("dense")
        sparse = ht.placeholder_op("sparse", dtype=np.int64)
        cache = DistCacheTable(store, tid, limit=max(vocab // 2, 256),
                               policy="lru", read_only=True)
        emb = ht.ps_embedding_lookup_op(cache, sparse, width=dim)
        flat = ht.array_reshape_op(emb, (-1, n_fields * dim))
        x = ht.concat_op(flat, dense, axis=1)
        h = x
        rng = np.random.RandomState(7)
        dims = [n_fields * dim + 13, 32, 1]
        for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
            w = ht.Variable(f"serve_w{i}",
                            value=(rng.randn(din, dout) * 0.2
                                   ).astype(np.float32))
            h = ht.matmul_op(h, w)
            if i < len(dims) - 2:
                h = ht.relu_op(h)
        prob = ht.sigmoid_op(h)
        iex = InferenceExecutor([prob], seed=0, validate="error",
                                buckets=(8, 16, 32, 64))
        return iex, dense, sparse, cache

    # the seeded stream: zipf(1.05)-skewed ids per field + dense features,
    # chopped into deterministic waves so both runs pack IDENTICAL
    # batches (bitwise parity requires each request to run in the same
    # bucket).  Determinism mechanics: a FULL wave (== max_batch) ships
    # the moment the count is reached, independent of timing; the two
    # trailing partial waves ship at the head-of-line deadline, which is
    # set generously below so a scheduler stall mid-submission cannot
    # split a wave into differently-bucketed halves between the runs.
    rng = np.random.RandomState(seed)
    per_field = vocab // n_fields
    ranks = np.arange(per_field, dtype=np.float64)
    p = 1.0 / (ranks + 1.0) ** 1.05
    p /= p.sum()
    field = np.stack([rng.choice(per_field, n_requests, p=p)
                      for _ in range(n_fields)], axis=1)
    sparse_all = (field + np.arange(n_fields) * per_field).astype(np.int64)
    dense_all = rng.rand(n_requests, 13).astype(np.float32)
    waves = [max_batch] * (n_requests // max_batch)
    rest = n_requests % max_batch
    if rest > 1:
        waves += [rest // 2, rest - rest // 2]   # two partial buckets
    elif rest:
        waves += [rest]

    env_chaos = os.environ.pop("HETU_CHAOS", None)
    chaos_mod.uninstall()

    def run_stream(tag):
        """One full serving run over the stream; returns (responses,
        per-request latency ms, per-wave wall ms, wave serve_failover
        deltas, rejections)."""
        reset_serve_counts()
        ports = _free_ports(world)
        stores, tid = make_cluster(ports)
        responses = [None] * n_requests
        lat_ms = [0.0] * n_requests
        wave_ms, wave_failover = [], []
        try:
            iex, dense, sparse, cache = build_serving(stores[0], tid)
            router = ServingRouter(iex, max_batch=max_batch,
                                   max_wait_ms=max_wait_ms,
                                   queue_limit=n_requests + 8)
            try:
                i = 0
                for wsize in waves:
                    t0 = time.monotonic()
                    before = serve_counts().get("serve_failovers", 0)
                    futs = []
                    for j in range(i, i + wsize):
                        t_sub = time.monotonic()
                        fut = router.submit({dense: dense_all[j],
                                             sparse: sparse_all[j]})
                        fut.add_done_callback(
                            lambda f, j=j, t=t_sub: lat_ms.__setitem__(
                                j, (time.monotonic() - t) * 1e3))
                        futs.append((j, fut))
                    for j, fut in futs:
                        responses[j] = np.asarray(fut.result(timeout=60)[0])
                    wave_ms.append((time.monotonic() - t0) * 1e3)
                    wave_failover.append(
                        serve_counts().get("serve_failovers", 0) - before)
                    i += wsize
            finally:
                router.close()
            return (responses, lat_ms, wave_ms, wave_failover,
                    serve_counts(), serve_latency_stats())
        finally:
            for s in stores:
                try:
                    s.close()
                except Exception:
                    pass

    try:
        # --- clean run: zero fault counters, the parity oracle -----------
        reset_faults()
        base_resp, base_lat, base_wave_ms, _, base_serve, base_hist = \
            run_stream("clean")
        clean_counters = fault_counts()

        # --- chaos run: shard-1 primary killed mid-load -------------------
        schedule = f"11:kill:primary@shard1:req{kill_req}"
        reset_faults()
        prev = chaos_mod.install(
            chaos_mod.ChaosInjector.from_spec(schedule))
        t0 = time.monotonic()
        try:
            resp, lat, wave_ms, wave_failover, serve_ctrs, chaos_hist = \
                run_stream("chaos")
        finally:
            chaos_mod.install(prev)
        total_ms = (time.monotonic() - t0) * 1e3
        counters = fault_counts()
    finally:
        if env_chaos is not None:
            os.environ["HETU_CHAOS"] = env_chaos

    answered = sum(r is not None for r in resp)
    bitwise = all(r is not None and b is not None and np.array_equal(r, b)
                  for r, b in zip(resp, base_resp))
    recovery_ms = sum(m for m, d in zip(wave_ms, wave_failover) if d)
    bound_ms = rpc_timeout * 1e3 + hb_deadline_ms
    qps = n_requests / (sum(wave_ms) / 1e3)
    base_qps = n_requests / (sum(base_wave_ms) / 1e3)

    def pct(xs, q):
        return float(np.percentile(np.asarray(xs), q))

    ok = (bitwise and answered == n_requests
          and counters.get("chaos_kill_primary", 0) == 1
          and counters.get("ps_failover_promoted", 0) >= 1
          and serve_ctrs.get("serve_failovers", 0) >= 1
          and serve_ctrs.get("serve_rejections", 0) == 0
          and recovery_ms < bound_ms
          and not clean_counters)
    return {
        "metric": "serve_qps",
        "value": round(base_qps, 1),
        "unit": "requests/s",
        "vs_baseline": 1.0 if ok else 0.0,
        "extra": {
            "baseline_def": "1.0 iff the chaos run's responses are "
                            "bitwise equal to the clean run's over the "
                            "same zipf(1.05) stream, every request was "
                            "answered with zero restarts and zero "
                            "rejections, exactly one primary kill was "
                            "absorbed by >=1 client-transparent failover "
                            "mid-serve, the failover wave stayed under "
                            "one rpc_timeout + heartbeat deadline, and "
                            "the clean run recorded zero fault counters",
            **_provenance({"n_requests": n_requests, "vocab": vocab,
                           "dim": dim, "world": world, "replication": 2,
                           "zipf_a": 1.05, "max_batch": max_batch,
                           "max_wait_ms": max_wait_ms,
                           "buckets": [8, 16, 32, 64],
                           "schedule": schedule, "smoke": bool(smoke)}),
            "p50_ms": round(pct(base_lat, 50), 2),
            "p99_ms": round(pct(base_lat, 99), 2),
            "qps": round(base_qps, 1),
            "chaos_p50_ms": round(pct(lat, 50), 2),
            "chaos_p99_ms": round(pct(lat, 99), 2),
            "chaos_qps": round(qps, 1),
            # queue-wait / batch-latency distributions from the obs
            # registry's log-bucketed histograms (ISSUE 10): the
            # router's contribution to tail latency vs the device
            # call's, separable per run — means alone could not tell a
            # p99 spike from a shifted mean
            "latency_hist_ms": _hist_ms(base_hist),
            "chaos_latency_hist_ms": _hist_ms(chaos_hist),
            "rejections": int(serve_ctrs.get("serve_rejections", 0)),
            "failover_recovery_ms": round(recovery_ms, 1),
            "recovery_bound_ms": bound_ms,
            "restarts": 0,
            "all_answered": answered == n_requests,
            "responses_bitwise_equal": bitwise,
            "serve_counters": serve_ctrs,
            "clean_serve_counters": base_serve,
            "fault_counters": counters,
            "clean_run_counters": clean_counters,
            "total_wall_ms": round(total_ms, 1),
            "backend": jax.default_backend(),
        },
    }


def bench_fleet(smoke=True, n_requests=None, seed=0, write_artifact=None):
    """ISSUE 17 acceptance: the fleet serving tier under a flash crowd.

    A seeded diurnal request stream (calm -> 10x spike -> cool, classes
    mixed 70/20/10 interactive/batch/best_effort) hits a ``FrontDoor``
    that starts at ONE replica of a 3-layer dense serving graph.  The
    ``SLOAutoscaler`` is polled on the ADMISSION clock (once per
    submission wave); the spike must breach its load watermark and the
    recorded scale-out must grow aggregate bounded-queue capacity so
    that the interactive p99 SLO holds and interactive traffic is NEVER
    rejected, while best_effort is shed EXPLICITLY (counted structured
    ``shed:best_effort`` rejections, zero unbounded queues).  Replica
    spin-up must be a ``step_cache_serve_hit``, not a compile.  The same
    stream then reruns with ``kill:replica@1:req<n>`` — the scaled-out
    replica killed mid-spike on the door's admission clock — which must
    be absorbed by ejection + queue rescue: restarts=0, every admitted
    request answered, and responses bitwise equal to the clean run on
    the requests admitted in both.  Host-side metric: admission,
    dispatch, health and scaling logic run on the host whatever the
    accelerator is; one CPU core drains both runs, so the scale-out win
    is CAPACITY (sheds stop, queues stay bounded), not raw throughput.
    """
    import jax
    import hetu_tpu as ht
    from hetu_tpu import chaos as chaos_mod
    from hetu_tpu.metrics import (fault_counts, fleet_counts,
                                  reset_faults, reset_fleet_counts,
                                  reset_serve_counts,
                                  reset_serve_rejection_counts,
                                  serve_counts, serve_rejection_counts,
                                  step_cache_counts)
    from hetu_tpu.serving import (FrontDoor, InferenceExecutor,
                                  ServeRejected, ServingRouter,
                                  SLOAutoscaler)

    n_requests = int(n_requests or (420 if smoke else 1400))
    calm_n = max(20, n_requests // 10)
    spike_n = n_requests - 2 * calm_n           # ~10x the calm volume
    wave = 20                                   # autoscaler poll cadence
    in_dim, hid, out_dim = 64, 256, 8
    max_batch, queue_limit = 8, 120
    slo_ms = 500.0 if smoke else 700.0
    # the kill lands mid-spike, after the first post-wave poll has
    # certainly scaled out (grow_grace=1): replica 1 exists by then
    kill_req = calm_n + 3 * wave + wave // 2

    # the serving graph: 3 dense layers — enough real device work per
    # batch that an unpaced submission burst outruns the drain on one
    # core, which is what makes the flash crowd a crowd
    rng = np.random.RandomState(seed)
    x = ht.placeholder_op("x_fleet_bench")
    h = x
    dims = [in_dim, hid, hid, out_dim]
    for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
        w = ht.Variable(f"fleet_w{i}",
                        value=(rng.randn(din, dout) * 0.1
                               ).astype(np.float32))
        h = ht.matmul_op(h, w)
        if i < len(dims) - 2:
            h = ht.relu_op(h)
    y = h

    # the seeded stream: request features + class mix, identical across
    # the clean and chaos runs (admission DECISIONS may differ — load
    # dynamics diverge after the kill — but request i's payload and
    # class never do, which is what makes per-request parity meaningful)
    feats = rng.randn(n_requests, in_dim).astype(np.float32)
    class_draw = rng.rand(n_requests)
    klasses = np.where(class_draw < 0.70, "interactive",
                       np.where(class_draw < 0.90, "batch",
                                "best_effort"))

    env_chaos = os.environ.pop("HETU_CHAOS", None)
    chaos_mod.uninstall()

    def run_stream(tag, schedule=None):
        reset_serve_counts()
        reset_serve_rejection_counts()
        reset_fleet_counts()
        reset_faults()
        sc0 = step_cache_counts().get("step_cache_serve_hit", 0)
        co0 = serve_counts().get("serve_bucket_compiles", 0)
        prev = None
        if schedule is not None:
            prev = chaos_mod.install(
                chaos_mod.ChaosInjector.from_spec(schedule))
        try:
            def mk(idx):
                return ServingRouter(
                    InferenceExecutor([y], seed=0, buckets=(max_batch,)),
                    max_batch=max_batch, max_wait_ms=2.0,
                    queue_limit=queue_limit, name=f"r{idx}")

            # best_effort's watermark sits LOW: the shed window is the
            # early spike, before the scale-outs triple aggregate
            # capacity and the load factor collapses — exactly the
            # degradation story (shed cheap traffic first, then grow)
            door = FrontDoor(mk, 1, shed_at={"interactive": None,
                                             "batch": 0.45,
                                             "best_effort": 0.1},
                             wedge_timeout_ms=2000.0)
            scaler = SLOAutoscaler(door, p99_target_ms=slo_ms,
                                   min_replicas=1, max_replicas=3,
                                   grow_grace=1, shrink_grace=4,
                                   grow_load=0.15, shrink_load=0.02)
            responses = [None] * n_requests
            lat_ms = [None] * n_requests
            rejections = {}             # (klass, reason) -> count
            max_pending = 0
            futs = []

            def submit(i):
                t0 = time.monotonic()
                try:
                    fut = door.submit({x: feats[i]},
                                      klass=str(klasses[i]))
                except ServeRejected as e:
                    key = f"{klasses[i]}:{e.reason}"
                    rejections[key] = rejections.get(key, 0) + 1
                    return
                fut.add_done_callback(
                    lambda f, i=i, t=t0: lat_ms.__setitem__(
                        i, (time.monotonic() - t) * 1e3))
                futs.append((i, fut))

            def poll():
                nonlocal max_pending
                scaler.poll()
                for rep in door.stats()["replicas"]:
                    max_pending = max(max_pending, rep["pending"])

            t_run = time.monotonic()
            for i in range(calm_n):                     # calm
                submit(i)
                if (i + 1) % wave == 0:
                    poll()
                time.sleep(0.0005)
            for i in range(calm_n, calm_n + spike_n):   # 10x flash crowd
                submit(i)
                if (i + 1) % wave == 0:
                    poll()
            for i in range(calm_n + spike_n, n_requests):   # cool-down
                submit(i)
                if (i + 1) % wave == 0:
                    poll()
                time.sleep(0.0005)
            failures = 0
            for i, fut in futs:
                try:
                    responses[i] = np.asarray(fut.result(timeout=60)[0])
                except Exception:   # noqa: BLE001 — counted, gated to 0
                    failures += 1
            poll()
            wall_ms = (time.monotonic() - t_run) * 1e3
            door.close()
            return {
                "tag": tag,
                "responses": responses,
                "lat_ms": lat_ms,
                "rejections": rejections,
                "reason_counts": dict(serve_rejection_counts()),
                "fleet_counts": dict(fleet_counts()),
                "fault_counts": dict(fault_counts()),
                "events": list(scaler.events),
                "failures": failures,
                "max_pending": max_pending,
                "wall_ms": wall_ms,
                "serve_hit_delta":
                    step_cache_counts().get("step_cache_serve_hit", 0)
                    - sc0,
                "compile_delta":
                    serve_counts().get("serve_bucket_compiles", 0) - co0,
            }
        finally:
            if schedule is not None:
                chaos_mod.install(prev)

    try:
        clean = run_stream("clean")
        schedule = f"13:kill:replica@1:req{kill_req}"
        chaos = run_stream("chaos", schedule=schedule)
    finally:
        if env_chaos is not None:
            os.environ["HETU_CHAOS"] = env_chaos

    def p99_interactive(run):
        lats = [l for i, l in enumerate(run["lat_ms"])
                if l is not None and klasses[i] == "interactive"]
        return float(np.percentile(np.asarray(lats), 99)) if lats \
            else 0.0

    def admitted_ids(run):
        return {i for i, r in enumerate(run["responses"])
                if r is not None}

    both = admitted_ids(clean) & admitted_ids(chaos)
    bitwise = all(np.array_equal(clean["responses"][i],
                                 chaos["responses"][i]) for i in both)
    clean_p99 = p99_interactive(clean)
    chaos_p99 = p99_interactive(chaos)

    def interactive_rejections(run):
        return sum(n for key, n in run["rejections"].items()
                   if key.startswith("interactive:"))

    # spin-up proof: across both runs exactly ONE real bucket build (the
    # very first replica of the clean run); every later replica — scaled
    # out or run-2 rebuilt — resolved through the serve step cache
    spinup_cheap = (clean["compile_delta"] == 1
                    and chaos["compile_delta"] == 0
                    and clean["serve_hit_delta"]
                    >= len(clean["events"])
                    and chaos["serve_hit_delta"] >= 1)

    scaled_out = (any(e["kind"] == "scale_out" for e in clean["events"])
                  and any(e["kind"] == "scale_out"
                          for e in chaos["events"]))
    sheds_counted = (clean["reason_counts"].get("shed:best_effort", 0)
                     > 0
                    and chaos["reason_counts"].get("shed:best_effort", 0)
                     > 0)
    # bounded queues: per-replica pending never exceeded the queue
    # limit (chaos run may briefly double a survivor's depth when it
    # ADOPTS the dead replica's rescued queue — that is the documented
    # bounded exception, not unbounded growth)
    bounded = (clean["max_pending"] <= queue_limit
               and chaos["max_pending"] <= 2 * queue_limit)
    kill_absorbed = (
        chaos["fault_counts"].get("chaos_kill_replica", 0) == 1
        and chaos["fleet_counts"].get("fleet_replica_ejected", 0) >= 1
        and chaos["failures"] == 0
        and chaos["fleet_counts"].get("fleet_request_failures", 0) == 0)

    ok = (clean_p99 <= slo_ms and chaos_p99 <= slo_ms
          and scaled_out and sheds_counted and bounded
          and interactive_rejections(clean) == 0
          and interactive_rejections(chaos) == 0
          and clean["failures"] == 0
          and kill_absorbed and bitwise and spinup_cheap
          and not clean["fault_counts"])

    result = {
        "metric": "fleet_spike_interactive_p99_ms",
        "value": round(clean_p99, 2),
        "unit": "ms",
        "vs_baseline": 1.0 if ok else 0.0,
        "extra": {
            "baseline_def": "1.0 iff the interactive p99 held the SLO "
                            "through the 10x spike in BOTH runs via a "
                            "recorded scale-out (replica spin-up proven "
                            "a step_cache_serve_hit, zero new "
                            "compiles), best_effort was shed as counted "
                            "structured rejections with zero "
                            "interactive rejections and bounded "
                            "per-replica queues, and the mid-spike "
                            "replica kill was absorbed by ejection + "
                            "queue rescue with restarts=0, zero failed "
                            "futures, and responses bitwise equal to "
                            "the clean run on every request admitted "
                            "in both",
            **_provenance({"n_requests": n_requests, "calm_n": calm_n,
                           "spike_n": spike_n, "wave": wave,
                           "dims": dims, "max_batch": max_batch,
                           "queue_limit": queue_limit,
                           "slo_ms": slo_ms, "schedule": schedule,
                           "class_mix": "70/20/10",
                           "smoke": bool(smoke)}),
            "slo": {"target_ms": slo_ms, "held": bool(ok or (
                        clean_p99 <= slo_ms and chaos_p99 <= slo_ms)),
                    "clean_p99_ms": round(clean_p99, 2),
                    "chaos_p99_ms": round(chaos_p99, 2)},
            "scaling": {"events": chaos["events"],
                        "clean_events": clean["events"],
                        "replicas_hw": chaos["fleet_counts"].get(
                            "fleet_replicas_hw", 1)},
            "rejections": chaos["reason_counts"],
            "clean_rejections": clean["reason_counts"],
            "per_class_rejections": {"clean": clean["rejections"],
                                     "chaos": chaos["rejections"]},
            "interactive_rejections": interactive_rejections(chaos),
            "bounded_queues": {"max_pending_clean": clean["max_pending"],
                               "max_pending_chaos": chaos["max_pending"],
                               "queue_limit": queue_limit,
                               "bounded": bounded},
            "spin_up": {"cheap": spinup_cheap,
                        "clean_compiles": clean["compile_delta"],
                        "chaos_compiles": chaos["compile_delta"],
                        "clean_serve_hits": clean["serve_hit_delta"],
                        "chaos_serve_hits": chaos["serve_hit_delta"]},
            "chaos": {"schedule": schedule, "kill_req": kill_req,
                      "restarts": 0,
                      "responses_bitwise_equal": bool(bitwise),
                      "answered_both": len(both),
                      "failed_futures": chaos["failures"],
                      "fleet_counters": chaos["fleet_counts"],
                      "fault_counters": chaos["fault_counts"]},
            "clean_fleet_counters": clean["fleet_counts"],
            "clean_run_fault_counters": clean["fault_counts"],
            "wall_ms": {"clean": round(clean["wall_ms"], 1),
                        "chaos": round(chaos["wall_ms"], 1)},
            "backend": jax.default_backend(),
        },
    }
    if write_artifact is None:
        # unlike the perf benches, the SMOKE run IS the committed
        # artifact: every gate is a robustness invariant, not a margin
        write_artifact = True
    if write_artifact:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "artifacts", "fleet_bench.json")
        with open(path, "w") as f:
            json.dump(result, f, indent=1)
    return result


def bench_decode(smoke=True, n_requests=None, seed=0, write_artifact=None):
    """ISSUE 16 acceptance: continuous-batching autoregressive decode.

    A zipf-sized seeded request stream (prompt lengths and generation
    budgets both skewed) decodes greedily through the
    ``hetu_tpu.serving.decode`` plane — incremental per-layer KV caches
    bucketed on the serving ladder, one jitted step per
    ``(batch_bucket, len_bucket)`` pair — under two scheduling policies:

    * **continuous** (the tentpole): sequences join/leave the in-flight
      batch per token, freed KV slots recycled immediately;
    * **request-level** (the baseline): joins only into an EMPTY engine,
      so the whole batch drains at the pace of its slowest sequence.

    ISSUE 18 (v2) adds prompt-INGESTION legs on top:

    * **token-by-token** (the PR 16 ingestion baseline): the same
      continuous stream with no chunked entry — every prompt token is
      one engine step;
    * the continuous leg now runs CHUNKED prefill (``max_chunk=8``):
      prompts ingest in ``ceil(P/chunk)`` mixed-batch steps through the
      q_len=C graph entry, pure-prefill steps skip the logits D2H;
    * **prefix**: a popularity-skewed pool stream decoded twice through
      chunked engines — cold (reference) and with a
      :class:`PrefixKVStore`, whose hits seat repeat prompts with their
      KV rows pre-filled and skip prefill outright;
    * **ttft**: time-to-first-token measured directly on engines (join
      -> first emitted token, min over reps) at controlled prompt
      lengths, chunked vs token-by-token.

    ISSUE 19 (v3) adds the RECOVERY legs: a 2-replica decode FrontDoor
    under a ``kill:replica@0:tok<n>`` chaos fault on the engine's own
    token clock — every in-flight stream migrated to the survivor and
    bitwise-equal to the unkilled reference with zero failures and zero
    restarts — plus a zero-survivor kill that must fail loudly
    (``recovery_exhausted`` + partial tokens), never hang.

    Gates: ALL policy/ingestion legs produce BITWISE-identical token
    streams (scheduling and ingestion mode must not change results);
    continuous beats request-level on tokens/s with a no-worse p99
    time-to-token, and chunked tokens/s is no worse than token-by-token;
    chunked TTFT beats token-by-token at EVERY measured prompt length;
    the prefix run's streams match its cold reference with hits > 0 and
    prefill rows saved; every stream records exactly one ``ttft``
    histogram observation; the counter proof of the compile-once steady
    state holds over the chunked stream (real compiles + serve-cache
    reuses == dispatch-plan misses == distinct bucket keys — ``(batch,
    len)`` pairs and ``(batch, chunk, len)`` triples — every other step
    a ``plan_cache_hit``); zero rejections.  A further leg times one
    incremental decode step against the naive full re-prefill forward at
    every measured cache length — the O(1)-vs-O(len) per-token claim.
    Host-side scheduling dominates the measured deltas, so CPU is a
    faithful backend for the policy comparison (the jitted step is the
    same program either way)."""
    import jax
    from hetu_tpu import metrics as ht_metrics
    from hetu_tpu.models import (GPT2Config, gpt2_decode_chunked_graph,
                                 gpt2_decode_graph)
    from hetu_tpu.models.gpt2 import gpt2_lm_graph
    from hetu_tpu.profiler import HetuProfiler
    from hetu_tpu.serving import (DecodeEngine, DecodeRouter,
                                  InferenceExecutor, PrefixKVStore)
    from hetu_tpu.serving.decode import _DecodeRequest

    if write_artifact is None:
        write_artifact = not smoke
    n_requests = int(n_requests or (16 if smoke else 100))
    max_slots = 4 if smoke else 8
    max_len = 32 if smoke else 64
    gen_cap = 6 if smoke else 12
    cfg = GPT2Config.tiny(n_positions=2 * max_len, batch_size=1,
                          seq_len=max_len)

    # the seeded zipf stream: most prompts short, a heavy tail, capped so
    # prompt + generation always fits max_len
    rng = np.random.RandomState(seed)
    plens = np.minimum(rng.zipf(1.5, n_requests), max_len // 2)
    news = np.minimum(rng.zipf(1.6, n_requests) + 1, gen_cap)
    prompts = [rng.randint(1, cfg.vocab_size, int(l)).astype(np.int32)
               for l in plens]

    def mk_engine(chunked, store=None):
        feeds, logits, caches, _ = gpt2_decode_graph(cfg, max_len=max_len)
        kw = {}
        if chunked:
            cf, cl, cc, _ = gpt2_decode_chunked_graph(cfg, max_len=max_len)
            kw = {"chunked": (cf, cl, cc), "max_chunk": 8}
        return DecodeEngine(feeds, logits, caches, max_slots=max_slots,
                            max_len=max_len, seed=0, prefix_store=store,
                            **kw)

    def one_pass(continuous, chunked, store=None, reqs=None):
        ht_metrics.reset_all()
        eng = mk_engine(chunked, store=store)
        lat_ms = []          # time-to-token over EVERY emitted token
        rq = reqs if reqs is not None else list(zip(prompts, news))
        with DecodeRouter(eng, queue_limit=len(rq) + 8,
                          max_wait_ms=5.0,
                          continuous=continuous) as router:
            t0 = time.monotonic()
            streams = []
            for p, nw in rq:
                t_sub = time.monotonic()
                s = router.submit(p, max_new_tokens=int(nw))
                for i in range(int(nw)):
                    s.token(i).add_done_callback(
                        lambda f, t=t_sub: lat_ms.append(
                            (time.monotonic() - t) * 1e3)
                        if not f.cancelled() and f.exception() is None
                        else None)
                streams.append(s)
            tokens = [s.result(timeout=600) for s in streams]
            wall_s = time.monotonic() - t0
        lat = HetuProfiler.latency_stats().get("decode_latency_us", {})
        return {
            "tokens": tokens,
            "lat_ms": lat_ms,
            "wall_s": wall_s,
            "tps": sum(len(t) for t in tokens) / wall_s,
            "decode": ht_metrics.decode_counts(),
            "serve": ht_metrics.serve_counts(),
            "run_plan": ht_metrics.run_plan_counts(),
            "step_cache": ht_metrics.step_cache_counts(),
            "prefix_ct": ht_metrics.prefix_cache_counts(),
            "ttft_hist": lat.get("ttft", {}),
            "ladder": (len(eng.batch_ladder), len(eng.len_ladder),
                       len(eng.chunk_ladder)),
        }

    # Warmup passes populate the process-wide serve cache so the
    # measured passes time SCHEDULING, not first-touch XLA compiles (the
    # steady state a long-lived server actually runs in; the measured
    # passes' counters still prove the compile-once claim — their builds
    # all land as step_cache_serve_hits).  The legs then run in
    # INTERLEAVED rounds with best-of on tokens/s: shared-host
    # contention and allocator warm-up drift only ever SLOW a pass and
    # hit whichever leg is running, so sequential legs would fold
    # process age into the policy comparison; interleaving gives every
    # leg the same noise exposure and the fastest pass is the
    # least-noise estimate of each (counters and token streams are
    # deterministic across passes — any pass serves as the proof).
    legs = {"tok": (True, False),    # PR 16 token-by-token ingestion
            "cont": (True, True),    # chunked continuous (the tentpole)
            "reql": (False, False)}  # request-level baseline
    for continuous, chunked in legs.values():
        one_pass(continuous, chunked)
    passes = {k: [] for k in legs}
    for _ in range(1 if smoke else 4):
        for k, (continuous, chunked) in legs.items():
            passes[k].append(one_pass(continuous, chunked))
    tok, cont, reql = (max(passes[k], key=lambda p: p["tps"])
                       for k in ("tok", "cont", "reql"))

    # --- shared-prefix KV reuse: popularity-skewed pool stream ----------
    # The same chunked engine decodes the pool stream cold (reference)
    # and with a PrefixKVStore; repeats must HIT, skip their prefill,
    # and still produce the cold run's exact tokens.
    pool_n = max(4, n_requests // 8)
    pool = [rng.randint(1, cfg.vocab_size,
                        int(rng.randint(4, max_len // 2 + 1))
                        ).astype(np.int32) for _ in range(pool_n)]
    picks = np.minimum(rng.zipf(1.3, n_requests) - 1, pool_n - 1)
    pref_reqs = [(pool[int(k)], int(min(rng.zipf(1.6) + 1, gen_cap)))
                 for k in picks]
    pref_cold = one_pass(True, True, reqs=pref_reqs)
    pref_warm = one_pass(True, True, store=PrefixKVStore(), reqs=pref_reqs)

    # --- exactly-once stream recovery: mid-generation replica kill -------
    # A 2-replica decode FrontDoor (chunked engines, one SHARED
    # PrefixKVStore) decodes a slice of the zipf stream while
    # ``kill:replica@0:tok<n>`` fail-stops replica 0 on its own
    # deterministic token clock; the door's sweep detaches the seated
    # streams with their journals and resurrects them on the survivor.
    # Gates: zero failed streams, zero restarts (the dead replica is
    # never rebuilt), every stream bitwise-equal to the uninterrupted
    # single-engine reference, and the decode_recovery counters + the
    # ``recovery`` decode-latency label tell a consistent timeline
    # (every detached stream reseated, one latency observation each).
    # A second leg kills the ONLY replica of a 1-replica door: every
    # in-flight stream must fail LOUDLY — structured
    # ``recovery_exhausted`` with the partial tokens attached — never
    # hang silently.
    from hetu_tpu import chaos as chaos_mod
    from hetu_tpu.serving import FrontDoor, ServeRejected

    rec_n = min(n_requests, 8 if smoke else 24)
    rec_reqs = list(zip(prompts, news))[:rec_n]
    rec_total = int(sum(int(nw) for _, nw in rec_reqs))
    kill_tok = max(3, rec_total // 8)
    rec_ref = one_pass(True, True, reqs=rec_reqs)["tokens"]

    def _poll_fleet(door, streams, timeout=300.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            door.poll()
            if all(s.done for s in streams):
                return True
            time.sleep(0.005)
        return False

    from hetu_tpu.analysis.protocol import PROTO, check_conformance

    ht_metrics.reset_all()
    rec_store = PrefixKVStore()
    inj = chaos_mod.ChaosInjector.from_spec(
        f"{seed}:kill:replica@0:tok{kill_tok}")
    prev_inj = chaos_mod.install(inj)
    # the kill run doubles as a recorded protocol trace: seat / emit /
    # detach / adopt / fence transitions replay against the decode-
    # recovery model (ISSUE 20) and conformance gates the leg
    PROTO.start()
    try:
        # wedge_timeout pushed out of the way: a first-touch bucket
        # compile inside a step would otherwise read as a wedge on CPU
        door = FrontDoor(
            lambda idx: DecodeRouter(mk_engine(True, store=rec_store),
                                     queue_limit=rec_n + 8,
                                     name=f"recb{idx}"),
            2, health_every_ms=1e9, wedge_timeout_ms=1e9)
        try:
            t0 = time.monotonic()
            rec_streams = [door.submit(p, max_new_tokens=int(nw))
                           for p, nw in rec_reqs]
            rec_done = _poll_fleet(door, rec_streams)
            rec_wall = time.monotonic() - t0
            rec_tokens, rec_failed = [], 0
            for s in rec_streams:
                try:
                    rec_tokens.append(s.result(timeout=60))
                except Exception:
                    rec_failed += 1
                    rec_tokens.append(None)
        finally:
            door.close()
    finally:
        rec_proto = PROTO.stop()
        chaos_mod.install(prev_inj)
    rec_conf = check_conformance(rec_proto)
    rec_c = ht_metrics.decode_recovery_counts()
    rec_fleet = ht_metrics.fleet_counts()
    rec_lat = HetuProfiler.latency_stats().get(
        "decode_latency_us", {}).get("recovery", {})
    rec_restarts = int(rec_fleet.get("fleet_scale_out", 0)) - 2
    rec_ok = (rec_done and rec_failed == 0
              and rec_tokens == rec_ref
              and rec_fleet.get("fleet_replica_ejected", 0) == 1
              and rec_fleet.get("fleet_request_failures", 0) == 0
              and rec_restarts == 0
              and rec_c.get("decode_recovery_reseated", 0) >= 1
              and rec_c.get("decode_recovery_reseated", 0)
              == rec_c.get("decode_recovery_detached", 0)
              and rec_c.get("decode_recovery_exhausted", 0) == 0
              and int(rec_lat.get("count", 0))
              == rec_c.get("decode_recovery_reseated", 0)
              and rec_conf["ok"]
              and ht_metrics.fault_counts().get(
                  "chaos_kill_replica", 0) == 1)

    ht_metrics.reset_all()
    inj0 = chaos_mod.ChaosInjector.from_spec(
        f"{seed}:kill:replica@0:tok3")
    prev_inj = chaos_mod.install(inj0)
    exhausted, zs_partials_ok = 0, True
    PROTO.start()
    try:
        door = FrontDoor(
            lambda idx: DecodeRouter(mk_engine(True), queue_limit=16,
                                     name=f"recz{idx}"),
            1, health_every_ms=1e9, wedge_timeout_ms=1e9)
        try:
            zs = [door.submit(np.full(4, 3 + i, np.int32),
                              max_new_tokens=gen_cap) for i in range(3)]
            _poll_fleet(door, zs, timeout=120.0)
            for s in zs:
                try:
                    s.result(timeout=60)
                    zs_partials_ok = False     # nothing may "succeed"
                except ServeRejected as exc:
                    if exc.reason == "recovery_exhausted":
                        exhausted += 1
                        zs_partials_ok = zs_partials_ok \
                            and isinstance(exc.partial, list) \
                            and len(exc.partial) >= 1
        finally:
            door.close()
    finally:
        zs_proto = PROTO.stop()
        chaos_mod.install(prev_inj)
    zs_conf = check_conformance(zs_proto)
    exhaust_ok = (exhausted >= 1 and zs_partials_ok
                  and zs_conf["ok"]
                  and ht_metrics.decode_recovery_counts().get(
                      "decode_recovery_exhausted", 0) == exhausted)

    def pct(xs, q):
        return float(np.percentile(np.asarray(xs), q))

    # --- incremental KV cache vs naive re-prefill, per cache length ------
    # This leg uses a WIDER model than the policy streams above: the
    # O(1)-vs-O(len) claim is about device math, and on the tiny stream
    # model the per-step host scheduling overhead (~1ms on CPU) would
    # drown the length-dependent term at small L.  The engine's max_len
    # leaves headroom above the largest measured length so the timed
    # steps never exhaust the cache and drop the sequence mid-measure.
    lengths = (8, 16, 32) if smoke else (8, 16, 32, 64)
    reps = 5 if smoke else 9
    kv_max_len = 128
    kvcfg = GPT2Config.tiny(n_positions=2 * kv_max_len, batch_size=1,
                            seq_len=kv_max_len, n_embd=384, n_layer=4,
                            n_head=4)
    feeds, logits, caches, _ = gpt2_decode_graph(kvcfg,
                                                 max_len=kv_max_len)
    eng = DecodeEngine(feeds, logits, caches, max_slots=1,
                       max_len=kv_max_len, seed=0)
    per_len = []
    for L in lengths:
        req = _DecodeRequest(np.full(L, 3, np.int32),
                             max_new=reps + 4, eos_id=None, fid=None)
        eng.join(req)
        for _ in range(L - 1):        # prefill to position L-1
            eng.step()
        eng.step()                    # warmup the generate-leg compile
        ts = []
        for _ in range(reps):
            t = time.perf_counter()
            eng.step()
            ts.append(time.perf_counter() - t)
        eng.abort(RuntimeError("bench drain"))
        incr_ms = float(min(ts)) * 1e3
        # the naive alternative: one FULL forward over the L-token
        # prefix for every generated token, including the host-side
        # fetch + argmax the engine's step also pays
        lcfg = GPT2Config.tiny(n_positions=2 * kv_max_len, batch_size=1,
                               seq_len=L, n_embd=384, n_layer=4,
                               n_head=4)
        f2, _loss, logits2 = gpt2_lm_graph(lcfg)
        iex_full = InferenceExecutor([logits2], buckets=(1,), seed=0,
                                     validate="off", donate=False)
        fn = iex_full.compiled(1)
        ids = np.full((1, L), 3, np.int32)
        fd = {iex_full._k(f2["input_ids"]): ids}
        jax.block_until_ready(fn(iex_full.params, fd))    # warmup
        ts = []
        for _ in range(reps):
            t = time.perf_counter()
            out = fn(iex_full.params, fd)
            row = np.asarray(out[0]).reshape(L, -1)[L - 1]
            int(np.argmax(row))
            ts.append(time.perf_counter() - t)
        reprefill_ms = float(min(ts)) * 1e3
        per_len.append({"len": L, "incremental_ms": round(incr_ms, 3),
                        "reprefill_ms": round(reprefill_ms, 3),
                        "speedup": round(reprefill_ms / incr_ms, 2)})

    # --- time-to-first-token: chunked vs token-by-token ingestion --------
    # Measured directly on engines (join -> stream complete with
    # max_new=1), min over reps after a compile-warmup rep.  Chunked
    # ingestion pays ceil(L/chunk) steps where token-by-token pays L, so
    # the win is structural, not a timing accident.
    ttft_lens = (4, 8, 16) if smoke else (4, 8, 16, 24)
    ttft_reps = 3 if smoke else 5
    engines = {"token_by_token": mk_engine(chunked=False),
               "chunked": mk_engine(chunked=True)}
    ttft_rows = []
    for L in ttft_lens:
        prompt = np.full(L, 3, np.int32)
        ms, toks = {}, {}
        for name, eng in engines.items():
            best = None
            for r in range(ttft_reps + 1):     # rep 0: compile warmup
                req = _DecodeRequest(prompt, max_new=1, eos_id=None,
                                     fid=None)
                t = time.perf_counter()
                eng.join(req)
                while eng.active:
                    eng.step()
                dt = (time.perf_counter() - t) * 1e3
                toks[name] = req.stream.result(timeout=60)
                if r:
                    best = dt if best is None else min(best, dt)
            ms[name] = best
        ttft_rows.append({
            "prompt_len": int(L),
            "token_by_token_ms": round(ms["token_by_token"], 3),
            "chunked_ms": round(ms["chunked"], 3),
            "speedup": round(ms["token_by_token"] / ms["chunked"], 2),
            "bitwise_equal": toks["token_by_token"] == toks["chunked"],
        })
    ttft_wins = all(r["chunked_ms"] < r["token_by_token_ms"]
                    and r["bitwise_equal"] for r in ttft_rows)

    # --- the acceptance gates --------------------------------------------
    bitwise = (cont["tokens"] == reql["tokens"]
               and cont["tokens"] == tok["tokens"])
    steps_n = cont["decode"]["decode_steps"]
    pairs = cont["run_plan"].get("plan_cache_miss", 0)
    compiles = (cont["serve"].get("serve_bucket_compiles", 0)
                + cont["step_cache"].get("step_cache_serve_hit", 0))
    compile_once = (pairs > 0 and compiles == pairs
                    and cont["run_plan"].get("plan_cache_hit", 0)
                    == steps_n - pairs
                    and pairs <= cont["ladder"][0] * cont["ladder"][1]
                    * cont["ladder"][2])
    kv_wins = all(r["incremental_ms"] < r["reprefill_ms"]
                  for r in per_len)
    no_rejects = all(leg["decode"].get("decode_rejections", 0) == 0
                     for leg in (cont, reql, tok, pref_warm))
    pc = pref_warm["prefix_ct"]
    hits = pc.get("prefix_cache_hits", 0)
    misses = pc.get("prefix_cache_misses", 0)
    prefix_ok = (pref_warm["tokens"] == pref_cold["tokens"]
                 and hits > 0
                 and pref_warm["decode"].get("decode_prefill_rows", 0)
                 < pref_cold["decode"].get("decode_prefill_rows", 0))
    ttft_counted = cont["ttft_hist"].get("count", 0) == n_requests
    cont_p99 = pct(cont["lat_ms"], 99)
    req_p99 = pct(reql["lat_ms"], 99)
    perf_ok = (cont["tps"] > reql["tps"] and cont_p99 <= req_p99
               and cont["tps"] >= tok["tps"])
    ok = bitwise and compile_once and kv_wins and no_rejects \
        and ttft_wins and prefix_ok and ttft_counted \
        and rec_ok and exhaust_ok \
        and (perf_ok or smoke)     # the perf margin gates the full run

    result = {
        "metric": "decode_tokens_per_s",
        "value": round(cont["tps"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(cont["tps"] / reql["tps"], 3) if ok else 0.0,
        "extra": {
            "baseline_def": "chunked continuous-batching tokens/s over "
                            "request-level batching of the SAME seeded "
                            "zipf stream (bitwise-identical token "
                            "streams required across continuous, "
                            "request-level AND token-by-token "
                            "ingestion); 0.0 unless every gate held: "
                            "compile-once per (batch,len) pair and "
                            "(batch,chunk,len) triple with "
                            "plan-cache-hit steady state, incremental "
                            "KV step faster than re-prefill at every "
                            "measured length, chunked TTFT faster than "
                            "token-by-token at every measured prompt "
                            "length, prefix-cache hits with prefill "
                            "rows saved and a bitwise-equal stream, one "
                            "ttft histogram observation per stream, "
                            "zero rejections, a mid-generation "
                            "kill:replica@0:tok<n> recovery leg with "
                            "zero failed streams / zero restarts and "
                            "every stream bitwise-equal to the "
                            "unkilled reference (and a zero-survivor "
                            "kill failing loudly with "
                            "recovery_exhausted + partial tokens), "
                            "and (full runs) better "
                            "tokens/s at no-worse p99 time-to-token "
                            "with chunked tokens/s no worse than "
                            "token-by-token",
            **_provenance({"n_requests": n_requests,
                           "max_slots": max_slots, "max_len": max_len,
                           "gen_cap": gen_cap, "zipf_prompt_a": 1.5,
                           "zipf_gen_a": 1.6, "n_embd": cfg.n_embd,
                           "n_layer": cfg.n_layer, "seed": seed,
                           "max_chunk": 8, "prefix_pool": pool_n,
                           "zipf_pool_a": 1.3,
                           "ttft_lens": list(ttft_lens),
                           "kv_leg_n_embd": 384, "kv_leg_n_layer": 4,
                           "kv_leg_max_len": kv_max_len,
                           "recovery_streams": rec_n,
                           "recovery_kill_tok": int(kill_tok),
                           "smoke": bool(smoke)}),
            "continuous": {
                "tokens_per_s": round(cont["tps"], 1),
                "p50_ms": round(pct(cont["lat_ms"], 50), 2),
                "p99_ms": round(cont_p99, 2),
                "wall_s": round(cont["wall_s"], 2),
                "counters": cont["decode"],
            },
            "request_level": {
                "tokens_per_s": round(reql["tps"], 1),
                "p50_ms": round(pct(reql["lat_ms"], 50), 2),
                "p99_ms": round(req_p99, 2),
                "wall_s": round(reql["wall_s"], 2),
                "counters": reql["decode"],
            },
            "token_by_token": {
                "tokens_per_s": round(tok["tps"], 1),
                "p50_ms": round(pct(tok["lat_ms"], 50), 2),
                "p99_ms": round(pct(tok["lat_ms"], 99), 2),
                "wall_s": round(tok["wall_s"], 2),
                "counters": tok["decode"],
            },
            "streams_bitwise_equal": bitwise,
            "compile_once": {
                "decode_steps": int(steps_n),
                "bucket_keys": int(pairs),
                "bucket_key_bound": int(cont["ladder"][0]
                                        * cont["ladder"][1]
                                        * cont["ladder"][2]),
                "serve_bucket_compiles": int(
                    cont["serve"].get("serve_bucket_compiles", 0)),
                "step_cache_serve_hits": int(
                    cont["step_cache"].get("step_cache_serve_hit", 0)),
                "plan_cache_hits": int(
                    cont["run_plan"].get("plan_cache_hit", 0)),
                "holds": bool(compile_once),
            },
            "prefill": {
                "steps": int(cont["decode"].get(
                    "decode_prefill_steps", 0)),
                "steps_saved_vs_token_by_token": int(cont["decode"].get(
                    "decode_prefill_steps_saved", 0)),
                "logits_fetches_skipped": int(cont["decode"].get(
                    "decode_logits_skipped", 0)),
            },
            "ttft_vs_token_by_token": ttft_rows,
            "ttft_wins_every_length": ttft_wins,
            "ttft_histogram": cont["ttft_hist"],
            "ttft_counted_per_stream": ttft_counted,
            "prefix_cache": {
                "hits": int(hits),
                "misses": int(misses),
                "hit_rate": round(hits / max(1, hits + misses), 3),
                "hit_rows": int(pc.get("prefix_cache_hit_rows", 0)),
                "evictions": int(pc.get("prefix_cache_evictions", 0)),
                "bytes_hw": int(pc.get("prefix_cache_bytes_hw", 0)),
                "prefill_rows_cold": int(pref_cold["decode"].get(
                    "decode_prefill_rows", 0)),
                "prefill_rows_warm": int(pref_warm["decode"].get(
                    "decode_prefill_rows", 0)),
                "streams_bitwise_equal": pref_warm["tokens"]
                == pref_cold["tokens"],
                "holds": bool(prefix_ok),
            },
            "kv_cache_vs_reprefill": per_len,
            "kv_incremental_wins_every_length": kv_wins,
            "recovery": {
                "kill_spec": f"kill:replica@0:tok{kill_tok}",
                "streams": int(rec_n),
                "failed_streams": int(rec_failed),
                "restarts": int(rec_restarts),
                "streams_bitwise_equal_to_unkilled":
                    rec_tokens == rec_ref,
                "counters": {k: int(v) for k, v in rec_c.items()},
                "fleet": {k: int(v) for k, v in rec_fleet.items()},
                "reseat_latency_us": rec_lat,
                "wall_s": round(rec_wall, 2),
                "protocol_conformance": rec_conf,
                "holds": bool(rec_ok),
                "zero_survivor": {
                    "streams": 3,
                    "recovery_exhausted": int(exhausted),
                    "partials_attached": bool(zs_partials_ok),
                    "protocol_conformance": zs_conf,
                    "holds": bool(exhaust_ok),
                },
            },
            "total_tokens": int(sum(len(t) for t in cont["tokens"])),
            "backend": jax.default_backend(),
        },
    }
    if write_artifact:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "artifacts", "decode_bench.json")
        with open(path, "w") as f:
            json.dump(result, f, indent=1)
    return result


def bench_trace(steps=5, kill_step=2, smoke=True, write_artifact=None):
    """ISSUE 10 demo: one unified telemetry trace of the framework's
    signature behaviours — ``artifacts/trace_step.json``.

    A 5-step wdl-style PS training run (3-rank ``replication=2``
    cluster, Adam through a PS embedding) executes under a
    ``kill:primary@shard1:step<k>`` chaos schedule with ``HETU_TRACE=1``
    live: the kill lands in step k's post-step hook, so the NEXT step's
    pull absorbs the failover — its ``fault:ps_rpc_retry`` /
    ``fault:ps_failover*`` point events appear INSIDE that step's span,
    between its per-opcode ``rpc:OP_*`` spans.  The run is driven by
    ``Executor.run_steps(sync=False)`` with the feed pipeline forced on
    (``HETU_FEED_PIPELINE_MIN_US=0``) so the background H2D copies show
    up as a named ``run-steps-feed`` track and the non-blocking window
    as flow arrows; a small serving burst through
    ``InferenceExecutor``/``ServingRouter`` adds the serve-router track
    (enqueue -> assemble -> device call -> scatter).  Losses stay
    BITWISE equal to an untraced clean run — telemetry and failover are
    both transparent.  The exported Chrome JSON loads directly in
    Perfetto; the step-time histogram and the MFU gauge (inferred-shape
    FLOPs over measured step time) land on the metrics registry and
    ride in ``extra``."""
    import jax
    import hetu_tpu as ht
    from hetu_tpu import chaos as chaos_mod, obs
    from hetu_tpu import metrics as ht_metrics
    from hetu_tpu.metrics import fault_counts, reset_faults
    from hetu_tpu.ps.dist_store import DistributedStore
    from hetu_tpu.serving import InferenceExecutor, ServingRouter

    if write_artifact is None:
        write_artifact = not smoke
    world, rows, width = 3, 48, 8
    rpc_timeout = 5.0
    assert 0 < kill_step < steps - 1, "the failover needs a later step"

    def make_cluster(ports):
        stores = [DistributedStore(
            r, world, [("127.0.0.1", p) for p in ports], port=ports[r],
            rpc_timeout=rpc_timeout, rpc_retries=2, connect_timeout=2.0,
            replication=2) for r in range(world)]
        tid = None
        for s in stores:
            tid = s.init_table(rows, width, opt="sgd", lr=0.1,
                               init_scale=0.0)
        table = np.random.RandomState(42).normal(
            0, 0.01, (rows, width)).astype(np.float32)
        stores[0].set_data(tid, table)
        return stores, tid

    def build(store, tid):
        rng = np.random.RandomState(1)
        ids = ht.placeholder_op("ids")
        y_ = ht.placeholder_op("y")
        h = ht.ps_embedding_lookup_op((store, tid), ids, width=width)
        w = ht.Variable("w", value=rng.randn(width, 2).astype(np.float32)
                        * .3)
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.matmul_op(h, w), y_), [0])
        ex = ht.Executor(
            {"train": [loss, ht.optim.AdamOptimizer(0.01).minimize(loss)]},
            seed=0, install_signal_handlers=False)
        return ex, loss, ids, y_

    rng = np.random.RandomState(0)
    feeds = [(rng.randint(0, rows, 32),
              np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)])
             for _ in range(steps)]

    def run_train(store, tid):
        ex, loss, ids, y_ = build(store, tid)
        rs = ex.run_steps(
            lambda i: {ids: feeds[i][0], y_: feeds[i][1]}, steps,
            name="train", sync=False)
        fd0 = {ids: feeds[0][0], y_: feeds[0][1]}
        return ex, loss, fd0, [
            np.asarray(r[0].jax(), np.float32).tobytes() for r in rs]

    env_chaos = os.environ.pop("HETU_CHAOS", None)
    env_min = os.environ.get("HETU_FEED_PIPELINE_MIN_US")
    # tiny batches: force the H2D double-buffer on so the feed-pipeline
    # track exists (the adaptive threshold would keep them inline)
    os.environ["HETU_FEED_PIPELINE_MIN_US"] = "0"
    chaos_mod.uninstall()
    prev_trace = obs.enabled()
    prev_timing = ht_metrics.step_timing

    try:
        # --- clean, untraced run: the parity oracle ----------------------
        obs.enable(False)
        reset_faults()
        stores, tid = make_cluster(_free_ports(world))
        try:
            _, _, _, base_losses = run_train(stores[0], tid)
        finally:
            for s in stores:
                s.close()
        clean_counters = fault_counts()

        # --- traced chaos run -------------------------------------------
        schedule = f"11:kill:primary@shard1:step{kill_step}"
        reset_faults()
        ht_metrics.reset_step_times()
        ht_metrics.enable_step_timing(True)
        obs.clear_trace()
        obs.enable(True)
        prev = chaos_mod.install(
            chaos_mod.ChaosInjector.from_spec(schedule))
        try:
            stores, tid = make_cluster(_free_ports(world))
            try:
                ex, loss, fd0, chaos_losses = run_train(stores[0], tid)
                # MFU gauge: PR 5 inferred-shape FLOPs over the MEASURED
                # per-step wall from the step_time_us histogram (the
                # run just recorded it) — a wall clock around the whole
                # run would fold cluster setup + compile into "step
                # time" and understate MFU ~100x on a 5-step run
                flops = obs.graph_flops([loss], feeds=fd0)
                # p50, not mean: step 0's recorded wall contains the
                # jit compile, which would dominate a 5-step mean
                step_s = ht_metrics.step_time_stats()["train"]["p50"] \
                    / 1e6
                peak, device_kind = _device_peak_flops()
                mfu = obs.record_mfu("trace_wdl", flops, step_s, peak)
                # serving burst: the router/assemble/device-call/scatter
                # lifecycle on its own named track
                sx = ht.placeholder_op("sx", shape=(width,))
                sw = ht.Variable("trace_serve_w", value=np.random.RandomState(
                    3).randn(width, 1).astype(np.float32))
                prob = ht.sigmoid_op(ht.matmul_op(sx, sw))
                iex = InferenceExecutor([prob], seed=0, buckets=(4, 8))
                with ServingRouter(iex, max_batch=4,
                                   max_wait_ms=20.0) as router:
                    futs = [router.submit(
                        {sx: np.ones((width,), np.float32) * i})
                        for i in range(8)]
                    for f in futs:
                        f.result(timeout=30)
            finally:
                for s in stores:
                    try:
                        s.close()
                    except Exception:
                        pass
        finally:
            chaos_mod.install(prev)
            obs.enable(False)
            ht_metrics.enable_step_timing(False)
        counters = fault_counts()
        evs = obs.trace_events()
        step_stats = ht_metrics.step_time_stats().get("train", {})
    finally:
        if env_chaos is not None:
            os.environ["HETU_CHAOS"] = env_chaos
        if env_min is None:
            os.environ.pop("HETU_FEED_PIPELINE_MIN_US", None)
        else:
            os.environ["HETU_FEED_PIPELINE_MIN_US"] = env_min
        obs.enable(prev_trace)
        ht_metrics.enable_step_timing(prev_timing)

    # --- trace self-checks (the acceptance claims, machine-checked) ------
    names = [e["name"] for e in evs]
    tracks = [e["args"]["name"] for e in evs
              if e.get("ph") == "M" and e["name"] == "thread_name"]
    step_spans = [e for e in evs if e.get("ph") == "X"
                  and e["name"] == "step"]
    promo = [e for e in evs if e["name"] == "fault:ps_failover_promoted"]
    # the promotion instant must land INSIDE one step span's window
    promo_in_step = any(
        s["ts"] <= p["ts"] <= s["ts"] + s["dur"]
        for p in promo for s in step_spans)
    checks = {
        "step_spans": len(step_spans),
        "rpc_spans": sum(1 for n in names if n.startswith("rpc:")),
        "retry_events": sum(1 for n in names
                            if n == "fault:ps_rpc_retry"),
        "failover_promotions": len(promo),
        "promotion_inside_step_span": bool(promo_in_step),
        "feed_pipeline_track": any("run-steps-feed" in t
                                   or "feed-pipeline" in t
                                   for t in tracks),
        "serve_router_track": any("hetu-serve-router" in t
                                  for t in tracks),
        "serve_device_calls": names.count("serve.device_call"),
        "flow_arrows": sum(1 for e in evs if e.get("ph") == "s"),
        "loss_parity": chaos_losses == base_losses,
        "clean_run_counters_empty": not clean_counters,
    }
    ok = (checks["step_spans"] >= steps
          and checks["rpc_spans"] > 0
          and checks["failover_promotions"] >= 1
          and checks["promotion_inside_step_span"]
          and checks["feed_pipeline_track"]
          and checks["serve_router_track"]
          and checks["serve_device_calls"] >= 1
          and checks["loss_parity"]
          and checks["clean_run_counters_empty"])

    if write_artifact:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "artifacts", "trace_step.json")
        obs.export_chrome_trace(path)

    workload = {"steps": steps, "kill_step": kill_step, "world": world,
                "replication": 2, "schedule": schedule,
                "smoke": bool(smoke)}
    return {
        "metric": "trace_step_events",
        "value": len(evs),
        "unit": "events",
        "vs_baseline": 1.0 if ok else 0.0,
        "extra": {
            "baseline_def": "1.0 iff the exported trace carries >= "
                            "steps step spans, per-opcode rpc spans, "
                            "the failover promotion as a point event "
                            "INSIDE a step span, the feed-pipeline and "
                            "serve-router thread tracks, >= 1 serving "
                            "device call, bitwise loss parity vs the "
                            "untraced clean run, and the clean run "
                            "recorded zero fault counters",
            **_provenance(workload),
            **checks,
            "tracks": sorted(set(tracks)),
            "step_time_us_p50": step_stats.get("p50"),
            "step_time_us_p99": step_stats.get("p99"),
            "mfu": mfu,
            "flops_per_step": flops,
            "device_kind": device_kind,
            "fault_counters": counters,
            "backend": jax.default_backend(),
        },
    }


def bench_partition(steps=10, cut_step=3, heal_step=7, smoke=True):
    """ISSUE 8 acceptance: partition tolerance with fencing epochs.

    Part A (3-rank training): the same seeded run three times — clean,
    ``partition:rank0|rank1@step<cut>`` without heal, and with
    ``:heal<m>``.  The partition cuts the training client (rank 0) off
    shard 1's primary: the client fails over to the ring backup (epoch
    bump), training continues with ZERO restarts, and losses stay
    BITWISE equal to the clean run in both chaos variants (every acked
    write lands on the surviving lineage).  After heal, a stale client
    (rank 1's own store) writes through the healed stale ex-primary:
    the op-log forward is epoch-refused by the promoted backup
    (``ps_epoch_refused``), the ex-primary demotes itself
    (``ps_demotions``) instead of acking, and the client re-routes the
    SAME op to the surviving lineage — then epoch-checked
    re-replication converges both copies, proven by
    ``ps_fsck(retries=2)``: zero stable divergence and exactly one
    serving epoch per shard.  The no-heal run documents the detectable
    split brain fsck sees when nothing converges it.

    Part B (2-cell geo-replicated serving): 4 ranks in two cells, each
    serving InferenceExecutor traffic through a ServingRouter off a
    read-only warmed DistCacheTable.  A cross-cell partition leaves
    BOTH cells answering local reads (rejections=0, errors=0); the east
    cell promotes a local backup for a missed shard (new lineage);
    cross-cell re-replication queues (deferred) until heal; at heal the
    west trainer's first stale write triggers the fence dance and
    ``CellHead.catch_up`` re-replicates — fsck converges to one lineage.

    Host-side metric: transport, fencing and routing run on the host
    whatever the accelerator is."""

    import jax
    import hetu_tpu as ht
    from hetu_tpu import chaos as chaos_mod
    from hetu_tpu.analysis.protocol import PROTO, check_conformance
    from hetu_tpu.metrics import fault_counts, reset_faults
    from hetu_tpu.ps.dist_store import DistributedStore
    from tools.ps_fsck import fsck

    world, rows, width = 3, 48, 8
    rpc_timeout = 5.0
    assert cut_step < heal_step < steps - 1, "need post-heal steps"

    def make_cluster(ports, nranks=world, nrows=rows, w=width):
        stores = [DistributedStore(
            r, nranks, [("127.0.0.1", p) for p in ports], port=ports[r],
            rpc_timeout=rpc_timeout, rpc_retries=2, connect_timeout=2.0,
            replication=2) for r in range(nranks)]
        tid = None
        for s in stores:
            tid = s.init_table(nrows, w, opt="sgd", lr=0.1, init_scale=0.0)
        table = np.random.RandomState(42).normal(
            0, 0.01, (nrows, w)).astype(np.float32)
        stores[0].set_data(tid, table)   # replicated seeding path
        return stores, tid

    def build(store, tid):
        rng = np.random.RandomState(1)
        ids = ht.placeholder_op("ids")
        y_ = ht.placeholder_op("y")
        h = ht.ps_embedding_lookup_op((store, tid), ids, width=width)
        w = ht.Variable("w", value=rng.randn(width, 2).astype(np.float32)
                        * .3)
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.matmul_op(h, w), y_), [0])
        ex = ht.Executor(
            {"train": [loss, ht.optim.AdamOptimizer(0.01).minimize(loss)]},
            seed=0, install_signal_handlers=False)
        return ex, ids, y_

    rng = np.random.RandomState(0)
    feeds = [(rng.randint(0, rows, 32),
              np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)])
             for _ in range(steps)]
    # the stale-client probe: shard-1-owned keys, ZERO grads — sgd leaves
    # the values bitwise unchanged, so the probe can ride every variant
    # without perturbing loss parity while still exercising the write
    # path (and, post-heal, the fence dance)
    probe_keys = np.asarray([1, 4], np.int64)
    probe_grads = np.zeros((2, width), np.float32)

    env_chaos = os.environ.pop("HETU_CHAOS", None)
    env_tick = os.environ.pop("HETU_PS_REREPLICATE_EVERY", None)
    chaos_mod.uninstall()

    def run_variant(schedule, heal):
        """One full training run; returns (losses, per-step ms, events,
        fault counters, fsck report, protocol-conformance report) — the
        run is also a RECORDED protocol trace replayed against the
        replication model (ISSUE 20)."""
        reset_faults()
        ports = _free_ports(world)
        stores, tid = make_cluster(ports)
        losses, step_ms = [None] * steps, [0.0] * steps
        events = {"failover_steps": [], "deferred_in_partition": False,
                  "probe_acked": False, "heal_catchup_ms": 0.0}
        prev = chaos_mod.install(
            chaos_mod.ChaosInjector.from_spec(schedule)) if schedule \
            else chaos_mod.uninstall()
        PROTO.start()
        try:
            ex, ids, y_ = build(stores[0], tid)
            for step in range(steps):
                before = fault_counts().get("ps_failover_promoted", 0)
                t0 = time.monotonic()
                # NO try/except, NO restart: a partitioned primary is
                # absorbed by failover inside the failing RPC
                losses[step] = float(
                    ex.run("train", feed_dict={ids: feeds[step][0],
                                               y_: feeds[step][1]}
                           )[0].asnumpy())
                step_ms[step] = (time.monotonic() - t0) * 1e3
                if fault_counts().get("ps_failover_promoted", 0) > before:
                    events["failover_steps"].append(step + 1)
                if schedule and step + 1 == cut_step + 2:
                    # mid-partition repair attempt: cross-cut
                    # re-replication must QUEUE (defer), not crash
                    d0 = fault_counts().get("ps_re_replicate_deferred", 0)
                    stores[0].maybe_re_replicate()
                    events["deferred_in_partition"] = \
                        fault_counts().get("ps_re_replicate_deferred",
                                           0) > d0
                if step + 1 == heal_step and (heal or not schedule):
                    # the stale client writes through the (in the heal
                    # variant: healed, still stale-serving) ex-primary —
                    # clean run: plain replicated write; heal run: the
                    # fence dance re-routes it to the surviving lineage
                    t1 = time.monotonic()
                    stores[1].push(tid, probe_keys, probe_grads)
                    events["probe_acked"] = True
                    stores[0].maybe_re_replicate()  # epoch-checked repair
                    events["heal_catchup_ms"] = \
                        (time.monotonic() - t1) * 1e3
            report = fsck([("127.0.0.1", p) for p in ports], n_tables=1,
                          replication=2, retries=2, retry_wait=0.2)
            out = (losses, step_ms, events, fault_counts(), report)
        finally:
            proto_events = PROTO.stop()  # before teardown closes fire
            chaos_mod.install(prev) if schedule else None
            for s in stores:
                try:
                    s.close()
                except Exception:
                    pass
        return out + (check_conformance(proto_events),)

    two_cell = None
    try:
        base, base_ms, base_ev, clean_counters, base_fsck, base_conf = \
            run_variant(None, heal=False)
        noheal = run_variant(
            f"13:partition:rank0|rank1@step{cut_step}", heal=False)
        heal = run_variant(
            f"13:partition:rank0|rank1@step{cut_step}:heal{heal_step}",
            heal=True)
        two_cell = _two_cell_scenario(cut_step, heal_step)
    finally:
        chaos_mod.uninstall()
        if env_chaos is not None:
            os.environ["HETU_CHAOS"] = env_chaos
        if env_tick is not None:
            os.environ["HETU_PS_REREPLICATE_EVERY"] = env_tick

    h_losses, h_ms, h_ev, h_counters, h_fsck, h_conf = heal
    n_losses, _, n_ev, n_counters, n_fsck, n_conf = noheal
    heal_parity = h_losses == base
    noheal_parity = n_losses == base
    one_lineage = all(len(r) == 1
                      for r in h_fsck["serving_ranks"].values())
    recovery_ms = sum(h_ms[s - 1] for s in h_ev["failover_steps"]) \
        + h_ev["heal_catchup_ms"]
    ok = (heal_parity and noheal_parity
          and h_ev["probe_acked"]
          and h_ev["deferred_in_partition"]
          and h_counters.get("partition_frames_dropped", 0) > 0
          and h_counters.get("ps_epoch_refused", 0) > 0
          and h_counters.get("ps_demotions", 0) > 0
          and h_counters.get("ps_epoch_bumps", 0) > 0
          and h_counters.get("ps_failover_promoted", 0) >= 1
          and h_fsck["ok"] and one_lineage
          and h_fsck["serving_ranks"][1] == [2]
          and not n_fsck["ok"]          # unhealed split brain is VISIBLE
          and bool(n_fsck["lineage_violations"])
          and base_fsck["ok"] and not clean_counters
          and base_conf["ok"] and n_conf["ok"] and h_conf["ok"]
          and bool(two_cell) and two_cell["ok"])
    return {
        "metric": "partition_recovery_ms",
        "value": round(recovery_ms, 1),
        "unit": "ms",
        "vs_baseline": 1.0 if ok else 0.0,
        "extra": {
            "baseline_def": "1.0 iff BOTH partition runs' loss "
                            "trajectories are bitwise equal to the clean "
                            "run's (restarts=0, zero lost acked writes), "
                            "the healed stale ex-primary was epoch-"
                            "refused and demoted instead of serving, "
                            "in-partition re-replication deferred, post-"
                            "heal fsck (retries=2) found zero stable "
                            "divergence and exactly one serving epoch "
                            "per shard, the UNHEALED run's split brain "
                            "stayed fsck-visible, the clean run recorded "
                            "zero fault counters, every variant's "
                            "recorded protocol trace conformed to the "
                            "replication model, and the 2-cell "
                            "scenario served local reads through the "
                            "cut (rejections=0) and converged after "
                            "heal",
            **_provenance({"steps": steps, "cut_step": cut_step,
                           "heal_step": heal_step, "world": world,
                           "replication": 2, "smoke": bool(smoke)}),
            "restarts": 0,
            "resumes": 0,
            "loss_parity_heal": heal_parity,
            "loss_parity_noheal": noheal_parity,
            "probe_acked": h_ev["probe_acked"],
            "failover_steps": h_ev["failover_steps"],
            "re_replication_deferred_in_partition":
                h_ev["deferred_in_partition"],
            "heal_catchup_ms": round(h_ev["heal_catchup_ms"], 1),
            "step_ms": [round(m, 1) for m in h_ms],
            "fault_counters": h_counters,
            "noheal_fault_counters": n_counters,
            "clean_run_counters": clean_counters,
            "fsck_ok": h_fsck["ok"],
            "fsck_retries_used": h_fsck["retries_used"],
            "fsck_serving_ranks": h_fsck["serving_ranks"],
            "fsck_epochs": {
                s: {r: v["epoch"] for r, v in eps.items()}
                for s, eps in h_fsck["epochs"].items()},
            "noheal_split_brain_detected":
                bool(n_fsck["lineage_violations"]) or not n_fsck["ok"],
            "noheal_lineage_violations": n_fsck["lineage_violations"],
            "protocol_conformance": h_conf,
            "noheal_protocol_conformance": n_conf,
            "two_cell": two_cell,
            "backend": jax.default_backend(),
        },
    }


def _two_cell_scenario(cut_step, heal_step):
    """Part B of ``bench_partition`` (docstring there): 2 cells x 2
    ranks, replicated store, per-cell read-only serving heads, a
    deterministic cross-cell partition + heal on a manual step clock."""
    import hetu_tpu as ht
    from hetu_tpu import chaos as chaos_mod
    from hetu_tpu.metrics import fault_counts, reset_faults
    from hetu_tpu.ps.dist_store import DistCacheTable, DistributedStore
    from hetu_tpu.serving import (CellHead, CellMap, InferenceExecutor,
                                  ServingRouter)
    from tools.ps_fsck import fsck

    vocab, dim, n_fields = 32, 4, 4
    cells = CellMap({"west": [0, 1], "east": [2, 3]})
    ports = _free_ports(cells.world)
    endpoints = [("127.0.0.1", p) for p in ports]
    reset_faults()
    stores = [DistributedStore(r, cells.world, endpoints, port=ports[r],
                               rpc_timeout=2.0, rpc_retries=2,
                               connect_timeout=2.0, replication=2)
              for r in range(cells.world)]
    heads = []
    try:
        tid = None
        for s in stores:
            tid = s.init_table(vocab, dim, opt="sgd", lr=0.1,
                               init_scale=0.0)
        stores[0].set_data(tid, np.random.RandomState(42).normal(
            0, 0.01, (vocab, dim)).astype(np.float32))

        def make_head(name, store):
            sparse = ht.placeholder_op(f"ids_{name}", dtype=np.int64)
            cache = DistCacheTable(store, tid, limit=2 * vocab,
                                   policy="lru", read_only=True)
            emb = ht.ps_embedding_lookup_op(cache, sparse, width=dim)
            flat = ht.array_reshape_op(emb, (-1, n_fields * dim))
            w = ht.Variable(f"w_{name}", value=(np.random.RandomState(7)
                            .randn(n_fields * dim, 1) * 0.2
                            ).astype(np.float32))
            prob = ht.sigmoid_op(ht.matmul_op(flat, w))
            iex = InferenceExecutor([prob], seed=0, validate="error",
                                    buckets=(4, 8))
            router = ServingRouter(iex, max_batch=8, max_wait_ms=100.0,
                                   queue_limit=64)
            return CellHead(name, store, router, cache), sparse

        west, west_ids = make_head("west", stores[0])
        east, east_ids = make_head("east", stores[2])
        heads = [west, east]
        # east leaves two shard-1 keys COLD so the partition exercises
        # the local-failover path (shard 1's ring backup, rank 2, lives
        # in east); everything else is warm in both cells
        cold_east = np.asarray([1, 5], np.int64)     # key % 4 == 1
        all_keys = np.arange(vocab, dtype=np.int64)
        west.warm(all_keys)
        east.warm(np.setdiff1d(all_keys, cold_east))

        rng = np.random.RandomState(3)

        def wave(head, node, ids_batch):
            return head.serve_wave([{node: ids} for ids in ids_batch])

        def warm_ids(n, forbid=()):
            pool = np.setdiff1d(all_keys, np.asarray(forbid, np.int64))
            return [rng.choice(pool, n_fields) for _ in range(n)]

        spec = "17:" + cells.partition_spec("west", "east", cut_step,
                                            heal_step)
        inj = chaos_mod.ChaosInjector.from_spec(spec)
        prev = chaos_mod.install(inj)
        try:
            # phase 1 — link up: both cells serve, trainer writes
            _, w1 = wave(west, west_ids, warm_ids(8))
            _, e1 = wave(east, east_ids, warm_ids(8, forbid=cold_east))
            stores[0].push(tid, np.arange(vocab),
                           rng.standard_normal((vocab, dim))
                           .astype(np.float32) * 0.1)
            inj.on_step(cut_step)                    # the link dies
            # phase 2 — partitioned: warm reads keep serving in BOTH
            # cells; east also hits its cold shard-1 keys, forcing a
            # LOCAL failover promotion (new lineage for shard 1)
            _, w2 = wave(west, west_ids, warm_ids(8))
            cold_feed = [np.concatenate((cold_east,
                                         rng.choice(vocab // 2, 2)))]
            _, e2a = wave(east, east_ids, cold_feed)
            _, e2b = wave(east, east_ids,
                          warm_ids(7, forbid=cold_east))
            # cross-cell re-replication QUEUES while the link is down
            d0 = fault_counts().get("ps_re_replicate_deferred", 0)
            east.catch_up()
            deferred = fault_counts().get("ps_re_replicate_deferred",
                                          0) > d0
            inj.on_step(heal_step)                   # the link heals
            # phase 3 — heal: the west trainer's first write through the
            # stale ex-primary is epoch-refused + re-routed (the fence
            # dance); catch-up re-replicates; both cells keep serving
            stores[0].push(tid, np.asarray([1, 5, 9], np.int64),
                           np.ones((3, dim), np.float32) * 0.01)
            east.catch_up()
            west.catch_up()
            _, w3 = wave(west, west_ids, warm_ids(8))
            _, e3 = wave(east, east_ids, warm_ids(8))
        finally:
            chaos_mod.install(prev)
        counters = fault_counts()
        report = fsck(endpoints, n_tables=1, replication=2, retries=2,
                      retry_wait=0.2)
        waves = {"west": [w1, w2, w3], "east": [e1, e2a, e2b, e3]}
        served_through_cut = all(
            w["rejections"] == 0 and w["errors"] == 0
            and w["answered"] == w["admitted"] > 0
            for w in (w2, e2a, e2b))
        ok = (served_through_cut and deferred
              and counters.get("ps_failover_promoted", 0) >= 1
              and counters.get("ps_epoch_refused", 0) >= 1
              and counters.get("ps_demotions", 0) >= 1
              and west.stats["rejections"] == 0
              and east.stats["rejections"] == 0
              and report["ok"]
              and all(len(r) == 1
                      for r in report["serving_ranks"].values()))
        return {
            "ok": ok,
            "cells": {name: cells.ranks(name) for name in cells.cells},
            "partition_spec": spec,
            "served_through_cut": served_through_cut,
            "re_replication_deferred_in_partition": deferred,
            "cell_stats": {h.name: h.stats for h in heads},
            "waves": waves,
            "fsck_ok": report["ok"],
            "fsck_serving_ranks": report["serving_ranks"],
            "fault_counters": counters,
        }
    finally:
        for h in heads:
            try:
                h.close()
            except Exception:
                pass
        for s in stores:
            try:
                s.close()
            except Exception:
                pass


def bench_elastic(steps=10, kill_step=3, rejoin_step=5, dp=4, zero=1,
                  smoke=True):
    """ISSUE 12 acceptance: elastic data-parallel training — kill one of
    dp=4 mid-run, keep training at dp=3 without a restart, grow back on
    rejoin.

    One chaos-driven run (``kill:proc@rank2:step<kill_step>`` on the
    deterministic step clock; the rank rejoins before step
    ``rejoin_step``) against the uninterrupted dp-MATCHED reference (same
    graph, same feeds, same world trajectory via explicit resizes, no
    chaos, no controller).  The artifact records the resize timeline
    (step, dp transition, recovery_ms per resize), restarts=0/resumes=0,
    BITWISE loss parity vs the reference, the compiled-step-cache
    evidence (2 misses for the two world sizes, >= 1 HIT on the
    grow-back — no recompile), the elastic counters, and both resizes as
    spans/instants counted out of the exported Perfetto trace.  Writes
    ``artifacts/elastic_smoke.json``."""
    import gc
    import jax
    import hetu_tpu as ht
    from hetu_tpu import chaos as chaos_mod, metrics as ht_metrics, obs
    from hetu_tpu.graph import step_cache
    from hetu_tpu.parallel.elastic import (ElasticController, LogicalRank,
                                           handles_alive_fn)

    if len(jax.devices()) < dp:
        raise RuntimeError(
            f"bench_elastic needs >= {dp} devices — run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count={dp} (bench.py "
            f"--config elastic sets this for its child automatically)")
    if not (0 < kill_step < rejoin_step <= steps - 2):
        raise ValueError(
            f"need 0 < kill_step < rejoin_step <= steps-2, got "
            f"kill={kill_step} rejoin={rejoin_step} steps={steps}")
    if dp < 3:
        # the scenario kills one rank and keeps training: the controller
        # floors the shrink at min_dp=2, so dp=2 would refuse the resize
        # and the run would fail the acceptance instead of explaining
        raise ValueError(
            f"bench_elastic needs dp >= 3 (kill one of dp, survive at "
            f"dp-1 >= the min_dp=2 floor), got dp={dp}")

    dead_rank = dp - 2
    per_rank = 4        # per-replica batch rows: global batch = dp * 4

    def build():
        rng = np.random.RandomState(0)
        x = ht.placeholder_op("x")
        y_ = ht.placeholder_op("y_")
        w1 = ht.Variable("w1",
                         value=rng.randn(16, 32).astype(np.float32) * 0.2)
        b1 = ht.Variable("b1", value=np.zeros(32, np.float32))
        w2 = ht.Variable("w2",
                         value=rng.randn(32, 8).astype(np.float32) * 0.2)
        h = ht.relu_op(ht.linear_op(x, w1, b1))
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(h, w2), y_), [0])
        opt = ht.optim.AdamOptimizer(0.01)
        ex = ht.Executor(
            {"train": [loss, opt.minimize(loss)]}, seed=0,
            dist_strategy=ht.dist.DataParallel(num_devices=dp), zero=zero)
        return x, y_, ex

    def batch(step, world):
        rng = np.random.RandomState(4242 + step)
        n = per_rank * world
        xv = rng.randn(n, 16).astype(np.float32)
        yv = np.eye(8, dtype=np.float32)[rng.randint(0, 8, n)]
        return xv, yv

    # the world trajectory both runs follow: shrink fires at the poll
    # after the kill (chaos on_step reports post-step counters, so
    # kill_step means "kill after the step that leaves the counter
    # there"), grow at the poll after the rejoin
    worlds = [dp if (i < kill_step or i >= rejoin_step) else dp - 1
              for i in range(steps)]

    step_cache.clear()
    gc.collect()
    ht_metrics.reset_all()

    # ---- elastic run: chaos kill + controller-driven resize ----------
    handles = [LogicalRank(r) for r in range(dp)]
    inj = chaos_mod.ChaosInjector.from_spec(
        f"7:kill:proc@rank{dead_rank}:step{kill_step}")
    for h in handles:
        inj.register_proc(h.rank, h)
    prev = chaos_mod.install(inj)
    obs.clear_trace()
    obs.enable(True)
    t_wall0 = time.perf_counter()
    try:
        x, y_, ex = build()
        ctl = ElasticController(ex, world=dp,
                                alive_fn=handles_alive_fn(handles),
                                min_dp=2)
        losses, seen_worlds = [], []
        for i in range(steps):
            xv, yv = batch(i, ctl.dp)
            out = ex.run("train", feed_dict={x: xv, y_: yv})
            losses.append(np.float32(out[0].asnumpy()))
            seen_worlds.append(ctl.dp)
            if i == rejoin_step - 1:
                handles[dead_rank].rejoin()
            ctl.poll()
        trace_evs = obs.trace_events()
    finally:
        obs.enable(False)
        obs.clear_trace()
        chaos_mod.install(prev)
    wall_s = time.perf_counter() - t_wall0
    elastic_counters = dict(ht_metrics.elastic_counts())
    fault_counters = dict(ht_metrics.fault_counts())
    sc = dict(ht_metrics.step_cache_counts())
    timeline = list(ctl.events)
    # drop BOTH references to the elastic executor (ctl.ex pins it) so
    # the reference run below doesn't coexist with its device buffers
    del ex, ctl
    gc.collect()

    resize_spans = [e for e in trace_evs if e.get("ph") == "X"
                    and e["name"] == "elastic.resize"]
    shrink_events = [e for e in trace_evs if e.get("ph") == "i"
                     and e["name"] == "elastic:shrink"]
    grow_events = [e for e in trace_evs if e.get("ph") == "i"
                   and e["name"] == "elastic:grow"]

    # ---- dp-matched reference: same trajectory, zero chaos -----------
    ht_metrics.reset_elastic_counts()
    x, y_, ex2 = build()
    ref_losses, active = [], list(range(dp))
    for i, w in enumerate(worlds):
        if w != len(active):
            active = [r for r in range(dp) if r != dead_rank] \
                if w == dp - 1 else list(range(dp))
            ex2.resize_world(active)
        xv, yv = batch(i, w)
        out = ex2.run("train", feed_dict={x: xv, y_: yv})
        ref_losses.append(np.float32(out[0].asnumpy()))
    clean_elastic = dict(ht_metrics.elastic_counts())
    del ex2
    step_cache.clear()
    gc.collect()

    loss_bits = [v.tobytes().hex() for v in losses]
    ref_bits = [v.tobytes().hex() for v in ref_losses]
    parity = loss_bits == ref_bits
    recovery_ms = max((e["recovery_ms"] for e in timeline), default=None)
    kinds = [e["kind"] for e in timeline]
    ok = (parity and seen_worlds == worlds
          and kinds == ["shrink", "grow"]
          and fault_counters.get("chaos_kill_proc") == 1
          and fault_counters.get("supervisor_restart", 0) == 0
          and fault_counters.get("resume", 0) == 0
          and sc.get("step_cache_miss") == 2
          and sc.get("step_cache_hit", 0) >= 1
          and len(resize_spans) == 2
          and len(shrink_events) >= 1 and len(grow_events) >= 1)

    res = {
        "metric": "elastic_resize_recovery_ms",
        "value": recovery_ms,
        "unit": "ms",
        # 1.0 = the elastic trajectory is bitwise the dp-matched
        # uninterrupted reference (the continuous-loss-trajectory gate)
        "vs_baseline": 1.0 if parity else 0.0,
        "extra": {
            "baseline_def": "value = slowest resize (detection poll -> "
                            "resized executor); vs_baseline 1.0 = losses "
                            "bitwise equal to an uninterrupted dp-matched "
                            "reference run (no restart, no checkpoint "
                            "resume anywhere)",
            **_provenance({"dp": dp, "steps": steps, "zero": zero,
                           "kill_step": kill_step,
                           "rejoin_step": rejoin_step,
                           "per_rank_batch": per_rank}),
            "world_trajectory": seen_worlds,
            "resize_timeline": timeline,
            "loss_bits": loss_bits,
            "final_loss": float(losses[-1]),
            "loss_bitwise_equal_vs_reference": parity,
            "restarts": int(fault_counters.get("supervisor_restart", 0)),
            "resumes": int(fault_counters.get("resume", 0)),
            "elastic_counters": elastic_counters,
            "fault_counters": fault_counters,
            "clean_run_elastic_counters": clean_elastic,
            "step_cache": sc,
            "trace": {"resize_spans": len(resize_spans),
                      "shrink_events": len(shrink_events),
                      "grow_events": len(grow_events)},
            "wall_s": round(wall_s, 2),
            "backend": jax.default_backend(),
            "smoke": bool(smoke),
        },
    }
    if not ok:
        res["error"] = (
            "elastic acceptance failed: "
            + "; ".join(filter(None, [
                None if parity else "loss NOT bitwise vs reference",
                None if seen_worlds == worlds
                else f"world trajectory {seen_worlds} != {worlds}",
                None if kinds == ["shrink", "grow"]
                else f"resize kinds {kinds}",
                None if sc.get("step_cache_hit", 0) >= 1
                else f"no step-cache hit on grow-back ({sc})",
                None if len(resize_spans) == 2
                else f"{len(resize_spans)} resize spans in trace",
            ])))
    try:
        from artifact_schema import provenance as _prov
        out = {**res, **_prov({"dp": dp, "steps": steps, "zero": zero,
                               "kill_step": kill_step,
                               "rejoin_step": rejoin_step})}
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "artifacts", "elastic_smoke.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        os.replace(path + ".tmp", path)
    except Exception:
        pass    # the printed result is the bench contract; file is extra
    return res


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="bert",
                   choices=["bert", "resnet18", "wdl", "moe", "attn",
                            "chaos", "failover", "emb", "zero", "serve",
                            "decode", "fleet", "partition", "overhead",
                            "trace", "elastic", "remat"])
    p.add_argument("--remat", default=None,
                   choices=["off", "dots", "full", "offload", "auto"],
                   help="bert: selective-remat policy for the flagship "
                        "measurement (parallel/remat.py).  The full "
                        "off/dots/full/auto sweep with per-cell "
                        "checkpointed resume is --config remat "
                        "(artifacts/remat_bench.json)")
    p.add_argument("--dp", type=int, default=4,
                   help="zero/elastic: data-parallel mesh size (the child "
                        "forces a CPU host-device mesh of >= this; "
                        "elastic needs >= 3 — kill one, survive at dp-1)")
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--seq-len", type=int, default=None,
                   help="bert only: sequence length (default 512 — the "
                        "flash-gated masked flagship config)")
    p.add_argument("--wdl-embed", default="lru",
                   choices=["lru", "lfu", "lfuopt", "dense"],
                   help="wdl embedding mode: HET cache policies (the "
                        "BASELINE config-4 headline) or 'dense' (plain "
                        "device embedding — the same-semantics torch "
                        "comparison)")
    p.add_argument("--emb-policy", default=None,
                   choices=["direct", "lru", "lfu"],
                   help="wdl only: route the CTR embedding through the "
                        "vectorized HET cache path (direct = PS store "
                        "without a cache; lru/lfu = vectorized "
                        "DistCacheTable) — overrides --wdl-embed")
    p.add_argument("--emb-device", default=None,
                   choices=["host", "device"],
                   help="wdl: where the HET cache's row slab lives "
                        "(default host).  device = ISSUE 11 device-"
                        "resident slab: on-device slot gather, "
                        "overlapped miss pulls, Pallas grad scatter-add; "
                        "the artifact extra records cache_mode, hit "
                        "rate, emb_pallas_fallback_reason and the same-"
                        "trace host-cache comparison (vs_host_cache)")
    p.add_argument("--smoke", action="store_true",
                   help="emb: 10^5-row smoke config (seconds, CPU) "
                        "instead of the 10^7x64 scale run; failover: "
                        "the CI-sized double-kill run; serve: the "
                        "300-request CI config (artifacts/"
                        "serve_smoke.json); partition: the CI-sized "
                        "partition+heal run (artifacts/"
                        "partition_smoke.json); overhead: the CI parity/"
                        "plan-cache gate (no artifact write); elastic: "
                        "the chaos-driven dp=4 kill+rejoin run "
                        "(artifacts/elastic_smoke.json); decode: the "
                        "16-request stream with all gates but the strict "
                        "perf margin (no artifact write)")
    p.add_argument("--steps", type=int, default=None,
                   help=f"timed steps (default {DEFAULT_STEPS}; smaller on "
                        "the CPU fallback unless given explicitly)")
    args = p.parse_args()
    if os.environ.get(CHILD_ENV_FLAG):
        _child_main(args)
    elif args.config in ("chaos", "failover", "emb", "zero", "serve",
                         "decode", "fleet", "partition", "overhead",
                         "trace", "elastic", "remat"):
        # host-side metrics: no TPU probe loop (backend-agnostic), but
        # still a budgeted child so a wedged backend import can't hang
        # the harness
        env = dict(os.environ, **{CHILD_ENV_FLAG: "1",
                                  "_HETU_BENCH_FORCE_CPU": "1"})
        if args.config in ("zero", "elastic", "remat"):
            # these acceptance runs measure a dp>=4 CPU mesh (remat's
            # overlap-audit gate compiles the dp=4 zero=3 config): the
            # device count flag must land before the child's backend
            # init
            flags = env.get("XLA_FLAGS", "")
            if "host_platform_device_count" not in flags:
                n = max(8, args.dp)
                env["XLA_FLAGS"] = (
                    f"{flags} "
                    f"--xla_force_host_platform_device_count={n}").strip()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                env=env, capture_output=True, text=True,
                timeout=min(CHILD_TIMEOUT_S, TOTAL_BUDGET_S))
            parsed = _parse_child_json(proc.stdout, 0)
            if parsed is None:
                parsed = _error_result(
                    args, f"host-side bench rc={proc.returncode} "
                          f"stderr: {proc.stderr[-1500:]}")
        except subprocess.TimeoutExpired:
            parsed = _error_result(args,
                                   "host-side bench exceeded wall clock")
        print(json.dumps(parsed))
    else:
        _parent_main(args)
