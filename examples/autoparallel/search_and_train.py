"""Auto-parallel workflow: profile -> search -> train.

Reference: the Galvatron workflow (``tools/Galvatron/README.md:15-100`` —
profile hardware, search a layerwise hybrid strategy, train with the
emitted config).  Here the three phases are:

1. profile  — ``calibrate_hardware()`` measures matmul FLOP/s + collective
              bandwidths on THIS machine (or loads the committed
              ``artifacts/tpu_calibration.json``);
2. search   — layerwise DP over (pp, tp, dp, cp, fsdp) candidates under
              the memory budget ('cp' is net-new vs Galvatron: sequence
              sharding for long-context, small-batch workloads);
3. train    — the plan's mesh axes + sharding directives drive a real
              Executor run.

    python examples/autoparallel/search_and_train.py               # BERT-ish
    python examples/autoparallel/search_and_train.py --long-context  # cp demo
    python examples/autoparallel/search_and_train.py --devices 16 --dry-run
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

if "--cpu" in sys.argv:  # must run before backend init (train_lm.py pattern)
    if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8"
                                   ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import hetu_tpu as ht  # noqa: E402
from hetu_tpu.autoparallel.cost_model import (  # noqa: E402
    HardwareSpec, model_layer_specs)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--hidden", type=int, default=768)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--vocab", type=int, default=30522)
    p.add_argument("--mem-gb", type=float, default=16.0)
    p.add_argument("--microbatches", type=int, default=4)
    p.add_argument("--long-context", action="store_true",
                   help="batch-1 256k-token workload: demonstrates the cp "
                        "axis (dp capped at the batch)")
    p.add_argument("--calibrate", action="store_true",
                   help="measure hardware live instead of artifact/defaults")
    p.add_argument("--dry-run", action="store_true",
                   help="search + describe only, no training step")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    # -- 1. profile --------------------------------------------------------
    if args.calibrate:
        hw = HardwareSpec.measure()
    else:
        hw = HardwareSpec.from_artifact() or HardwareSpec()
    hw.mem_bytes = args.mem_gb * 1e9
    print(f"hardware: {hw.flops/1e12:.0f} TF/s, "
          f"{hw.mem_bytes/1e9:.0f} GB, ici {hw.ici_bw/1e9:.1f} GB/s")

    # -- 2. search ---------------------------------------------------------
    if args.long_context:
        plan, _ = ht.autoparallel.long_context_cp_plan(
            args.devices, hw=hw, layers=args.layers, hidden=args.hidden)
    else:
        specs = model_layer_specs(args.layers, args.hidden, args.seq,
                                  args.batch, args.vocab)
        plan = ht.autoparallel.search(specs, n_devices=args.devices, hw=hw,
                                      microbatches=args.microbatches,
                                      uniform=True)
    print(plan.describe())
    if args.dry_run:
        return 0

    # -- 3. train (tiny stand-in model on the PLANNED mesh) ----------------
    import jax
    axes = plan.mesh_axes()
    n_needed = 1
    for v in axes.values():
        n_needed *= v
    if len(jax.devices()) < n_needed:
        print(f"(only {len(jax.devices())} devices visible; "
              f"skipping the training step — plan needs {n_needed})")
        return 0
    axes.setdefault("dp", 1)
    if args.long_context:
        from hetu_tpu.models.t5 import T5Config, t5_seq2seq_graph
        from hetu_tpu.models import synthetic_seq2seq_batch
        cfg = T5Config.tiny(batch_size=2 * axes["dp"], src_len=32,
                            tgt_len=32, num_heads=4, dropout_rate=0.0,
                            context_parallel="ring")
        feeds, loss, _ = t5_seq2seq_graph(cfg)
        src, tgt_in, labels = synthetic_seq2seq_batch(cfg)
        fd_vals = {"input_ids": src, "decoder_input_ids": tgt_in,
                   "labels": labels}
    else:
        from hetu_tpu.models.bert import (BertConfig, bert_pretrain_graph,
                                          synthetic_mlm_batch)
        cfg = BertConfig.tiny(batch_size=4 * axes.get("dp", 1), seq_len=32)
        feeds, loss, _ = bert_pretrain_graph(cfg)
        ids, tt, labels, attn = synthetic_mlm_batch(cfg)
        fd_vals = {"input_ids": ids, "token_type_ids": tt,
                   "masked_lm_labels": labels, "attention_mask": attn}
    opt_op = ht.optim.AdamOptimizer(1e-3).minimize(loss)
    if max(s.tp for s in plan.strategies) == 1 \
            and max(s.pp for s in plan.strategies) == 1:
        # the executor-integrated path (ISSUE 15): the plan drives mesh,
        # strategy and ZeRO routing, and is lint-validated before compile
        ex = ht.Executor({"train": [loss, opt_op]}, seed=0, plan=plan)
    else:
        # tp/pp plans need per-layer bindings this stand-in model does
        # not expose — run on the plan's mesh with generic specs
        mesh = ht.make_mesh(axes, jax.devices()[:n_needed])
        ex = ht.Executor({"train": [loss, opt_op]}, seed=0, mesh=mesh,
                         dist_strategy=ht.dist.ModelParallel(axes))
    fd = {feeds[k]: v for k, v in fd_vals.items()}
    for i in range(3):
        out = ex.run("train", feed_dict=fd)
        print(f"step {i}: loss {float(out[0].asnumpy()):.4f}")
    print("trained on the searched mesh:",
          dict(ex.mesh.shape) if ex.mesh is not None else None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
