"""CNN training driver — port of the reference ``examples/cnn/main.py`` flow
to hetu_tpu (same flags, same Dataloader/Executor usage)."""
import argparse
import logging
import os
import sys
import time

import numpy as np

# HETU_PLATFORM=cpu forces the CPU backend (numerics runs while the TPU
# tunnel is wedged); must land before the first backend use.  The env var
# JAX_PLATFORMS alone cannot do this: site customization pins it earlier.
import jax  # noqa: E402

if os.environ.get("HETU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["HETU_PLATFORM"])
elif "--cpu" in sys.argv:   # same flag as the rest of the cookbook
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import hetu_tpu as ht  # noqa: E402
import models  # noqa: E402

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger(__name__)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (= HETU_PLATFORM=cpu)")
    parser.add_argument("--model", type=str, required=True)
    parser.add_argument("--dataset", type=str, default="cifar10")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--learning-rate", type=float, default=0.1)
    parser.add_argument("--opt", type=str, default="sgd")
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--validate", action="store_true")
    parser.add_argument("--timing", action="store_true")
    parser.add_argument("--comm-mode", default=None,
                        help="None (single device) or allreduce/ps/hybrid (DP)")
    parser.add_argument("--json-out", default=None,
                        help="write final metrics as JSON (artifact path)")
    args = parser.parse_args()

    model = getattr(models, args.model.lower())
    opt = {
        "sgd": lambda: ht.optim.SGDOptimizer(args.learning_rate),
        "momentum": lambda: ht.optim.MomentumOptimizer(args.learning_rate),
        "nesterov": lambda: ht.optim.MomentumOptimizer(args.learning_rate,
                                                       nesterov=True),
        "adagrad": lambda: ht.optim.AdaGradOptimizer(
            args.learning_rate, initial_accumulator_value=0.1),
        "adam": lambda: ht.optim.AdamOptimizer(args.learning_rate),
    }[args.opt.lower()]()

    if args.dataset == "mnist":
        (tx, ty), (vx, vy), _ = ht.data.mnist()
        num_class = 10
    else:
        num_class = {"cifar10": 10, "cifar100": 100}[args.dataset]
        tx, ty, vx, vy = ht.data.normalize_cifar(num_class)
        if args.model == "mlp":
            tx, vx = tx.reshape(len(tx), -1), vx.reshape(len(vx), -1)

    x = ht.dataloader_op([ht.Dataloader(tx, args.batch_size, "train"),
                          ht.Dataloader(vx, args.batch_size, "validate")])
    y_ = ht.dataloader_op([ht.Dataloader(ty, args.batch_size, "train"),
                           ht.Dataloader(vy, args.batch_size, "validate")])
    loss, y = model(x, y_, num_class) if args.dataset == "cifar100" \
        else model(x, y_)
    train_op = opt.minimize(loss)

    eval_nodes = {"train": [loss, y, y_, train_op], "validate": [loss, y, y_]}
    strategy = ht.dist.DataParallel(args.comm_mode) if args.comm_mode else None
    executor = ht.Executor(eval_nodes, dist_strategy=strategy)

    n_train = executor.get_batch_num("train")
    n_valid = executor.get_batch_num("validate")
    logger.info("training %s on hetu_tpu (%s)", args.model,
                "DP" if strategy else "single-device")
    history = []
    for epoch in range(args.num_epochs):
        t0 = time.time()
        tl = []
        for _ in range(n_train):
            lv, *_ = executor.run("train")
            tl.append(float(lv.asnumpy()))
        entry = {"epoch": epoch, "train_loss": round(float(np.mean(tl)), 4)}
        msg = f"epoch {epoch}: train_loss={entry['train_loss']:.4f}"
        if args.validate:
            accs = []
            for _ in range(n_valid):
                _, pred, yv = executor.run("validate")
                accs.append(ht.metrics.accuracy(pred.asnumpy(), yv.asnumpy()))
            entry["val_acc"] = round(float(np.mean(accs)), 4)
            msg += f" val_acc={entry['val_acc']:.4f}"
        if args.timing:
            msg += f" ({time.time() - t0:.2f}s)"
        history.append(entry)
        logger.info(msg)
    if args.json_out:
        import json
        out = {"model": args.model, "dataset": args.dataset,
               "batch_size": args.batch_size, "opt": args.opt,
               "learning_rate": args.learning_rate,
               "epochs": args.num_epochs,
               "data_dir": os.environ.get("HETU_DATA_DIR"),
               "history": history, "final": history[-1] if history else {}}
        with open(args.json_out, "w") as f:
            json.dump(out, f, indent=1)
        logger.info("wrote %s", args.json_out)


if __name__ == "__main__":
    main()
