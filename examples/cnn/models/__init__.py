from .mlp import mlp
from .logreg import logreg
from .cnn import cnn_3_layers
from .lenet import lenet
from .alexnet import alexnet
from .vgg import vgg, vgg16, vgg19
from .resnet import resnet, resnet18, resnet34
from .rnn import rnn
from .lstm import lstm
