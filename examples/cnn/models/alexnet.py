import hetu_tpu as ht
from .common import conv2d, bn, fc, ce_loss


def alexnet(x, y_, num_class=10):
    """CIFAR-scale AlexNet (reference examples/cnn/models/AlexNet.py)."""
    x = bn(conv2d(x, 3, 64, 5, 1, 2, "a1"), 64, "a1bn", relu=True)
    x = ht.max_pool2d_op(x, 2, 2, 0, 2)
    x = bn(conv2d(x, 64, 192, 3, 1, 1, "a2"), 192, "a2bn", relu=True)
    x = ht.max_pool2d_op(x, 2, 2, 0, 2)
    x = ht.relu_op(conv2d(x, 192, 384, 3, 1, 1, "a3"))
    x = ht.relu_op(conv2d(x, 384, 256, 3, 1, 1, "a4"))
    x = ht.relu_op(conv2d(x, 256, 256, 3, 1, 1, "a5"))
    x = ht.max_pool2d_op(x, 2, 2, 0, 2)
    x = ht.array_reshape_op(x, output_shape=(-1, 256 * 4 * 4))
    x = ht.dropout_op(fc(x, (256 * 4 * 4, 1024), "f1", relu=True), 0.5)
    x = ht.dropout_op(fc(x, (1024, 512), "f2", relu=True), 0.5)
    logits = fc(x, (512, num_class), "f3")
    return ce_loss(logits, y_)
