import hetu_tpu as ht
from .common import conv2d, fc, ce_loss


def cnn_3_layers(x, y_, num_class=10):
    """3-conv CNN on 28x28 inputs (reference examples/cnn/models/CNN.py)."""
    x = ht.array_reshape_op(x, output_shape=(-1, 1, 28, 28))
    x = ht.relu_op(conv2d(x, 1, 32, 5, 1, 2, "c1"))
    x = ht.relu_op(conv2d(x, 32, 64, 5, 2, 2, "c2"))
    x = ht.relu_op(conv2d(x, 64, 64, 5, 2, 2, "c3"))
    x = ht.array_reshape_op(x, output_shape=(-1, 7 * 7 * 64))
    logits = fc(x, (7 * 7 * 64, num_class), "fc")
    return ce_loss(logits, y_)
