"""Shared graph-building helpers for the CNN model zoo (parity with the
reference examples/cnn/models helper style)."""
import hetu_tpu as ht
from hetu_tpu import initializers as init


def conv2d(x, in_ch, out_ch, kernel_size=3, stride=1, padding=1, name="conv",
           data_format="NCHW"):
    w = init.he_normal(shape=(out_ch, in_ch, kernel_size, kernel_size),
                       name=name + "_weight")
    return ht.conv2d_op(x, w, stride=stride, padding=padding,
                        data_format=data_format)


def bn(x, ch, name, relu=False, data_format="NCHW"):
    scale = init.ones(shape=(ch,), name=name + "_scale")
    bias = init.zeros(shape=(ch,), name=name + "_bias")
    x = ht.batch_normalization_op(x, scale, bias, momentum=0.9, eps=1e-5,
                                  data_format=data_format)
    return ht.relu_op(x) if relu else x


def fc(x, shape, name, relu=False):
    w = init.he_normal(shape=shape, name=name + "_weight")
    b = init.zeros(shape=shape[-1:], name=name + "_bias")
    x = ht.linear_op(x, w, b)
    return ht.relu_op(x) if relu else x


def ce_loss(logits, y_):
    loss = ht.softmaxcrossentropy_op(logits, y_)
    return ht.reduce_mean_op(loss, [0]), ht.softmax_op(logits)
