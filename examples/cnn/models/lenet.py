import hetu_tpu as ht
from .common import conv2d, fc, ce_loss


def lenet(x, y_, num_class=10):
    """LeNet-5 (reference examples/cnn/models/LeNet.py)."""
    x = ht.array_reshape_op(x, output_shape=(-1, 1, 28, 28))
    x = ht.relu_op(conv2d(x, 1, 6, 5, 1, 2, "l1"))
    x = ht.max_pool2d_op(x, 2, 2, 0, 2)
    x = ht.relu_op(conv2d(x, 6, 16, 5, 1, 0, "l2"))
    x = ht.max_pool2d_op(x, 2, 2, 0, 2)
    x = ht.array_reshape_op(x, output_shape=(-1, 16 * 5 * 5))
    x = fc(x, (16 * 5 * 5, 120), "f1", relu=True)
    x = fc(x, (120, 84), "f2", relu=True)
    logits = fc(x, (84, num_class), "f3")
    return ce_loss(logits, y_)
