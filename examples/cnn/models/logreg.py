from .common import fc, ce_loss


def logreg(x, y_, num_class=10):
    """Logistic regression (reference examples/cnn/models/LogReg.py)."""
    logits = fc(x, (784, num_class), "logreg")
    loss, y = ce_loss(logits, y_)
    return loss, y
