import hetu_tpu as ht
from hetu_tpu import initializers as init
from .common import fc, ce_loss


def lstm(x, y_, num_class=10, hidden=128, timesteps=28, dim=28):
    """LSTM over row-sliced MNIST (reference examples/cnn/models/LSTM.py);
    the 4 gates are one fused (dim, 4*hidden) matmul — MXU-friendly."""
    wx = init.xavier_uniform(shape=(dim, 4 * hidden), name="lstm_wx")
    wh = init.xavier_uniform(shape=(hidden, 4 * hidden), name="lstm_wh")
    b = init.zeros(shape=(4 * hidden,), name="lstm_b")
    h = c = None
    for t in range(timesteps):
        xt = ht.slice_op(x, begin=(0, t * dim), size=(-1, dim))
        z = ht.linear_op(xt, wx, b)
        if h is not None:
            z = z + ht.matmul_op(h, wh)
        i = ht.sigmoid_op(ht.slice_op(z, begin=(0, 0), size=(-1, hidden)))
        f = ht.sigmoid_op(ht.slice_op(z, begin=(0, hidden), size=(-1, hidden)))
        o = ht.sigmoid_op(ht.slice_op(z, begin=(0, 2 * hidden), size=(-1, hidden)))
        g = ht.tanh_op(ht.slice_op(z, begin=(0, 3 * hidden), size=(-1, hidden)))
        c = i * g if c is None else f * c + i * g
        h = o * ht.tanh_op(c)
    logits = fc(h, (hidden, num_class), "lstm_head")
    return ce_loss(logits, y_)
