import hetu_tpu as ht
from .common import fc, ce_loss


def mlp(x, y_, num_class=10, hidden=256):
    """3-layer MLP (reference examples/cnn/models/MLP.py)."""
    x = fc(x, (784, hidden), "mlp_fc1", relu=True)
    x = fc(x, (hidden, hidden), "mlp_fc2", relu=True)
    logits = fc(x, (hidden, num_class), "mlp_fc3")
    loss, y = ce_loss(logits, y_)
    return loss, y
