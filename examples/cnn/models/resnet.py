import hetu_tpu as ht
from .common import conv2d, bn, fc, ce_loss


def _basic_block(x, in_ch, out_ch, stride, name):
    shortcut = x
    x = bn(conv2d(x, in_ch, out_ch, 3, stride, 1, name + "_c1"), out_ch,
           name + "_bn1", relu=True)
    x = bn(conv2d(x, out_ch, out_ch, 3, 1, 1, name + "_c2"), out_ch,
           name + "_bn2")
    if in_ch != out_ch or stride > 1:
        shortcut = bn(conv2d(shortcut, in_ch, out_ch, 1, stride, 0,
                             name + "_cs"), out_ch, name + "_bns")
    return ht.relu_op(x + shortcut)


_LAYERS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3)}


def resnet(x, y_, num_layers=18, num_class=10):
    """ResNet-18/34, CIFAR stem (reference examples/cnn/models/ResNet.py)."""
    reps = _LAYERS[num_layers]
    x = bn(conv2d(x, 3, 64, 3, 1, 1, "stem"), 64, "stem_bn", relu=True)
    in_ch = 64
    for stage, (rep, ch) in enumerate(zip(reps, (64, 128, 256, 512))):
        for r in range(rep):
            stride = 2 if (stage > 0 and r == 0) else 1
            x = _basic_block(x, in_ch, ch, stride, f"s{stage}b{r}")
            in_ch = ch
    x = ht.avg_pool2d_op(x, 4, 4, 0, 4)
    x = ht.array_reshape_op(x, output_shape=(-1, 512))
    logits = fc(x, (512, num_class), "head")
    return ce_loss(logits, y_)


def resnet18(x, y_, num_class=10):
    return resnet(x, y_, 18, num_class)


def resnet34(x, y_, num_class=10):
    return resnet(x, y_, 34, num_class)
