import hetu_tpu as ht
from .common import conv2d, bn, fc, ce_loss


def _basic_block(x, in_ch, out_ch, stride, name, df):
    shortcut = x
    x = bn(conv2d(x, in_ch, out_ch, 3, stride, 1, name + "_c1",
                  data_format=df), out_ch, name + "_bn1", relu=True,
           data_format=df)
    x = bn(conv2d(x, out_ch, out_ch, 3, 1, 1, name + "_c2",
                  data_format=df), out_ch, name + "_bn2", data_format=df)
    if in_ch != out_ch or stride > 1:
        shortcut = bn(conv2d(shortcut, in_ch, out_ch, 1, stride, 0,
                             name + "_cs", data_format=df), out_ch,
                      name + "_bns", data_format=df)
    return ht.relu_op(x + shortcut)


_LAYERS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3)}


def resnet(x, y_, num_layers=18, num_class=10, data_format="NCHW"):
    """ResNet-18/34, CIFAR stem (reference examples/cnn/models/ResNet.py).

    ``data_format``: the feed stays NCHW (reference/torch convention);
    "NHWC" transposes ONCE at the stem and keeps activations channels-last
    through the network — the layout the TPU wants (C on the 128-lane
    axis).  MEASURED per backend (artifacts/resnet_cpu_root_cause.json):
    on XLA-CPU channels-last is 1.5x SLOWER in composition (its NCHW
    pipeline already relayouts internally where profitable), so NCHW
    stays the default; bench.py picks the layout per backend.
    """
    df = data_format
    if df == "NHWC":
        x = ht.transpose_op(x, perm=(0, 2, 3, 1))
    reps = _LAYERS[num_layers]
    x = bn(conv2d(x, 3, 64, 3, 1, 1, "stem", data_format=df), 64,
           "stem_bn", relu=True, data_format=df)
    in_ch = 64
    for stage, (rep, ch) in enumerate(zip(reps, (64, 128, 256, 512))):
        for r in range(rep):
            stride = 2 if (stage > 0 and r == 0) else 1
            x = _basic_block(x, in_ch, ch, stride, f"s{stage}b{r}", df)
            in_ch = ch
    x = ht.avg_pool2d_op(x, 4, 4, 0, 4, data_format=df)
    x = ht.array_reshape_op(x, output_shape=(-1, 512))
    logits = fc(x, (512, num_class), "head")
    return ce_loss(logits, y_)


def resnet18(x, y_, num_class=10, data_format="NCHW"):
    return resnet(x, y_, 18, num_class, data_format)


def resnet34(x, y_, num_class=10, data_format="NCHW"):
    return resnet(x, y_, 34, num_class, data_format)
