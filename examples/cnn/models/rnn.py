import hetu_tpu as ht
from hetu_tpu import initializers as init
from .common import fc, ce_loss


def rnn(x, y_, num_class=10, hidden=128, timesteps=28, dim=28):
    """Elman RNN over row-sliced MNIST (reference examples/cnn/models/RNN.py).
    The reference unrolls with per-step slice ops; we do the same at graph
    level — XLA fuses the unrolled steps."""
    wx = init.xavier_uniform(shape=(dim, hidden), name="rnn_wx")
    wh = init.xavier_uniform(shape=(hidden, hidden), name="rnn_wh")
    b = init.zeros(shape=(hidden,), name="rnn_b")
    h = None
    for t in range(timesteps):
        xt = ht.slice_op(x, begin=(0, t * dim), size=(-1, dim))
        z = ht.linear_op(xt, wx, b)
        if h is not None:
            z = z + ht.matmul_op(h, wh)
        h = ht.tanh_op(z)
    logits = fc(h, (hidden, num_class), "rnn_head")
    return ce_loss(logits, y_)
