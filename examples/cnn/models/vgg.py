import hetu_tpu as ht
from .common import conv2d, bn, fc, ce_loss

_CFG = {16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}


def vgg(x, y_, num_layers, num_class=10):
    """VGG-16/19 with BN, CIFAR head (reference examples/cnn/models/VGG.py)."""
    reps = _CFG[num_layers]
    chans = (64, 128, 256, 512, 512)
    in_ch = 3
    for b, (rep, ch) in enumerate(zip(reps, chans)):
        for r in range(rep):
            x = bn(conv2d(x, in_ch, ch, 3, 1, 1, f"v{b}_{r}"), ch,
                   f"v{b}_{r}bn", relu=True)
            in_ch = ch
        x = ht.max_pool2d_op(x, 2, 2, 0, 2)
    x = ht.array_reshape_op(x, output_shape=(-1, 512))
    x = fc(x, (512, 4096), "f1", relu=True)
    x = fc(x, (4096, 4096), "f2", relu=True)
    logits = fc(x, (4096, num_class), "f3")
    return ce_loss(logits, y_)


def vgg16(x, y_, num_class=10):
    return vgg(x, y_, 16, num_class)


def vgg19(x, y_, num_class=10):
    return vgg(x, y_, 19, num_class)
