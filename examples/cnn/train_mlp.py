"""MLP on MNIST — reference examples/cnn/main.py flow on hetu_tpu."""
import os, sys, time
import numpy as np
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

if "--cpu" in sys.argv or os.environ.get("HETU_PLATFORM") == "cpu":
    # must land before the first backend use (cookbook-wide flag)
    import jax
    jax.config.update("jax_platforms", "cpu")

import hetu_tpu as ht

datasets = ht.data.mnist()
(train_x, train_y), (valid_x, valid_y), _ = datasets
batch = 128

x = ht.dataloader_op([ht.Dataloader(train_x, batch, 'train'),
                      ht.Dataloader(valid_x, batch, 'validate')])
y_ = ht.dataloader_op([ht.Dataloader(train_y, batch, 'train'),
                       ht.Dataloader(valid_y, batch, 'validate')])

from hetu_tpu.layers import Linear, Sequence
model = Sequence(
    Linear(784, 256, activation='relu', name='fc1'),
    Linear(256, 10, name='fc2'),
)
logits = model(x)
loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_), [0])
opt = ht.optim.MomentumOptimizer(learning_rate=0.05, momentum=0.9)
train_op = opt.minimize(loss)

executor = ht.Executor({'train': [loss, logits, y_, train_op],
                        'validate': [loss, logits, y_]})
n_train = executor.get_batch_num('train')
n_valid = executor.get_batch_num('validate')
print(f"devices={__import__('jax').devices()} train_batches={n_train}")

for epoch in range(3):
    t0 = time.time()
    tl = []
    for _ in range(n_train):
        lv, pred, yv, _ = executor.run('train')
        tl.append(float(lv.asnumpy()))
    accs, vls = [], []
    for _ in range(n_valid):
        lv, pred, yv = executor.run('validate')
        vls.append(float(lv.asnumpy()))
        accs.append(ht.metrics.accuracy(pred.asnumpy(), yv.asnumpy()))
    print(f"epoch {epoch}: train_loss={np.mean(tl):.4f} val_loss={np.mean(vls):.4f} "
          f"val_acc={np.mean(accs):.4f} ({time.time()-t0:.2f}s)")
