"""PyTorch comparison baselines — the reference's perf-comparison
methodology (every example family ships TF/PyTorch/Horovod scripts with no
committed numbers, e.g. ``examples/cnn/tf_main.py:1``,
``examples/embedding/ctr/run_tf_horovod.py:1``).  Each config mirrors the
matching ``bench.py`` workload exactly (model dims, batch, steps) and prints
ONE JSON line in the same schema, so ``tools/compare_frameworks.py`` can put
the two frameworks side by side on identical work.

CPU-only torch is what this image ships; on-TPU comparisons use the
reference's published claims (BASELINE.md) instead.

Usage: python examples/compare/torch_baselines.py --config {bert,resnet18,wdl,moe}
"""
import argparse
import json
import time

import numpy as np
import torch
import torch.nn as nn


def _timed(step, steps, warmup):
    for _ in range(warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(steps):
        step()
    return (time.perf_counter() - t0) / steps


def bench_bert(batch_size=192, seq_len=128, steps=3, warmup=1):
    from transformers import BertConfig, BertForMaskedLM
    cfg = BertConfig()                     # BERT-base, matches bench.py
    model = BertForMaskedLM(cfg)
    opt = torch.optim.AdamW(model.parameters(), lr=1e-4)
    rng = np.random.RandomState(0)
    ids = torch.from_numpy(
        rng.randint(0, cfg.vocab_size, (batch_size, seq_len))).long()
    # same padded-pretraining length distribution as synthetic_mlm_batch
    # (hetu_tpu/models/bert.py): 35% packed full, rest uniform [s/4, s]
    lengths = np.full((batch_size,), seq_len, np.int64)
    short = rng.rand(batch_size) >= 0.35
    lengths[short] = rng.randint(max(1, seq_len // 4), seq_len + 1,
                                 short.sum())
    attn = torch.from_numpy(
        (np.arange(seq_len)[None, :] < lengths[:, None]).astype(np.int64))
    ids[attn == 0] = 0
    labels = ids.clone()
    labels[(torch.rand(labels.shape) > 0.15) | (attn == 0)] = -100

    def step():
        opt.zero_grad()
        out = model(input_ids=ids, attention_mask=attn, labels=labels)
        out.loss.backward()
        opt.step()

    dt = _timed(step, steps, warmup)
    return {"metric": "bert_base_pretrain_samples_per_sec_per_chip",
            "value": round(batch_size / dt, 2), "unit": "samples/s/chip",
            "vs_baseline": 0.0,
            "extra": {"framework": f"torch-{torch.__version__}",
                      "device": "cpu", "batch_size": batch_size,
                      "seq_len": seq_len,
                      "step_time_ms": round(dt * 1e3, 2)}}


class _BasicBlock(nn.Module):
    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.c1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.b1 = nn.BatchNorm2d(cout)
        self.c2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.b2 = nn.BatchNorm2d(cout)
        self.sc = nn.Sequential()
        if stride != 1 or cin != cout:
            self.sc = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        h = torch.relu(self.b1(self.c1(x)))
        h = self.b2(self.c2(h))
        return torch.relu(h + self.sc(x))


def _resnet18(num_classes=10):
    layers = [nn.Conv2d(3, 64, 3, 1, 1, bias=False), nn.BatchNorm2d(64),
              nn.ReLU()]
    cin = 64
    for cout, stride in [(64, 1), (64, 1), (128, 2), (128, 1),
                         (256, 2), (256, 1), (512, 2), (512, 1)]:
        layers.append(_BasicBlock(cin, cout, stride))
        cin = cout
    return nn.Sequential(*layers, nn.AdaptiveAvgPool2d(1), nn.Flatten(),
                         nn.Linear(512, num_classes))


def bench_resnet18(batch_size=128, steps=5, warmup=1):
    model = _resnet18()
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    lossf = nn.CrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = torch.from_numpy(rng.rand(batch_size, 3, 32, 32).astype(np.float32))
    y = torch.from_numpy(rng.randint(0, 10, batch_size)).long()

    def step():
        opt.zero_grad()
        lossf(model(x), y).backward()
        opt.step()

    dt = _timed(step, steps, warmup)
    return {"metric": "resnet18_cifar10_step_time",
            "value": round(dt * 1e3, 2), "unit": "ms/step",
            "vs_baseline": 0.0,
            "extra": {"framework": f"torch-{torch.__version__}",
                      "device": "cpu", "batch_size": batch_size}}


def bench_wdl(batch_size=2048, steps=5, warmup=1, vocab=100000, dim=16):
    n_dense, n_sparse = 13, 26

    class WDL(nn.Module):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(vocab, dim)
            self.deep = nn.Sequential(
                nn.Linear(n_sparse * dim + n_dense, 256), nn.ReLU(),
                nn.Linear(256, 256), nn.ReLU(), nn.Linear(256, 1))
            self.wide = nn.Linear(n_dense, 1)

        def forward(self, dense, sparse):
            e = self.emb(sparse).reshape(sparse.shape[0], -1)
            return self.wide(dense) + self.deep(
                torch.cat([e, dense], dim=1))

    model = WDL()
    opt = torch.optim.SGD(model.parameters(), lr=0.01)
    lossf = nn.BCEWithLogitsLoss()
    rng = np.random.RandomState(0)
    dense = torch.from_numpy(rng.rand(batch_size, n_dense).astype(np.float32))
    sparse = torch.from_numpy(
        rng.randint(0, vocab, (batch_size, n_sparse))).long()
    y = torch.from_numpy(
        (rng.rand(batch_size, 1) > 0.5).astype(np.float32))

    def step():
        opt.zero_grad()
        lossf(model(dense, sparse), y).backward()
        opt.step()

    dt = _timed(step, steps, warmup)
    return {"metric": "wdl_criteo_cache_samples_per_sec",
            "value": round(batch_size / dt, 1), "unit": "samples/s",
            "vs_baseline": 0.0,
            "extra": {"framework": f"torch-{torch.__version__}",
                      "device": "cpu", "batch_size": batch_size,
                      "step_time_ms": round(dt * 1e3, 2)}}


def bench_moe(batch_tokens=8192, steps=3, warmup=1, d=512, experts=16):
    class MoE(nn.Module):
        def __init__(self):
            super().__init__()
            self.gate = nn.Linear(d, experts)
            self.w1 = nn.Parameter(torch.randn(experts, d, 4 * d) * 0.02)
            self.w2 = nn.Parameter(torch.randn(experts, 4 * d, d) * 0.02)

        def forward(self, x):                      # dense top-2 mixture
            probs = torch.softmax(self.gate(x), dim=-1)      # (T, E)
            top, idx = probs.topk(2, dim=-1)
            top = top / top.sum(-1, keepdim=True)
            out = torch.zeros_like(x)
            for j in range(2):
                for e in range(experts):
                    sel = idx[:, j] == e
                    if sel.any():
                        h = torch.relu(x[sel] @ self.w1[e]) @ self.w2[e]
                        out[sel] += top[sel, j:j + 1] * h
            return out

    model = MoE()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    rng = np.random.RandomState(0)
    x = torch.from_numpy(rng.randn(batch_tokens, d).astype(np.float32))
    y = torch.from_numpy(rng.randn(batch_tokens, d).astype(np.float32))

    def step():
        opt.zero_grad()
        ((model(x) - y) ** 2).mean().backward()
        opt.step()

    dt = _timed(step, steps, warmup)
    return {"metric": "moe_ep_tokens_per_sec",
            "value": round(batch_tokens / dt, 1), "unit": "tokens/s",
            "vs_baseline": 0.0,
            "extra": {"framework": f"torch-{torch.__version__}",
                      "device": "cpu", "tokens": batch_tokens,
                      "experts": experts,
                      "step_time_ms": round(dt * 1e3, 2)}}


BENCHES = {"bert": bench_bert, "resnet18": bench_resnet18,
           "wdl": bench_wdl, "moe": bench_moe}


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="resnet18", choices=sorted(BENCHES))
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--seq-len", type=int, default=None,
                   help="bert only — MUST match the hetu side's seq_len")
    p.add_argument("--steps", type=int, default=None)
    args = p.parse_args()
    kw = {}
    if args.batch_size:
        kw["batch_size" if args.config != "moe" else "batch_tokens"] = \
            args.batch_size
    if args.seq_len:
        if args.config != "bert":
            p.error("--seq-len only applies to bert")
        kw["seq_len"] = args.seq_len
    if args.steps:
        kw["steps"] = args.steps
    torch.manual_seed(0)
    print(json.dumps(BENCHES[args.config](**kw)))
