"""CTR models over ht ops — WDL / DeepFM / DCN on Criteo-format data.

Parity with the reference ``examples/embedding/ctr/models/`` (wdl_criteo,
deepfm_criteo, dcn_criteo): 13 dense + 26 categorical fields, a shared
embedding table addressed with per-field offsets, binary cross-entropy loss.
The embedding either lives in-graph (dense variable) or host-side through
``ht.ps_embedding_lookup_op`` (+ optional HET cache) — the reference's
PS/cache path (run_hetu.py:121-126).
"""
import numpy as np

import hetu_tpu as ht

NUM_DENSE = 13
NUM_SPARSE = 26


def _embed(ids_node, vocab, dim, mode, lr, name, batch_ids=None):
    """Shared embedding: dense variable or PS/cache host table.

    Modes: ``dense`` (in-graph variable), ``ps`` (direct host store, no
    cache), ``lru``/``lfu``/``lfuopt`` (native C++ HET cache),
    ``vlru``/``vlfu`` (the vectorized numpy HET cache —
    :class:`hetu_tpu.ps.DistCacheTable` — the batched sparse-RPC path
    ``bench.py --config wdl --emb-policy`` exercises), and
    ``vlru_dev``/``vlfu_dev`` (the same cache with the DEVICE-RESIDENT
    slab: hit rows gathered on-device by slot index, only miss rows
    crossing the host boundary, grads segment-summed by the Pallas
    scatter-add kernel — ``bench.py --config wdl --emb-device
    device``)."""
    if mode == "dense":
        table = ht.Variable(
            name, initializer=ht.init.GenNormal(0.0, 0.01), shape=(vocab, dim),
            trainable=True, is_embed=True)
        return ht.embedding_lookup_op(table, ids_node)
    if mode == "ps":
        store = ht.default_store()
        t = store.init_table(vocab, dim, opt="sgd", lr=lr, seed=0,
                             init_scale=0.01)
        return ht.ps_embedding_lookup_op((store, t), ids_node, width=dim)
    if mode in ("vlru", "vlfu", "vlru_dev", "vlfu_dev"):
        from hetu_tpu.ps import DistCacheTable, EmbeddingStore
        store = EmbeddingStore()
        t = store.init_table(vocab, dim, opt="sgd", lr=lr, seed=0,
                             init_scale=0.01)
        device = mode.endswith("_dev")
        # scratch bound: a batch can never hold more uncacheable unique
        # keys than its own flattened id count, so batch_ids scratch
        # rows make overflow impossible at batch-sized memory cost (the
        # vocab would also bound it — but a vocab-sized scratch would
        # dwarf the cache and defeat its memory rationale)
        scratch = min(vocab, batch_ids) if device and batch_ids \
            else (vocab if device else None)
        cache = DistCacheTable(store, t, limit=max(vocab // 10, 256),
                               pull_bound=10, push_bound=10,
                               policy=mode[1:4], device=device,
                               device_scratch=scratch)
        return ht.ps_embedding_lookup_op(cache, ids_node, width=dim)
    # native cache policies: lru / lfu / lfuopt
    cs = ht.CacheSparseTable(limit=max(vocab // 10, 256), length=vocab,
                             width=dim, policy=mode, bound=10, opt="sgd",
                             lr=lr, seed=0)
    return ht.ps_embedding_lookup_op(cs, ids_node)


def _mlp(x, dims, name):
    h = x
    for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
        w = ht.Variable(f"{name}_w{i}",
                        initializer=ht.init.GenXavierNormal(),
                        shape=(din, dout))
        b = ht.Variable(f"{name}_b{i}", initializer=ht.init.GenZeros(),
                        shape=(dout,))
        hm = ht.matmul_op(h, w)
        h = hm + ht.broadcastto_op(b, hm)
        if i < len(dims) - 2:
            h = ht.relu_op(h)
    return h


def wdl_criteo(dense, sparse, y_, batch_size, vocab=100000, dim=16,
               embed_mode="dense", lr=0.01):
    """Wide & Deep (reference models/wdl_criteo.py)."""
    emb = _embed(sparse, vocab, dim, embed_mode, lr, "wdl_embed",
                 batch_ids=batch_size * NUM_SPARSE)
    flat = ht.array_reshape_op(emb, (batch_size, NUM_SPARSE * dim))
    deep_in = ht.concat_op(flat, dense, axis=1)
    deep = _mlp(deep_in, [NUM_SPARSE * dim + NUM_DENSE, 256, 256, 1], "deep")
    wide = _mlp(dense, [NUM_DENSE, 1], "wide")
    logit = wide + deep
    prob = ht.sigmoid_op(logit)
    loss = ht.reduce_mean_op(
        ht.binarycrossentropy_op(prob, y_), [0, 1])
    return loss, prob


def deepfm_criteo(dense, sparse, y_, batch_size, vocab=100000, dim=16,
                  embed_mode="dense", lr=0.01):
    """DeepFM (reference models/deepfm_criteo.py): FM 2nd-order term via
    0.5*((Σv)² − Σv²) + linear term + deep MLP."""
    emb = _embed(sparse, vocab, dim, embed_mode, lr, "fm_embed",
                 batch_ids=batch_size * NUM_SPARSE)  # B,26,D
    sum_vec = ht.reduce_sum_op(emb, [1])                  # B,D
    sum_sq = ht.mul_op(sum_vec, sum_vec)
    sq = ht.mul_op(emb, emb)
    sq_sum = ht.reduce_sum_op(sq, [1])
    fm2 = ht.reduce_sum_op(sum_sq - sq_sum, [1], keepdims=True) * 0.5  # B,1
    lin = _mlp(dense, [NUM_DENSE, 1], "fm_lin")
    flat = ht.array_reshape_op(emb, (batch_size, NUM_SPARSE * dim))
    deep = _mlp(flat, [NUM_SPARSE * dim, 256, 256, 1], "fm_deep")
    prob = ht.sigmoid_op(lin + fm2 + deep)
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0, 1])
    return loss, prob


def dcn_criteo(dense, sparse, y_, batch_size, vocab=100000, dim=16,
               embed_mode="dense", lr=0.01, n_cross=3):
    """Deep & Cross (reference models/dcn_criteo.py): x_{l+1} = x0·(x_l·w) +
    b + x_l cross layers alongside a deep tower."""
    emb = _embed(sparse, vocab, dim, embed_mode, lr, "dcn_embed",
                 batch_ids=batch_size * NUM_SPARSE)
    flat = ht.array_reshape_op(emb, (batch_size, NUM_SPARSE * dim))
    x0 = ht.concat_op(flat, dense, axis=1)
    width = NUM_SPARSE * dim + NUM_DENSE
    x = x0
    for i in range(n_cross):
        w = ht.Variable(f"cross_w{i}", initializer=ht.init.GenXavierNormal(),
                        shape=(width, 1))
        b = ht.Variable(f"cross_b{i}", initializer=ht.init.GenZeros(),
                        shape=(width,))
        xw = ht.matmul_op(x, w)                       # B,1
        x = ht.mul_op(x0, ht.broadcastto_op(xw, x0)) \
            + ht.broadcastto_op(b, x) + x
    deep = _mlp(x0, [width, 256, 256], "dcn_deep")
    both = ht.concat_op(x, deep, axis=1)
    logit = _mlp(both, [width + 256, 1], "dcn_out")
    prob = ht.sigmoid_op(logit)
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0, 1])
    return loss, prob


def synthetic_criteo_skewed(n_rows, vocab=100000, seed=0, zipf_a=1.1):
    """Criteo-FORMAT dataset with the two properties the real one has that
    the uniform generator lacks: heavily skewed (Zipf) id frequencies —
    which is what makes the HET cache effective (reference README ctr:33,
    HET VLDB'22) — and a click signal carried partly by the CATEGORICAL
    fields, so embedding learning moves AUC, not just the dense MLP.

    Returns (dense, sparse, y) for the whole dataset; slice into batches.
    """
    rng = np.random.RandomState(seed)
    dense = rng.rand(n_rows, NUM_DENSE).astype(np.float32)
    per_field = vocab // NUM_SPARSE
    ranks = np.arange(per_field, dtype=np.float64)
    p = 1.0 / (ranks + 1.0) ** zipf_a
    p /= p.sum()
    field = np.stack([rng.choice(per_field, n_rows, p=p)
                      for _ in range(NUM_SPARSE)], axis=1)
    offsets = np.arange(NUM_SPARSE) * per_field
    sparse = (field + offsets).astype(np.int64)
    # planted signal: dense linear part + per-id categorical effects on a
    # few fields (hash-derived so frequent ids carry consistent signal)
    cat_effect = np.cos(field[:, :6] * 2.399963).sum(axis=1)
    signal = dense @ rng.randn(NUM_DENSE) * 0.5 + 0.8 * cat_effect
    y = signal + 0.5 * rng.randn(n_rows) > np.median(signal)
    return dense, sparse, y.astype(np.float32).reshape(-1, 1)


def validate_cache_parity(steps=300, batch_size=512, vocab=100000, dim=16,
                          policy="lru", bound=10, lr=0.01, seed=0,
                          record_every=10):
    """Loss-parity validation: WDL trained through the HET cache vs the
    direct store on identical Criteo-format skewed data (BASELINE config 4;
    reference cache flags run_hetu.py:121-126).  Returns a JSON-ready dict
    with both loss curves, AUCs, divergence, and cache counters."""
    import jax
    import hetu_tpu as ht
    from hetu_tpu.ps import EmbeddingStore, CacheSparseTable

    n_rows = steps * batch_size + batch_size
    dense_all, sparse_all, y_all = synthetic_criteo_skewed(
        n_rows, vocab=vocab, seed=seed)
    table0 = np.random.RandomState(seed).normal(
        0.0, 0.01, (vocab, dim)).astype(np.float32)

    def run(use_cache):
        store = EmbeddingStore()
        t = store.init_table(vocab, dim, opt="sgd", lr=lr, seed=seed,
                             init_scale=0.01)
        store.set_data(t, table0.copy())
        if use_cache:
            cs = CacheSparseTable(limit=max(vocab // 10, 256), length=vocab,
                                  width=dim, policy=policy, bound=bound,
                                  store=store, table=t)
            embed_src = cs
        else:
            cs = None
            embed_src = (store, t)
        dense = ht.placeholder_op("dense")
        sparse = ht.placeholder_op("sparse", dtype=np.int64)
        y_ = ht.placeholder_op("y")
        emb = ht.ps_embedding_lookup_op(embed_src, sparse, width=dim)
        flat = ht.array_reshape_op(emb, (batch_size, NUM_SPARSE * dim))
        deep_in = ht.concat_op(flat, dense, axis=1)
        deep = _mlp(deep_in, [NUM_SPARSE * dim + NUM_DENSE, 256, 256, 1],
                    "deep")
        wide = _mlp(dense, [NUM_DENSE, 1], "wide")
        prob = ht.sigmoid_op(wide + deep)
        loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0, 1])
        opt = ht.optim.AdamOptimizer(lr)
        ex = ht.Executor({"train": [loss, opt.minimize(loss)],
                          "eval": [prob]}, seed=seed)
        curve = []
        for i in range(steps):
            lo = batch_size * i
            fd = {dense: dense_all[lo:lo + batch_size],
                  sparse: sparse_all[lo:lo + batch_size],
                  y_: y_all[lo:lo + batch_size]}
            out = ex.run("train", feed_dict=fd)
            if i % record_every == 0:
                curve.append(round(float(out[0].asnumpy()), 6))
        lo = batch_size * steps      # held-out tail batch
        pv = ex.run("eval", feed_dict={
            dense: dense_all[lo:lo + batch_size],
            sparse: sparse_all[lo:lo + batch_size],
            y_: y_all[lo:lo + batch_size]},
            convert_to_numpy_ret_vals=True)[0]
        auc = float(ht.metrics.auc(pv.ravel(),
                                   y_all[lo:lo + batch_size].ravel()))
        perf = cs.perf() if cs is not None else {}
        if cs is not None:
            cs.flush()
        return curve, auc, perf

    curve_off, auc_off, _ = run(False)
    curve_on, auc_on, perf = run(True)
    diffs = [abs(a - b) for a, b in zip(curve_off, curve_on)]
    return {
        "config": {"steps": steps, "batch_size": batch_size, "vocab": vocab,
                   "dim": dim, "policy": policy, "bound": bound, "lr": lr,
                   "zipf_a": 1.1},
        "loss_curve_cache_off": curve_off,
        "loss_curve_cache_on": curve_on,
        "max_curve_divergence": round(max(diffs), 6),
        "final_divergence": round(diffs[-1], 6),
        "auc_cache_off": round(auc_off, 4),
        "auc_cache_on": round(auc_on, 4),
        "cache_perf": perf,
        # READ hit rate: read hits / read lookups (write traffic counts
        # separately since the round-4 counter split — cache.h perf_
        # semantics; the old shared counter reported hits > lookups)
        "cache_hit_rate": round(perf.get("hit_rate", 0.0), 4),
    }


def synthetic_criteo(batch_size, vocab=100000, seed=0):
    """Criteo-shaped synthetic batch: 13 float features, 26 categorical ids
    (field-offset layout like the reference's preprocessed dataset), click
    label with a planted linear signal so AUC is learnable."""
    rng = np.random.RandomState(seed)
    dense = rng.rand(batch_size, NUM_DENSE).astype(np.float32)
    per_field = vocab // NUM_SPARSE
    field = rng.randint(0, per_field, (batch_size, NUM_SPARSE))
    offsets = np.arange(NUM_SPARSE) * per_field
    sparse = (field + offsets).astype(np.int64)
    signal = dense @ rng.randn(NUM_DENSE) + 0.003 * (field[:, 0] % 37 - 18)
    y = signal + 0.3 * rng.randn(batch_size) > np.median(signal)
    return dense, sparse, y.astype(np.float32).reshape(-1, 1)
