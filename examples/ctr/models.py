"""CTR models over ht ops — WDL / DeepFM / DCN on Criteo-format data.

Parity with the reference ``examples/embedding/ctr/models/`` (wdl_criteo,
deepfm_criteo, dcn_criteo): 13 dense + 26 categorical fields, a shared
embedding table addressed with per-field offsets, binary cross-entropy loss.
The embedding either lives in-graph (dense variable) or host-side through
``ht.ps_embedding_lookup_op`` (+ optional HET cache) — the reference's
PS/cache path (run_hetu.py:121-126).
"""
import numpy as np

import hetu_tpu as ht

NUM_DENSE = 13
NUM_SPARSE = 26


def _embed(ids_node, vocab, dim, mode, lr, name):
    """Shared embedding: dense variable or PS/cache host table."""
    if mode == "dense":
        table = ht.Variable(
            name, initializer=ht.init.GenNormal(0.0, 0.01), shape=(vocab, dim),
            trainable=True, is_embed=True)
        return ht.embedding_lookup_op(table, ids_node)
    if mode == "ps":
        store = ht.default_store()
        t = store.init_table(vocab, dim, opt="sgd", lr=lr, seed=0,
                             init_scale=0.01)
        return ht.ps_embedding_lookup_op((store, t), ids_node, width=dim)
    # cache policies: lru / lfu / lfuopt
    cs = ht.CacheSparseTable(limit=max(vocab // 10, 256), length=vocab,
                             width=dim, policy=mode, bound=10, opt="sgd",
                             lr=lr, seed=0)
    return ht.ps_embedding_lookup_op(cs, ids_node)


def _mlp(x, dims, name):
    h = x
    for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
        w = ht.Variable(f"{name}_w{i}",
                        initializer=ht.init.GenXavierNormal(),
                        shape=(din, dout))
        b = ht.Variable(f"{name}_b{i}", initializer=ht.init.GenZeros(),
                        shape=(dout,))
        hm = ht.matmul_op(h, w)
        h = hm + ht.broadcastto_op(b, hm)
        if i < len(dims) - 2:
            h = ht.relu_op(h)
    return h


def wdl_criteo(dense, sparse, y_, batch_size, vocab=100000, dim=16,
               embed_mode="dense", lr=0.01):
    """Wide & Deep (reference models/wdl_criteo.py)."""
    emb = _embed(sparse, vocab, dim, embed_mode, lr, "wdl_embed")
    flat = ht.array_reshape_op(emb, (batch_size, NUM_SPARSE * dim))
    deep_in = ht.concat_op(flat, dense, axis=1)
    deep = _mlp(deep_in, [NUM_SPARSE * dim + NUM_DENSE, 256, 256, 1], "deep")
    wide = _mlp(dense, [NUM_DENSE, 1], "wide")
    logit = wide + deep
    prob = ht.sigmoid_op(logit)
    loss = ht.reduce_mean_op(
        ht.binarycrossentropy_op(prob, y_), [0, 1])
    return loss, prob


def deepfm_criteo(dense, sparse, y_, batch_size, vocab=100000, dim=16,
                  embed_mode="dense", lr=0.01):
    """DeepFM (reference models/deepfm_criteo.py): FM 2nd-order term via
    0.5*((Σv)² − Σv²) + linear term + deep MLP."""
    emb = _embed(sparse, vocab, dim, embed_mode, lr, "fm_embed")  # B,26,D
    sum_vec = ht.reduce_sum_op(emb, [1])                  # B,D
    sum_sq = ht.mul_op(sum_vec, sum_vec)
    sq = ht.mul_op(emb, emb)
    sq_sum = ht.reduce_sum_op(sq, [1])
    fm2 = ht.reduce_sum_op(sum_sq - sq_sum, [1], keepdims=True) * 0.5  # B,1
    lin = _mlp(dense, [NUM_DENSE, 1], "fm_lin")
    flat = ht.array_reshape_op(emb, (batch_size, NUM_SPARSE * dim))
    deep = _mlp(flat, [NUM_SPARSE * dim, 256, 256, 1], "fm_deep")
    prob = ht.sigmoid_op(lin + fm2 + deep)
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0, 1])
    return loss, prob


def dcn_criteo(dense, sparse, y_, batch_size, vocab=100000, dim=16,
               embed_mode="dense", lr=0.01, n_cross=3):
    """Deep & Cross (reference models/dcn_criteo.py): x_{l+1} = x0·(x_l·w) +
    b + x_l cross layers alongside a deep tower."""
    emb = _embed(sparse, vocab, dim, embed_mode, lr, "dcn_embed")
    flat = ht.array_reshape_op(emb, (batch_size, NUM_SPARSE * dim))
    x0 = ht.concat_op(flat, dense, axis=1)
    width = NUM_SPARSE * dim + NUM_DENSE
    x = x0
    for i in range(n_cross):
        w = ht.Variable(f"cross_w{i}", initializer=ht.init.GenXavierNormal(),
                        shape=(width, 1))
        b = ht.Variable(f"cross_b{i}", initializer=ht.init.GenZeros(),
                        shape=(width,))
        xw = ht.matmul_op(x, w)                       # B,1
        x = ht.mul_op(x0, ht.broadcastto_op(xw, x0)) \
            + ht.broadcastto_op(b, x) + x
    deep = _mlp(x0, [width, 256, 256], "dcn_deep")
    both = ht.concat_op(x, deep, axis=1)
    logit = _mlp(both, [width + 256, 1], "dcn_out")
    prob = ht.sigmoid_op(logit)
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0, 1])
    return loss, prob


def synthetic_criteo(batch_size, vocab=100000, seed=0):
    """Criteo-shaped synthetic batch: 13 float features, 26 categorical ids
    (field-offset layout like the reference's preprocessed dataset), click
    label with a planted linear signal so AUC is learnable."""
    rng = np.random.RandomState(seed)
    dense = rng.rand(batch_size, NUM_DENSE).astype(np.float32)
    per_field = vocab // NUM_SPARSE
    field = rng.randint(0, per_field, (batch_size, NUM_SPARSE))
    offsets = np.arange(NUM_SPARSE) * per_field
    sparse = (field + offsets).astype(np.int64)
    signal = dense @ rng.randn(NUM_DENSE) + 0.003 * (field[:, 0] % 37 - 18)
    y = signal + 0.3 * rng.randn(batch_size) > np.median(signal)
    return dense, sparse, y.astype(np.float32).reshape(-1, 1)
