"""CTR training driver (reference ``examples/embedding/ctr/run_hetu.py``).

    python examples/ctr/run_ctr.py --model wdl --embed dense
    python examples/ctr/run_ctr.py --model deepfm --embed ps
    python examples/ctr/run_ctr.py --model dcn --embed lru --bound 10

``--embed`` selects where the embedding table lives: in-graph ("dense"),
host PS store ("ps"), or PS + HET bounded-staleness cache
("lru"/"lfu"/"lfuopt" — reference --cache flag, run_hetu.py:121-126).
"""
import argparse
import os
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_HERE)))  # repo root
sys.path.insert(0, _HERE)

if "--cpu" in sys.argv:  # must run before hetu_tpu/jax backend init
    import jax
    jax.config.update("jax_platforms", "cpu")

import models  # noqa: E402
import hetu_tpu as ht  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    p.add_argument("--model", default="wdl",
                   choices=["wdl", "deepfm", "dcn"])
    p.add_argument("--embed", default="dense",
                   choices=["dense", "ps", "lru", "lfu", "lfuopt",
                            "vlru", "vlfu", "vlru_dev", "vlfu_dev"])
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--vocab", type=int, default=100000)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()

    dense = ht.placeholder_op("dense")
    sparse = ht.placeholder_op("sparse")
    y_ = ht.placeholder_op("y")
    builder = {"wdl": models.wdl_criteo, "deepfm": models.deepfm_criteo,
               "dcn": models.dcn_criteo}[args.model]
    loss, prob = builder(dense, sparse, y_, args.batch_size,
                         vocab=args.vocab, dim=args.dim,
                         embed_mode=args.embed, lr=args.lr)
    opt = ht.optim.SGDOptimizer(args.lr)
    ex = ht.Executor({"train": [loss, prob, opt.minimize(loss)]}, seed=0)

    t0 = time.time()
    for it in range(args.iters):
        dv, sv, yv = models.synthetic_criteo(args.batch_size,
                                             vocab=args.vocab, seed=it)
        out = ex.run("train", feed_dict={dense: dv, sparse: sv, y_: yv})
        if it % 20 == 0 or it == args.iters - 1:
            lv = float(out[0].asnumpy())
            auc = ht.metrics.auc(np.asarray(out[1].asnumpy()).ravel(),
                                 yv.ravel())
            print(f"iter {it:4d}  loss {lv:.4f}  auc {auc:.4f}")
    dt = time.time() - t0
    print(f"{args.model}/{args.embed}: {args.iters} iters in {dt:.1f}s "
          f"({args.iters * args.batch_size / dt:.0f} samples/s)")


if __name__ == "__main__":
    main()
