"""Distributed 1.5D GCN node classification on a synthetic graph.

Reference parity: ``examples/embedding/gnn`` + ``tests/test_DistGCN``.
``--shards N`` runs the row-partitioned SPMD path on an N-way mesh axis.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

if "--cpu" in sys.argv:  # must run before hetu_tpu/jax backend init
    import jax
    jax.config.update("jax_platforms", "cpu")

import hetu_tpu as ht  # noqa: E402
from hetu_tpu.gnn import (DistGCN15D, normalized_adjacency,  # noqa
                          partition_edges_by_row)


def synthetic_graph(rng, n, avg_deg, classes, feat):
    """Community graph: nodes of a class connect mostly within it."""
    y = rng.randint(0, classes, n)
    src, dst = [], []
    for _ in range(n * avg_deg):
        a = rng.randint(0, n)
        same = np.flatnonzero(y == y[a])
        b = same[rng.randint(len(same))] if rng.rand() < 0.8 \
            else rng.randint(0, n)
        src.append(a)
        dst.append(b)
    x = rng.randn(n, feat).astype(np.float32) * 0.3
    x[np.arange(n), y % feat] += 2.0  # informative feature bump
    return np.stack([src, dst], 1), x, y.astype(np.int32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    p.add_argument("--nodes", type=int, default=256)
    p.add_argument("--shards", type=int, default=1)
    p.add_argument("--steps", type=int, default=40)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    classes, feat, hidden = 4, 16, 32
    edges, x_np, y_np = synthetic_graph(rng, args.nodes, 8, classes, feat)
    vals, rows, cols = normalized_adjacency(edges, args.nodes)
    axis = "row" if args.shards > 1 else None
    if axis:
        vals, rows, cols = partition_edges_by_row(
            vals, rows, cols, args.nodes, args.shards)

    v, r, c = (ht.placeholder_op(s) for s in "vrc")
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    model = DistGCN15D(feat, hidden, classes, args.nodes, axis=axis)
    logits = model(v, r, c, x)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_sparse_op(logits, y), [0])
    strategy = ht.dist.ModelParallel({"row": args.shards}) if axis else None
    if axis:
        from jax.sharding import PartitionSpec as P
        for node in (v, r, c):
            ht.dispatch(node, P(axis))
        ht.dispatch(x, P(axis, None))
    ex = ht.Executor({"train": [loss,
                                ht.optim.AdamOptimizer(1e-2).minimize(loss)],
                      "infer": [logits]},
                     dist_strategy=strategy, seed=0)
    fd = {v: vals, r: rows, c: cols, x: x_np, y: y_np}
    for step in range(args.steps):
        out = ex.run("train", feed_dict=fd)
        if step % 10 == 0 or step == args.steps - 1:
            lg = np.asarray(ex.run("infer", feed_dict={
                v: vals, r: rows, c: cols, x: x_np})[0].asnumpy())
            acc = (lg.argmax(-1) == y_np).mean()
            print(f"step {step}: loss={float(out[0].asnumpy()):.4f} "
                  f"acc={acc:.3f}")


if __name__ == "__main__":
    main()
