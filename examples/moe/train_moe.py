"""MoE transformer-block training with every gate family.

Reference parity: ``examples/moe/test_moe_{base,top,hash,ktop1,sam}.py``
(single script, --gate flag). Runs EP-sharded when devices allow:
``python examples/moe/train_moe.py --gate top2 --ep 4``.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

if "--cpu" in sys.argv:  # must run before hetu_tpu/jax backend init
    import jax
    jax.config.update("jax_platforms", "cpu")

import hetu_tpu as ht  # noqa: E402
from hetu_tpu.layers import (Expert, KTop1Gate, MoELayer, SAMGate,  # noqa
                             TopKGate)
from hetu_tpu.layers.gates import BalanceAssignmentGate, HashGate  # noqa
from hetu_tpu.layers.moe_layer import BalancedMoELayer  # noqa


class _HashGateAdapter:
    """HashGate routes on token IDS (reference HashGate.py), not embeddings;
    adapt it to the MoELayer gate(x) calling convention."""

    def __init__(self, gate, ids_node):
        self.gate = gate
        self.ids_node = ids_node

    def __call__(self, x):
        return self.gate(self.ids_node)


def build_gate(kind, d, tokens, experts, ids_node=None):
    if kind == "base":  # BASE layer: balanced assignment (auction)
        return BalanceAssignmentGate(d, tokens, experts)
    if kind == "top1":
        return TopKGate(d, tokens, experts, k=1, capacity_factor=1.5)
    if kind == "top2":
        return TopKGate(d, tokens, experts, k=2, capacity_factor=2.0)
    if kind == "hash":
        return _HashGateAdapter(
            HashGate(tokens, experts, capacity_factor=2.0), ids_node)
    if kind == "ktop1":
        return KTop1Gate(d, tokens, experts, k=2, capacity_factor=2.0)
    if kind == "sam":
        return SAMGate(d, tokens, experts, k=1, capacity_factor=4.0,
                       num_local_devices=2)
    raise ValueError(kind)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    p.add_argument("--gate", default="top2",
                   choices=["base", "top1", "top2", "hash", "ktop1", "sam"])
    p.add_argument("--experts", type=int, default=4)
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel width (mesh 'ep' axis)")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--tokens", type=int, default=256)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    d, tokens, e = args.dim, args.tokens, args.experts
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    ids_node = ht.Variable("token_ids",
                           value=(np.arange(tokens) % 97).astype(np.int32),
                           trainable=False)
    gate = build_gate(args.gate, d, tokens, e, ids_node=ids_node)
    if args.gate == "base":
        moe = BalancedMoELayer(gate, Expert(e, d, 2 * d), e, tokens, d)
    else:
        moe = MoELayer(gate, Expert(e, d, 2 * d))
    h, aux = moe(x)
    from hetu_tpu.layers import Linear
    logits = Linear(d, 8, name="head")(h)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_sparse_op(logits, y), [0])
    if aux is not None:
        loss = loss + aux * 0.01
    strategy = ht.dist.ModelParallel({"ep": args.ep}) if args.ep > 1 else None
    ex = ht.Executor({"train": [loss,
                                ht.optim.AdamOptimizer(1e-3).minimize(loss)]},
                     dist_strategy=strategy, seed=0)
    x_np = rng.randn(tokens, d).astype(np.float32)
    y_np = np.argmax(x_np[:, :8], axis=-1).astype(np.int32)
    for step in range(args.steps):
        out = ex.run("train", feed_dict={x: x_np, y: y_np})
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={float(out[0].asnumpy()):.4f}")


if __name__ == "__main__":
    main()
