"""Neural Collaborative Filtering (reference ``examples/embedding/ncf``).

GMF + MLP twin towers over user/item embeddings with implicit-feedback
binary loss; embeddings can live in the host PS store (``--ps``) exactly
like the CTR examples (HET path, SURVEY.md §3.3).
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

if "--cpu" in sys.argv:  # must run before hetu_tpu/jax backend init
    import jax
    jax.config.update("jax_platforms", "cpu")

import hetu_tpu as ht  # noqa: E402
from hetu_tpu.layers import Linear  # noqa


def build_ncf(users, items, dim, u_ids, i_ids, use_ps):
    if use_ps:
        store = ht.EmbeddingStore()
        tables = {}
        for idx, (nm, rows) in enumerate((("gmf_u", users), ("gmf_i", items),
                                          ("mlp_u", users),
                                          ("mlp_i", items))):
            tables[nm] = store.init_table(rows, dim, opt="sgd", lr=0.05,
                                          seed=idx)
        def emb(nm, ids):
            return ht.ps_embedding_lookup_op((store, tables[nm]), ids,
                                             width=dim)
    else:
        import hetu_tpu.initializers as init
        vars_ = {nm: init.random_normal((rows, dim), stddev=0.05,
                                        name=nm)
                 for nm, rows in (("gmf_u", users), ("gmf_i", items),
                                  ("mlp_u", users), ("mlp_i", items))}
        def emb(nm, ids):
            return ht.embedding_lookup_op(vars_[nm], ids)

    gmf = ht.mul_op(emb("gmf_u", u_ids), emb("gmf_i", i_ids))
    mlp_in = ht.concat_op(emb("mlp_u", u_ids), emb("mlp_i", i_ids), axis=1)
    h = Linear(2 * dim, dim, activation="relu", name="mlp1")(mlp_in)
    h = Linear(dim, dim // 2, activation="relu", name="mlp2")(h)
    fused = ht.concat_op(gmf, h, axis=1)
    logit = Linear(dim + dim // 2, 1, name="predict")(fused)
    return ht.array_reshape_op(logit, output_shape=(-1,))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    p.add_argument("--users", type=int, default=200)
    p.add_argument("--items", type=int, default=100)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--ps", action="store_true",
                   help="host parameter-server embeddings")
    args = p.parse_args()

    rng = np.random.RandomState(0)
    u = ht.placeholder_op("u")
    i = ht.placeholder_op("i")
    y = ht.placeholder_op("y")
    logit = build_ncf(args.users, args.items, args.dim, u, i, args.ps)
    prob = ht.sigmoid_op(logit)
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y), [0])
    ex = ht.Executor({"train": [loss,
                                ht.optim.AdamOptimizer(5e-3).minimize(loss)],
                      "infer": [prob]}, seed=0)

    # synthetic preference structure: user_class == item_class → positive
    u_np = rng.randint(0, args.users, args.batch).astype(np.int64)
    i_np = rng.randint(0, args.items, args.batch).astype(np.int64)
    y_np = ((u_np % 7) == (i_np % 7)).astype(np.float32)
    for step in range(args.steps):
        out = ex.run("train", feed_dict={u: u_np, i: i_np, y: y_np})
        if step % 15 == 0 or step == args.steps - 1:
            pv = np.asarray(ex.run("infer", feed_dict={
                u: u_np, i: i_np})[0].asnumpy())
            auc = ht.metrics.auc(pv, y_np)
            print(f"step {step}: loss={float(out[0].asnumpy()):.4f} "
                  f"auc={auc:.3f}")


if __name__ == "__main__":
    main()
