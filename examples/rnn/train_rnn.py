"""Character-level sequence classification with RNN/LSTM/GRU.

Reference parity: ``examples/rnn/`` (train_hetu_rnn scripts, TF/torch
comparisons). Synthetic task: classify the dominant token of a sequence.
``python examples/rnn/train_rnn.py --cell lstm``.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

if "--cpu" in sys.argv:  # must run before hetu_tpu/jax backend init
    import jax
    jax.config.update("jax_platforms", "cpu")

import hetu_tpu as ht  # noqa: E402
from hetu_tpu.layers import GRU, LSTM, RNN, Embedding, Linear  # noqa


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    p.add_argument("--cell", default="lstm", choices=["rnn", "lstm", "gru"])
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--seq", type=int, default=16)
    p.add_argument("--vocab", type=int, default=32)
    p.add_argument("--hidden", type=int, default=64)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    B, T, V, H = args.batch, args.seq, args.vocab, args.hidden
    classes = 4

    ids = ht.placeholder_op("ids")
    y = ht.placeholder_op("y")
    emb = Embedding(V, H, name="emb")
    cell = {"rnn": RNN, "lstm": LSTM, "gru": GRU}[args.cell](H, H)
    seq = cell(emb(ids))
    last = ht.slice_op(seq, begin=[0, T - 1, 0], size=[-1, 1, -1])
    last = ht.array_reshape_op(last, output_shape=(B, H))
    logits = Linear(H, classes, name="head")(last)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_sparse_op(logits, y), [0])
    ex = ht.Executor({"train": [loss,
                                ht.optim.AdamOptimizer(1e-2).minimize(loss)],
                      "infer": [logits]}, seed=0)

    ids_np = rng.randint(0, classes, (B, T)).astype(np.int32)
    y_np = np.array([np.bincount(r).argmax() for r in ids_np], np.int32)
    for step in range(args.steps):
        out = ex.run("train", feed_dict={ids: ids_np, y: y_np})
        if step % 15 == 0 or step == args.steps - 1:
            logits_v = np.asarray(
                ex.run("infer", feed_dict={ids: ids_np})[0].asnumpy())
            acc = (logits_v.argmax(-1) == y_np).mean()
            print(f"step {step}: loss={float(out[0].asnumpy()):.4f} "
                  f"acc={acc:.3f}")


if __name__ == "__main__":
    main()
