"""Parallelism config sweep — the reference's cookbook surface.

The reference ships 20+ per-config scripts under ``examples/runner/
parallel/`` (``complex_pipeline_mlp.py``, ``dp4_tp2.py``, ...) plus
``all_mlp_tests.sh``/``all_cnn_tests.sh`` drivers.  Here the same cookbook
is ONE parameterised sweep: every named config builds the same model under
a different strategy on the virtual 8-device CPU mesh, trains a few steps
and (where the math promises it) checks loss parity against the
single-device run — so each config doubles as copy-paste documentation
for that parallelism mode.

    python examples/runner/parallel_sweep.py                # all configs
    python examples/runner/parallel_sweep.py --model mlp --configs dp8,tp4
    python examples/runner/parallel_sweep.py --list

Add a config: one entry in CONFIGS — (name, strategy factory, kwargs).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    # append, don't setdefault: a pre-existing XLA_FLAGS must keep its
    # options AND gain the 8 virtual devices the sweep meshes need
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np                      # noqa: E402

import hetu_tpu as ht                   # noqa: E402


def build_mlp(batch, strategy=None, pipeline=None, num_microbatches=None):
    rng = np.random.RandomState(0)
    x = ht.placeholder_op("x")
    y_ = ht.placeholder_op("y")
    h = ht.layers.Linear(32, 64, activation="relu", name="swp.fc1")(x)
    h = ht.layers.Linear(64, 64, activation="relu", name="swp.fc2")(h)
    logits = ht.layers.Linear(64, 10, name="swp.fc3")(h)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_), [0])
    ex = ht.Executor(
        {"train": [loss, ht.optim.AdamOptimizer(1e-2).minimize(loss)]},
        seed=0, dist_strategy=strategy, pipeline=pipeline,
        num_microbatches=num_microbatches)
    W = rng.randn(32, 10).astype(np.float32)
    X = rng.randn(batch, 32).astype(np.float32)
    Y = np.eye(10, dtype=np.float32)[np.argmax(X @ W, 1)]
    return ex, {x: X, y_: Y}


def build_pipeline_mlp(batch, strategy=None, **_):
    """Staged MLP through ht.pipeline_block (the scheduled-pipeline path —
    reference complex_pipeline_mlp.py)."""
    rng = np.random.RandomState(0)
    x = ht.placeholder_op("x")
    y_ = ht.placeholder_op("y")

    def stage(h):
        return ht.layers.Linear(32, 32, activation="relu", name="swp.ps")(h)

    h = ht.pipeline_block(x, stage, n_stages=4, n_microbatches=4)
    w = ht.Variable("swp.head", value=rng.randn(32, 10).astype(np.float32) * .2)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(h, w), y_), [0])
    ex = ht.Executor(
        {"train": [loss, ht.optim.SGDOptimizer(0.1).minimize(loss)]},
        seed=0, dist_strategy=strategy)
    W = rng.randn(32, 10).astype(np.float32)
    X = rng.randn(batch, 32).astype(np.float32)
    Y = np.eye(10, dtype=np.float32)[np.argmax(X @ W, 1)]
    return ex, {x: X, y_: Y}


def build_cp_attention(batch, strategy=None, **_):
    """Causal MHA under context parallelism (ring) — the long-context
    recipe at toy size."""
    rng = np.random.RandomState(0)
    B, S, hid = 2, 16, 32
    x = ht.placeholder_op("x")
    y_ = ht.placeholder_op("y")
    mha = ht.layers.MultiHeadAttention(
        hid, 4, causal=True,
        context_parallel="ring" if strategy else None, name="swp.mha")
    h = mha(x, B, S)
    w = ht.Variable("swp.aw", value=rng.randn(hid, 3).astype(np.float32) * .2)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(h, w), y_), [0])
    ex = ht.Executor(
        {"train": [loss, ht.optim.AdamOptimizer(1e-2).minimize(loss)]},
        seed=0, dist_strategy=strategy)
    X = rng.randn(B * S, hid).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, B * S)]
    return ex, {x: X, y_: Y}


#: name -> (builder, strategy factory, executor kwargs, parity?)
CONFIGS = {
    "single":      (build_mlp, lambda: None, {}, True),
    "dp8":         (build_mlp, lambda: ht.dist.DataParallel(), {}, True),
    "tp4":         (build_mlp, lambda: ht.dist.ModelParallel(
                        {"tp": 4}), {}, True),
    "dp2_tp4":     (build_mlp, lambda: ht.dist.ModelParallel(
                        {"dp": 2, "tp": 4}), {}, True),
    "gpipe_mb4":   (build_mlp, lambda: None,
                    {"pipeline": "gpipe", "num_microbatches": 4}, True),
    "1f1b_mb4":    (build_mlp, lambda: None,
                    {"pipeline": "pipedream", "num_microbatches": 4}, True),
    "pp4_block":   (build_pipeline_mlp, lambda: ht.PipelineParallel(pp=4),
                    {}, True),
    "dp2_pp4":     (build_pipeline_mlp,
                    lambda: ht.PipelineParallel(pp=4, dp=2), {}, True),
    "cp4_ring":    (build_cp_attention, lambda: ht.ContextParallel(cp=4),
                    {}, True),
}


def run_config(name, steps, batch):
    builder, strat, kw, _ = CONFIGS[name]
    ex, fd = builder(batch, strategy=strat(), **kw)
    return [float(ex.run("train", feed_dict=fd)[0].asnumpy())
            for _ in range(steps)]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--configs", default=None,
                   help="comma list (default: all)")
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--list", action="store_true")
    args = p.parse_args()
    if args.list:
        print("\n".join(CONFIGS))
        return 0
    names = [c.strip() for c in (args.configs or ",".join(CONFIGS)).split(",")
             if c.strip()]
    unknown = [c for c in names if c not in CONFIGS]
    if unknown:
        p.error(f"unknown config(s) {unknown}; see --list")
    base = {}
    failures = []
    for name in names:
        builder = CONFIGS[name][0]
        if (builder, "single") not in base and CONFIGS[name][3]:
            # single-device reference per builder, for parity checks
            ex, fd = builder(args.batch_size, strategy=None)
            base[(builder, "single")] = [
                float(ex.run("train", feed_dict=fd)[0].asnumpy())
                for _ in range(args.steps)]
        losses = run_config(name, args.steps, args.batch_size)
        status = "ok"
        if CONFIGS[name][3]:
            ref = base[(builder, "single")]
            if not np.allclose(ref, losses, rtol=2e-4):
                status = f"PARITY FAIL vs single: {ref} != {losses}"
                failures.append(name)
        print(f"{name:12s} losses={[round(v, 4) for v in losses]} {status}",
              flush=True)
    if failures:
        print(f"FAILED: {failures}")
        return 1
    print(f"all {len(names)} configs ran; parity checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
