"""MLP runner (reference ``examples/runner/run_mlp.py`` + yaml pattern).

Single host:   python examples/runner/run_mlp.py --cpu
Multi host:    bin/heturun -c examples/runner/config.yml examples/runner/run_mlp.py
Local 2-rank:  bin/heturun -n 2 --no-ssh --local-devices 4 examples/runner/run_mlp.py --cpu
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

if "--cpu" in sys.argv:
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np                      # noqa: E402

import hetu_tpu as ht                   # noqa: E402
from hetu_tpu import launcher           # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()
    launcher.init_distributed()         # no-op on a single host
    import jax

    rng = np.random.RandomState(0)
    W = rng.randn(32, 10).astype(np.float32)
    X = rng.randn(args.batch_size * 4, 32).astype(np.float32)
    Y = np.eye(10, dtype=np.float32)[np.argmax(X @ W, 1)]

    x = ht.placeholder_op("x")
    y_ = ht.placeholder_op("y")
    h = ht.layers.Linear(32, 64, activation="relu", name="mlp.fc1")(x)
    logits = ht.layers.Linear(64, 10, name="mlp.fc2")(h)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_), [0])
    ex = ht.Executor(
        {"train": [loss, ht.optim.AdamOptimizer(args.lr).minimize(loss)]},
        seed=0, dist_strategy=ht.dist.DataParallel())
    n = args.batch_size
    for i in range(args.steps):
        lo = (i * n) % (len(X) - n + 1)
        out = ex.run("train", feed_dict={x: X[lo:lo + n], y_: Y[lo:lo + n]})
        if jax.process_index() == 0 and i % 5 == 0:
            print(f"step {i} loss {float(out[0].asnumpy()):.4f}", flush=True)
    if jax.process_index() == 0:
        print("done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
