"""Wide&Deep runner with PS embedding flags (reference
``examples/runner/run_wdl.py`` + ctr cache flags, run_hetu.py:121-126).

    python examples/runner/run_wdl.py --cpu --embed-mode dense|ps|lru|lfu|lfuopt
"""
import argparse
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "examples", "ctr"))

if "--cpu" in sys.argv:
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np                      # noqa: E402

import hetu_tpu as ht                   # noqa: E402
import models as ctr                    # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--vocab", type=int, default=100000)
    p.add_argument("--embed-mode", default="lru",
                   choices=["dense", "ps", "lru", "lfu", "lfuopt",
                            "vlru", "vlfu", "vlru_dev", "vlfu_dev"])
    p.add_argument("--bsp", type=int, default=0,
                   help="0 BSP, -1 ASP, k>0 SSP staleness bound")
    args = p.parse_args()

    dense = ht.placeholder_op("dense")
    sparse = ht.placeholder_op("sparse", dtype=np.int64)
    y_ = ht.placeholder_op("y")
    loss, _prob = ctr.wdl_criteo(dense, sparse, y_, args.batch_size,
                                vocab=args.vocab, dim=16,
                                embed_mode=args.embed_mode, lr=0.01)
    ex = ht.Executor(
        {"train": [loss, ht.optim.SGDOptimizer(0.01).minimize(loss)]},
        seed=0, bsp=args.bsp)
    d_all, s_all, y_all = ctr.synthetic_criteo_skewed(
        args.steps * args.batch_size + args.batch_size, vocab=args.vocab)
    n = args.batch_size
    for i in range(args.steps):
        lo = i * n
        out = ex.run("train", feed_dict={dense: d_all[lo:lo + n],
                                         sparse: s_all[lo:lo + n],
                                         y_: y_all[lo:lo + n]})
        if i % 5 == 0:
            print(f"step {i} loss {float(out[0].asnumpy()):.4f}", flush=True)
    ex.ps_flush()
    print("done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
