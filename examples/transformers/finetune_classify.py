"""BERT sequence-classification fine-tuning — the reference's GLUE flow
(``examples/transformers/bert/test_glue_hetu_bert.py`` +
``scripts/``: pretrain → checkpoint → swap head → fine-tune), on a
synthetic sequence-level task so the example is hermetic.

    python examples/transformers/finetune_classify.py --cpu
    python examples/transformers/finetune_classify.py --cpu --dp  # 8-way

The pretrain checkpoint restores the encoder trunk BY NAME into the
classification graph (``models.bert_classify_graph``); the pooler and
classifier heads start fresh.  With ``--dp`` both phases run 8-way
data-parallel on a virtual CPU mesh.
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

if "--cpu" in sys.argv:  # must run before hetu_tpu/jax backend init
    if "--dp" in sys.argv and "host_platform_device_count" \
            not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8"
                                   ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

import hetu_tpu as ht  # noqa: E402
from hetu_tpu import models  # noqa: E402
from hetu_tpu.models.bert import synthetic_mlm_batch  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--dp", action="store_true", help="8-way data parallel")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--num-labels", type=int, default=3)
    p.add_argument("--pretrain-steps", type=int, default=10)
    p.add_argument("--finetune-steps", type=int, default=40)
    p.add_argument("--ckpt", default="/tmp/hetu_bert_pretrain_ckpt")
    args = p.parse_args()

    strat = ht.dist.DataParallel() if args.dp else None
    cfg = models.BertConfig.tiny(batch_size=args.batch_size,
                                 seq_len=args.seq_len,
                                 hidden_dropout_prob=0.0,
                                 attention_probs_dropout_prob=0.0)

    # -- phase 1: MLM pretraining --------------------------------------
    feeds, loss, _ = models.bert_pretrain_graph(cfg)
    ex = ht.Executor(
        {"train": [loss, ht.optim.AdamOptimizer(1e-3).minimize(loss)]},
        seed=0, dist_strategy=strat)
    ids, tt, labels, attn = synthetic_mlm_batch(cfg)
    fd = {feeds["input_ids"]: ids, feeds["token_type_ids"]: tt,
          feeds["masked_lm_labels"]: labels,
          feeds["attention_mask"]: attn}
    t0 = time.time()
    mlm_loss = float("nan")
    for i in range(args.pretrain_steps):
        mlm_loss = float(ex.run("train", feed_dict=fd)[0].asnumpy())
    print(f"pretrain: {args.pretrain_steps} steps, final MLM loss "
          f"{mlm_loss:.4f} ({time.time() - t0:.1f}s)")
    ex.save(args.ckpt)
    print(f"checkpoint -> {args.ckpt}")

    # -- phase 2: classification fine-tune (warm start) ----------------
    feeds2, loss2, logits2 = models.bert_classify_graph(
        cfg, num_labels=args.num_labels)
    ex2 = ht.Executor(
        {"train": [loss2, logits2,
                   ht.optim.AdamOptimizer(1e-3).minimize(loss2)]},
        seed=1, dist_strategy=strat)
    # params_only: restore the trunk, NOT the pretrain optimizer
    # moments or LR-schedule step (those belong to the old task)
    ex2.load(args.ckpt, params_only=True)
    rng = np.random.RandomState(7)
    f_ids = rng.randint(0, cfg.vocab_size,
                        (cfg.batch_size, cfg.seq_len)).astype(np.int32)
    # learnable sequence-level rule standing in for a GLUE task
    f_lab = (f_ids[:, 0] % args.num_labels).astype(np.int32)
    fd2 = {feeds2["input_ids"]: f_ids,
           feeds2["token_type_ids"]: np.zeros_like(f_ids),
           feeds2["labels"]: f_lab,
           feeds2["attention_mask"]: np.ones_like(f_ids)}
    t0 = time.time()
    out = None
    for i in range(args.finetune_steps):
        out = ex2.run("train", feed_dict=fd2)
    if out is None:
        print("finetune: 0 steps requested; nothing to report")
        return
    cls_loss = float(out[0].asnumpy())
    acc = float((np.argmax(out[1].asnumpy(), -1) == f_lab).mean())
    print(f"finetune: {args.finetune_steps} steps, loss {cls_loss:.4f}, "
          f"train acc {acc:.2f} ({time.time() - t0:.1f}s)")


if __name__ == "__main__":
    main()
