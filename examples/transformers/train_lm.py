"""Transformer-family training driver (reference ``examples/transformers/*``
per-model scripts, e.g. bert/train_hetu_bert_dp.py:68-69).

    python examples/transformers/train_lm.py --model bert --dp     # 8-way DP
    python examples/transformers/train_lm.py --model gpt2 --size tiny
    python examples/transformers/train_lm.py --model t5
    python examples/transformers/train_lm.py --model vit
    python examples/transformers/train_lm.py --model transformer
    python examples/transformers/train_lm.py --model bart|longformer|
        bigbird|reformer|transfoxl|xlnet|clip|mae|swin  # 14-family zoo
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

if "--cpu" in sys.argv:  # must run before hetu_tpu/jax backend init
    if any(a == "--cp" or a.startswith("--cp=") for a in sys.argv) \
            and "host_platform_device_count" \
            not in os.environ.get("XLA_FLAGS", ""):
        # a dp x cp mesh needs multiple (virtual) devices on CPU
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8"
                                   ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

import hetu_tpu as ht  # noqa: E402
from hetu_tpu import models  # noqa: E402


def build(model, size, batch_size, seq_len, cp_mode=None):
    if model == "bert":
        cfg = getattr(models.BertConfig, size)(batch_size=batch_size,
                                               seq_len=seq_len)
        feeds, loss, logits = models.bert_pretrain_graph(cfg)
        from hetu_tpu.models.bert import synthetic_mlm_batch
        ids, tt, labels, attn = synthetic_mlm_batch(cfg)
        vals = {"input_ids": ids, "token_type_ids": tt,
                "masked_lm_labels": labels, "attention_mask": attn}
    elif model == "gpt2":
        cfg = getattr(models.GPT2Config, size)(batch_size=batch_size,
                                               seq_len=seq_len)
        feeds, loss, logits = models.gpt2_lm_graph(cfg)
        ids, labels = models.synthetic_lm_batch(cfg)
        vals = {"input_ids": ids, "labels": labels}
    elif model == "t5":
        cfg = getattr(models.T5Config, size)(batch_size=batch_size,
                                             src_len=seq_len, tgt_len=seq_len,
                                             context_parallel=cp_mode)
        feeds, loss, logits = models.t5_seq2seq_graph(cfg)
        src, tgt_in, labels = models.synthetic_seq2seq_batch(cfg)
        vals = {"input_ids": src, "decoder_input_ids": tgt_in,
                "labels": labels}
    elif model == "vit":
        cfg = getattr(models.ViTConfig, size)(batch_size=batch_size)
        feeds, loss, logits = models.vit_classify_graph(cfg)
        imgs, y = models.synthetic_image_batch(cfg)
        vals = {"images": imgs, "labels": y}
    elif model == "swin":
        cfg = getattr(models.SwinConfig, size)(batch_size=batch_size)
        feeds, loss, logits = models.swin_classify_graph(cfg)
        imgs, y = models.synthetic_image_batch(cfg)
        vals = {"images": imgs, "labels": y}
    elif model == "bart":
        cfg = getattr(models.BartConfig, size)(batch_size=batch_size,
                                               src_len=seq_len,
                                               tgt_len=seq_len)
        feeds, loss, logits = models.bart_seq2seq_graph(cfg)
        rng = np.random.RandomState(0)
        src = rng.randint(0, cfg.vocab_size,
                          (batch_size, seq_len)).astype(np.int32)
        tgt = rng.randint(0, cfg.vocab_size,
                          (batch_size, seq_len + 1)).astype(np.int32)
        vals = {"input_ids": src, "decoder_input_ids": tgt[:, :-1],
                "labels": tgt[:, 1:]}
    elif model in ("longformer", "bigbird"):
        cls = models.LongformerConfig if model == "longformer" \
            else models.BigBirdConfig
        cfg = getattr(cls, size)(batch_size=batch_size, seq_len=seq_len)
        graph = models.longformer_mlm_graph if model == "longformer" \
            else models.bigbird_mlm_graph
        feeds, loss, logits = graph(cfg)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size,
                          (batch_size, cfg.seq_len)).astype(np.int32)
        labels = np.where(rng.rand(batch_size, cfg.seq_len) < 0.15,
                          ids, -1).astype(np.int32)
        vals = {"input_ids": ids, "labels": labels}
    elif model == "reformer":
        cfg = getattr(models.ReformerConfig, size)(
            batch_size=batch_size, seq_len=seq_len,
            chunk_length=min(seq_len, 16 if size == "tiny" else 64))
        feeds, loss, logits = models.reformer_lm_graph(cfg)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size,
                          (batch_size, cfg.seq_len + 1)).astype(np.int32)
        vals = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    elif model == "transfoxl":
        cfg = getattr(models.TransfoXLConfig, size)(batch_size=batch_size,
                                                    tgt_len=seq_len)
        feeds, loss, logits = models.transfoxl_lm_graph(cfg)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size,
                          (batch_size, seq_len + 1)).astype(np.int32)
        vals = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    elif model == "xlnet":
        cfg = getattr(models.XLNetConfig, size)(batch_size=batch_size,
                                                seq_len=seq_len)
        feeds, loss, logits = models.xlnet_plm_graph(cfg)
        ids, cmask, qmask, labels = models.synthetic_plm_batch(cfg)
        vals = {"input_ids": ids, "labels": labels,
                "content_mask": cmask, "query_mask": qmask}
    elif model == "clip":
        cfg = getattr(models.CLIPConfig, size)(batch_size=batch_size)
        feeds, loss, _ = models.clip_graph(cfg)
        rng = np.random.RandomState(0)
        imgs = rng.rand(batch_size, 3, cfg.image_size,
                        cfg.image_size).astype(np.float32)
        ids = rng.randint(0, cfg.vocab_size,
                          (batch_size, cfg.text_len)).astype(np.int32)
        vals = {"images": imgs, "input_ids": ids}
    elif model == "mae":
        cfg = getattr(models.MAEConfig, size)(batch_size=batch_size)
        feeds, loss, _ = models.mae_pretrain_graph(cfg)
        imgs, shuffle = models.synthetic_mae_batch(cfg)
        vals = {"images": imgs, "shuffle": shuffle}
    else:
        cfg = getattr(models.TransformerConfig, size)(
            batch_size=batch_size, src_len=seq_len, tgt_len=seq_len)
        feeds, loss, logits = models.transformer_graph(cfg)
        src, tgt_in, labels = models.synthetic_copy_batch(cfg)
        vals = {"src_ids": src, "tgt_ids": tgt_in, "labels": labels}
    return feeds, loss, vals


SIZES = {"bert": ["tiny", "base", "large"], "gpt2": ["tiny", "small",
                                                     "medium"],
         "t5": ["tiny", "small"], "vit": ["tiny", "base"],
         "swin": ["tiny", "base"],
         "transformer": ["tiny"],
         "bart": ["tiny", "base"], "longformer": ["tiny", "base"],
         "bigbird": ["tiny", "base"], "reformer": ["tiny", "base"],
         "transfoxl": ["tiny", "base"], "xlnet": ["tiny", "base"],
         "clip": ["tiny", "base"], "mae": ["tiny", "base"]}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="bert",
                   choices=list(SIZES))
    p.add_argument("--size", default="tiny")
    p.add_argument("--dp", action="store_true",
                   help="data-parallel over all local devices")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--cp", type=int, default=0,
                   help="context-parallel degree over a dp x cp mesh "
                        "(t5 only: ring/ulysses self-attention)")
    p.add_argument("--cp-mode", default="ring", choices=["ring", "ulysses"])
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (handled pre-import)")
    args = p.parse_args()
    if args.size not in SIZES[args.model]:
        p.error(f"--size {args.size!r} invalid for {args.model}; "
                f"choose from {SIZES[args.model]}")
    if args.cp and args.model != "t5":
        p.error("--cp currently applies to t5 (ring/ulysses self-attn)")

    feeds, loss, vals = build(args.model, args.size, args.batch_size,
                              args.seq_len,
                              cp_mode=args.cp_mode if args.cp else None)
    opt = ht.optim.AdamOptimizer(args.lr)
    if args.cp:
        import jax
        n = len(jax.devices())
        axes = {"dp": max(1, n // args.cp), "cp": args.cp}
        mesh = ht.make_mesh(axes)
        strategy = ht.dist.ModelParallel(axes)
        ex = ht.Executor({"train": [loss, opt.minimize(loss)]}, seed=0,
                         mesh=mesh, dist_strategy=strategy)
    else:
        strategy = ht.dist.DataParallel() if args.dp else None
        ex = ht.Executor({"train": [loss, opt.minimize(loss)]}, seed=0,
                         dist_strategy=strategy)
    fd = {feeds[k]: v for k, v in vals.items()}
    t0 = time.time()
    for it in range(args.iters):
        out = ex.run("train", feed_dict=fd)
        if it % 10 == 0 or it == args.iters - 1:
            print(f"iter {it:4d}  loss {float(out[0].asnumpy()):.4f}")
    dt = time.time() - t0
    print(f"{args.model}/{args.size}: {args.iters} iters, "
          f"{args.iters * args.batch_size / dt:.1f} samples/s")


if __name__ == "__main__":
    main()
