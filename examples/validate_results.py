"""Numerical validation of parallel modes against the single-device run.

Reference parity: ``examples/runner/parallel/validate_results.py`` +
``all_mlp_tests.sh`` (SURVEY.md §4.9) — the reference saves single-GPU
``std/*.npy`` weights and compares each mpirun configuration against them.
Here the comparisons run in ONE process on a simulated 8-device mesh
(``--xla_force_host_platform_device_count``), so the whole sweep is a
single command:

    python examples/validate_results.py            # all configs
    python examples/validate_results.py --configs dp8 pp4

Each config trains the same seeded MLP for a few steps and asserts the
loss trajectory matches the single-device run.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

import hetu_tpu as ht  # noqa: E402

STEPS = 5
RTOL = 1e-4


def _build(strategy=None, mesh=None, pipeline=None, placed=False,
           pp_block=False):
    import contextlib
    x = ht.placeholder_op("x", shape=(32, 16))
    y = ht.placeholder_op("y", shape=(32, 8))
    c0 = ht.context(ht.gpu(0)) if placed else contextlib.nullcontext()
    c1 = ht.context(ht.gpu(1)) if placed else contextlib.nullcontext()
    with c0:
        h = ht.layers.Linear(16, 32, activation="relu", name="v0")(x)
    with c1:
        if pp_block:
            h = ht.pipeline_block(
                h, lambda s: ht.layers.Linear(32, 32, activation="tanh",
                                              name="vp")(s),
                n_stages=4, n_microbatches=4)
        logits = ht.layers.Linear(32, 8, name="v1")(h)
        loss = ht.ops.reduce_mean_op(
            ht.ops.softmaxcrossentropy_op(logits, y), [0])
    opt = ht.optim.MomentumOptimizer(0.05)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]}, seed=42,
                     dist_strategy=strategy, mesh=mesh, pipeline=pipeline)
    return x, y, ex


def _losses(build_kwargs):
    rng = np.random.RandomState(7)
    xv = rng.randn(32, 16).astype(np.float32)
    yv = np.eye(8, dtype=np.float32)[rng.randint(0, 8, 32)]
    x, y, ex = _build(**build_kwargs)
    return [float(ex.run("train", feed_dict={x: xv, y: yv})[0].asnumpy())
            for _ in range(STEPS)]


CONFIGS = {
    "dp8": dict(strategy=ht.dist.DataParallel()),
    "pp4": dict(strategy=ht.parallel.PipelineParallel(pp=4), pp_block=True),
    "pp4_1f1b": dict(strategy=ht.parallel.PipelineParallel(pp=4),
                     pipeline="pipedream", pp_block=True),
    "dp2xpp2": dict(strategy=ht.parallel.PipelineParallel(pp=2, dp=2),
                    pp_block=True),
    "interop2": dict(placed=True),
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--configs", nargs="*", default=list(CONFIGS))
    args = p.parse_args()

    print(f"devices: {len(jax.devices())} ({jax.default_backend()})")
    failures = []
    for name in args.configs:
        kwargs = dict(CONFIGS[name])
        pp_block = kwargs.pop("pp_block", False)
        base = _losses(dict(pp_block=pp_block))
        got = _losses(dict(kwargs, pp_block=pp_block))
        ok = np.allclose(base, got, rtol=RTOL)
        status = "PASS" if ok else "FAIL"
        print(f"[{status}] {name:10s} single={['%.5f' % v for v in base]} "
              f"parallel={['%.5f' % v for v in got]}")
        if not ok:
            failures.append(name)
    if failures:
        print(f"FAILED: {failures}")
        sys.exit(1)
    print(f"all {len(args.configs)} parallel configs match the "
          "single-device run")


if __name__ == "__main__":
    main()
