"""hetu_tpu — a TPU-native deep learning framework.

A ground-up JAX/XLA/Pallas re-design with the capability surface of Hetu
(PKU DAIR's dataflow DL system, see SURVEY.md): define-then-run graph API,
executor, distributed strategies (DP/TP/PP/EP/CP) over ``jax.sharding`` device
meshes, MoE, host-resident embedding store with bounded-staleness cache,
auto-parallel search, tokenizers/ONNX/metrics tooling.

Typical use (identical shape to reference examples)::

    import hetu_tpu as ht
    x = ht.placeholder_op('x')
    w = ht.init.xavier_uniform((784, 10), name='w')
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    executor = ht.Executor({'train': [loss, train_op]})
    executor.run('train', feed_dict={...})
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 compat: the codebase targets the stable ``jax.shard_map``
    # API (``check_vma=`` keyword); older jaxlibs ship it as
    # ``jax.experimental.shard_map.shard_map`` with the keyword spelled
    # ``check_rep``.  Install an adapter so one spelling works everywhere.
    from jax.experimental.shard_map import shard_map as _esm

    def _shard_map_compat(f, mesh=None, in_specs=None, out_specs=None,
                          check_vma=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        return _esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    **kw)

    _jax.shard_map = _shard_map_compat

from . import initializers as init
from . import optim
from .optim import lr_scheduler as lr  # reference alias: ht.lr.StepScheduler
from . import context as _context_mod
from .context import (cpu, gpu, tpu, rcpu, rgpu, DLContext, DeviceGroup,
                      context, current_context, get_current_context,
                      DistConfig, make_mesh)
from .ndarray import (NDArray, NDSparseArray, array, empty, sparse_array,
                      IndexedSlices, is_gpu_ctx)
from .graph import (Op, PlaceholderOp, Variable, placeholder_op, gradients,
                    GradientOp, Executor, topo_sort,
                    worker_init, worker_finish, server_init, server_finish,
                    scheduler_init, scheduler_finish)
from .ops import *  # noqa: F401,F403 — full op surface (ht.matmul_op, ...)
from .data import Dataloader, DataloaderOp, GNNDataLoaderOp, dataloader_op
from . import data
from . import parallel
from . import parallel as dist  # reference alias: ht.dist.DataParallel
from .parallel.dispatch import dispatch
from .parallel.pipeline import pipeline_block, PipelineParallel
from .parallel.ring_attention import ContextParallel
from . import layers
from . import metrics
from . import obs
from . import chaos
from . import tokenizers
from .profiler import HetuProfiler, CollectiveProfiler
# reference script compat: ht.NCCLProfiler is the collectives
# profiler's name there (profiler.py:390); same surface here
NCCLProfiler = CollectiveProfiler
from . import analysis
from .analysis import lint, GraphValidationError
from . import autoparallel
from . import onnx
from . import gnn
from . import graphboard
from . import launcher
from .gnn import csrmm_op, csrmv_op, gcn_aggregate_op
from .launcher import init_distributed
from . import ps
from .ps import (EmbeddingStore, CacheSparseTable, ps_embedding_lookup_op,
                 default_store)
from . import serving
from .serving import InferenceExecutor, ServingRouter, ServeRejected

__version__ = "0.1.0"
