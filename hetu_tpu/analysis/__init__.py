"""Static graph analysis: total shape/dtype inference + lint rules.

``ht.lint(fetches, feeds=...)`` verifies a define-then-run graph BEFORE
anything compiles: an abstract interpreter (``jax.eval_shape`` over each
op's own lowering rule) assigns every node a static ``(shape, dtype)`` with
zero FLOPs, and a registry of lint rules turns graph bugs into diagnostics
that name the offending node and the user line that created it.

``Executor(validate='warn'|'error'|'off')`` (default ``'warn'``) runs the
same rules at construction and checks fed values against declared
placeholder shapes on every ``run()``.

The framework's own static analysis lives in ``tools/hetu_lint.py`` — an
AST pass gated by ``tests/test_lint.py`` — whose concurrency engine
(repo-wide lock-order + shared-state + blocking-under-lock detectors,
ISSUE 14) is this package's :mod:`~hetu_tpu.analysis.concurrency`, and
whose protocol model checker (exhaustive BFS verification of the PS
replication / decode recovery / elastic resize protocols plus the
trace-conformance layer, ISSUE 20) is :mod:`~hetu_tpu.analysis.protocol`.
"""
from .shapes import GraphShapes, abstract_infer_shape, infer_graph
from .lint import (RULES, Diagnostic, GraphInfo, GraphValidationError,
                   LintReport, lint, rule)
from . import concurrency  # noqa: F401  (stdlib-only; ISSUE 14 verifier)
from . import protocol  # noqa: F401  (stdlib-only; ISSUE 20 checker)

__all__ = ["GraphShapes", "abstract_infer_shape", "infer_graph",
           "RULES", "Diagnostic", "GraphInfo", "GraphValidationError",
           "LintReport", "lint", "rule", "concurrency", "protocol"]
