"""Static concurrency verifier — repo-wide lock-order + shared-state lint
(ISSUE 14 tentpole, static pass).

The system runs at least eight concurrent host-side planes (feed
pipeline, serve router, emb-refresh sweeper, ps-serve handler threads,
heartbeat pinger, elastic controller, tracer rings, metricsd exporter),
and the PR 3-13 review logs show the same failure class repeatedly:
races and lock-discipline holes found only by human review (the
``set_result``/cancel race in the serving router, ``refresh_stale`` RPCs
under the cache lock, the commit-vs-evict window, the GC-reentrancy
drain deadlock).  The PR 5 self-lint proved the approach on one package
(``ps/``); this module grows it into a first-class verifier over the
WHOLE package, wired as ``tools/hetu_lint.py --concurrency`` and gated
at zero findings by ``tests/test_lint.py``.

Model
-----
One pass over ``{filename: source}`` builds a :class:`Model`:

* per-class **lock inventory** — every ``self.x = threading.Lock()`` /
  ``RLock`` / ``Condition`` / ``Semaphore`` / ``Event`` assignment, with
  its creation site (file:line) for provenance; module-level
  ``NAME = threading.Lock()`` assignments join as ``<module>.NAME``;
* per-method **acquisition scans** — ``with self._x_lock:`` nesting
  edges, same-class calls made while holding a lock, attribute-typed
  calls (``self.store.push(...)``) resolved ACROSS modules through the
  class's ``self.attr = ClassName(...)`` constructor assignments, writes
  to ``self.*`` attributes with the lock set held at each write, calls
  from a blocking-call blocklist, and ``Condition.wait`` sites;
* **thread entrypoints** — ``threading.Thread(target=...)`` targets,
  executor ``submit(...)`` callables and local closures handed to
  either, each closed transitively over same-class calls into a
  "thread plane" per entrypoint.

Detectors (each proven live by a synthetic-violation test)
----------------------------------------------------------
``lock-order``
    acquisition-order cycles (ABBA deadlocks) over the GLOBAL lock
    graph — lexical nesting plus held-call propagation, including
    cross-class edges through resolved attribute calls.
``lock-reentry``
    re-entrant acquisition of a non-reentrant ``threading.Lock``
    (self-deadlock), including re-entry through a call chain.
``shared-state-without-lock``
    a mutable ``self.*`` attribute written both from a discovered
    thread entrypoint's plane and from another plane, where the two
    writes share no common lock (``__init__`` writes are construction,
    not sharing, and are exempt).
``blocking-call-under-lock``
    an RPC / ``.result()`` / ``.join()`` / ``device_put`` /
    ``time.sleep`` style blocking call made while a lock is held —
    directly or through a call chain (the exact ``refresh_stale``-
    under-the-cache-lock bug class).
``wait-without-predicate-loop``
    ``Condition.wait()`` whose surrounding code does not re-check a
    predicate in a ``while`` loop (missed-wakeup / spurious-wakeup
    hazard; ``wait_for`` carries its own loop, ``Event.wait`` has no
    predicate to re-check).

Justified allowlist
-------------------
Intentional violations are DOCUMENTED, not silenced: the flagged line
(or the ``with`` statement that holds the lock) carries a marker
comment with a MANDATORY reason::

    with self._repl_lock:        # lint: held-rpc-ok apply+mirror is one
                                 # critical section (backup sees primary order)
        self.rpc_fn(...)

Tokens: ``held-rpc-ok`` (blocking-call-under-lock), ``unlocked-ok``
(shared-state-without-lock), ``lock-order-ok`` (cycles), ``reentry-ok``
(non-reentrant re-entry), ``wait-loop-ok`` (predicate-loop).  A marker
with no reason text is itself a finding.

The static pass cannot see through ``ctypes``, sockets or callbacks —
the runtime twin (:mod:`hetu_tpu.obs.lock_witness`) records the REAL
acquisition graph under ``HETU_LOCK_WITNESS=1`` and catches orders this
pass can't.  This module is deliberately stdlib-only so
``tools/hetu_lint.py`` can load it without importing the package.
"""
from __future__ import annotations

import ast
import os

#: attribute-name tokens that mark a with-item as a lock even when the
#: class inventory cannot see its construction (e.g. a lock handed in)
LOCK_TOKENS = ("lock", "cond", "_cv", "mutex")

#: constructors the per-class inventory recognizes — the raw threading
#: primitives plus the witness factories (``obs.lock_witness``) the
#: instrumented call sites use
LOCK_CTORS = {
    "Lock": "Lock", "RLock": "RLock", "Condition": "Condition",
    "Semaphore": "Semaphore", "BoundedSemaphore": "BoundedSemaphore",
    "Event": "Event",
    "make_lock": "Lock", "make_rlock": "RLock",
    "make_condition": "Condition",
}

#: lock kinds that may be re-acquired by the holding thread
REENTRANT = {"RLock", "Condition"}   # Condition defaults to an RLock

#: method names treated as blocking when called while a lock is held.
#: RPC/transport verbs (the PS client surface + raw sockets), future /
#: thread joins, sleeps, and host<->device transfers.  ``wait`` /
#: ``wait_for`` are NOT here: a Condition wait releases its own lock
#: (the predicate-loop detector owns those sites).
BLOCKING_CALLS = {
    "result", "join", "sleep", "recv", "recv_into", "sendall", "send",
    "accept", "connect", "pull", "push", "push_pull", "versions",
    "_rpc", "rpc_fn", "ssp_sync", "device_put", "block_until_ready",
    "urlopen", "getaddrinfo",
}

#: allowlist marker tokens per detector
ALLOW_TOKENS = ("held-rpc-ok", "unlocked-ok", "lock-order-ok",
                "reentry-ok", "wait-loop-ok")


# --------------------------------------------------------------- allowlist

class _Allow:
    """Per-file ``# lint: <token> <reason>`` markers, by line."""

    def __init__(self, src):
        self.by_line = {}           # lineno -> (token, reason)
        self.bad = []               # linenos with a token but no reason
        for i, line in enumerate(src.splitlines(), 1):
            if "# lint:" not in line:
                continue
            body = line.split("# lint:", 1)[1].strip()
            for tok in ALLOW_TOKENS:
                if body.startswith(tok):
                    reason = body[len(tok):].strip()
                    self.by_line[i] = (tok, reason)
                    if not reason:
                        self.bad.append((i, tok))
                    break

    def ok(self, token, *linenos):
        """True iff any of ``linenos`` — or the line directly above one
        (the standard marker-comment-above-the-statement placement) —
        carries a justified ``token`` marker (reason text present)."""
        for ln in linenos:
            for cand in (ln, ln - 1):
                ent = self.by_line.get(cand)
                if ent and ent[0] == token and ent[1]:
                    return True
        return False


# ------------------------------------------------------------------ scans

def _call_name(func):
    """Constructor/callee name of a Call's func node."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _self_attr(expr):
    """'x' for ``self.x``, else None."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """One method (or thread-target closure): lock acquisitions, nesting,
    held calls, attribute writes, blocking calls, waits, thread spawns."""

    def __init__(self, cls, name, assigns, lock_attrs):
        self.cls = cls              # _ClassModel
        self.name = name
        self.assigns = assigns      # local name -> value expr
        self.lock_attrs = lock_attrs
        self.held = []              # stack of (lock id or None=anonymous,
                                    #           with-stmt lineno)
        self.acquires = {}          # lock id -> first with lineno
        self.edges = set()          # (outer, inner, lineno)
        self.self_calls = set()     # same-class method names called
        self.site_calls = []        # (callee, frozenset(held)) per site
        self.attr_calls = set()     # (self-attr, method) calls, any context
        self.calls_under = []       # (lock, with_ln, kind, target, call_ln)
        self.writes = []            # (attr, frozenset(held ids), lineno)
        self.blocking = []          # (desc, lineno)  own direct blocking
        self.waits = []             # (recv id, lineno, in_while)
        self.spawns = []            # (target method name, lineno)
        self._loops = []            # While/For stack

    # -- lock identity ----------------------------------------------------
    def _lock_of(self, expr):
        """(lock id or None, known) — id like 'Cls.attr', 'Cls.attr[*]'
        or '<module>.NAME'; ``known`` True when the expr is lock-like at
        all (an anonymous lock still counts as held)."""
        attr = _self_attr(expr)
        if attr is not None:
            if attr in self.lock_attrs or \
                    any(t in attr.lower() for t in LOCK_TOKENS):
                return f"{self.cls.name}.{attr}", True
            return None, False
        if isinstance(expr, ast.Name):
            if expr.id in self.cls.module_locks:
                return f"<module {self.cls.file}>.{expr.id}", True
            src = self.assigns.get(expr.id)
            if src is not None:
                for sub in ast.walk(src):
                    a = _self_attr(sub)
                    if a is not None and (
                            a in self.lock_attrs or
                            any(t in a.lower() for t in LOCK_TOKENS)):
                        return f"{self.cls.name}.{a}[*]", True
                # a lock reached through another object: anonymous —
                # held for blocking checks, absent from the order graph
                for sub in ast.walk(src):
                    if isinstance(sub, ast.Attribute) and any(
                            t in sub.attr.lower() for t in LOCK_TOKENS):
                        return None, True
            return None, False
        if isinstance(expr, ast.Attribute) and any(
                t in expr.attr.lower() for t in LOCK_TOKENS):
            # obj._lock for a non-self obj: anonymous held lock
            return None, True
        return None, False

    # -- visitors ---------------------------------------------------------
    def visit_With(self, node):
        # items acquire LEFT TO RIGHT, so `with a, b:` orders a before b
        # exactly like nested withs — each item sees the earlier ones
        # already on the held stack (review finding: computing edges
        # before pushing any item missed multi-item ABBA halves)
        pushed = 0
        for item in node.items:
            lid, known = self._lock_of(item.context_expr)
            if not known:
                continue
            if lid is not None:
                self.acquires.setdefault(lid, node.lineno)
                for outer, _ln in self.held:
                    if outer is not None:
                        self.edges.add((outer, lid, node.lineno))
            self.held.append((lid, node.lineno))
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def visit_While(self, node):
        self._loops.append(node)
        self.generic_visit(node)
        self._loops.pop()

    def _note_call(self, node):
        fn = node.func
        cname = _call_name(fn)
        call_ln = node.lineno
        # thread spawns: Thread(target=...), pool.submit(fn, ...)
        if cname == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    self._note_spawn(kw.value, call_ln)
        elif cname in ("submit", "start_new_thread") and node.args:
            self._note_spawn(node.args[0], call_ln)
        # same-class call / resolved attribute call (any context: both
        # feed the reachability closures even when no lock is held)
        target = None
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                self.self_calls.add(fn.attr)
                self.site_calls.append((fn.attr, frozenset(
                    l for l, _ in self.held if l is not None)))
                target = ("self", (fn.attr,))
            else:
                a = _self_attr(recv)
                if a is not None:
                    self.attr_calls.add((a, fn.attr))
                    target = ("attr", (a, fn.attr))
        if cname in BLOCKING_CALLS:
            desc = ast.unparse(fn) if hasattr(ast, "unparse") \
                else str(cname)
            # direct blocking site (held or not: callers holding a lock
            # reach it through the call-chain closure)
            self.blocking.append((desc, call_ln))
            if self.held:
                innermost = self.held[-1]
                self.calls_under.append(
                    (innermost[0], innermost[1], "blocking", desc, call_ln))
        if target is not None and self.held:
            for lid, wln in self.held:
                if lid is not None:
                    self.calls_under.append(
                        (lid, wln, target[0], target[1], call_ln))
        # condition waits
        if isinstance(fn, ast.Attribute) and fn.attr == "wait":
            lid, known = self._lock_of(fn.value)
            if lid is not None or known:
                self.waits.append((lid, call_ln, bool(self._loops)))

    def _note_spawn(self, target, lineno):
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            self.spawns.append((target.attr, lineno))
        elif isinstance(target, ast.Name):
            # a local closure: scan it as its own entrypoint body
            self.spawns.append((f"{self.name}.<{target.id}>", lineno))
        elif isinstance(target, ast.Lambda):
            # an inline lambda target: its body runs on the spawned
            # thread's plane (registered as a pseudo-method by
            # _scan_class under the same lineno-keyed name)
            self.spawns.append(
                (f"{self.name}.<lambda@{target.lineno}>", lineno))

    def visit_Call(self, node):
        self._note_call(node)
        self.generic_visit(node)

    def _note_write(self, tgt, lineno):
        attr = _self_attr(tgt)
        if attr is None and isinstance(tgt, ast.Subscript):
            attr = _self_attr(tgt.value)      # self.x[...] = ...
        if attr is not None and attr not in self.lock_attrs:
            locks = frozenset(l for l, _ in self.held if l is not None)
            self.writes.append((attr, locks, lineno))

    def visit_Assign(self, node):
        for tgt in node.targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                for el in tgt.elts:
                    self._note_write(el, node.lineno)
            else:
                self._note_write(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._note_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # nested defs are scanned separately when spawned; their bodies
        # must not leak writes/acquires into the enclosing method scan
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        # a lambda body is DEFERRED — `submit(lambda: self.pull())`
        # under a lock runs the pull on the pool thread after the lock
        # is long released, so scanning it inline manufactured a false
        # blocking-call-under-lock (review finding); like nested defs,
        # lambdas are scanned as their own pseudo-methods when spawned
        pass


def _name_assigns(func):
    """local name -> value expr for simple assignments inside ``func``."""
    out = {}
    for node in ast.walk(func):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    for el in tgt.elts:
                        if isinstance(el, ast.Name):
                            out[el.id] = node.value
    return out


class _ClassModel:
    """One class: lock inventory, method scans, attr->class bindings."""

    def __init__(self, name, file, module_locks):
        self.name = name
        self.file = file
        self.module_locks = module_locks    # module NAME -> (ctor, lineno)
        self.locks = {}         # attr -> (ctor kind, lineno)
        self.methods = {}       # method name -> _MethodScan
        self.attr_classes = {}  # attr -> class name (self.x = Cls(...))
        self.entrypoints = {}   # method name -> spawn lineno


def _scan_class(cls_node, fname, module_locks, registry, reg_name=None):
    cm = _ClassModel(reg_name or cls_node.name, fname, module_locks)
    # lock inventory + attr->class bindings (anywhere in the class body)
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            attr = _self_attr(tgt)
            if attr is None or not isinstance(node.value, ast.Call):
                continue
            ctor = _call_name(node.value.func)
            if ctor in LOCK_CTORS:
                cm.locks.setdefault(attr, (LOCK_CTORS[ctor], node.lineno))
            elif ctor is not None and ctor[:1].isupper():
                cm.attr_classes.setdefault(attr, ctor)
    lock_attrs = set(cm.locks)
    # method scans (closures handed to Thread/submit become their own
    # pseudo-methods so their writes land on the right plane)
    for meth in cls_node.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        assigns = _name_assigns(meth)
        scan = _MethodScan(cm, meth.name, assigns, lock_attrs)
        for stmt in meth.body:
            scan.visit(stmt)
        cm.methods[meth.name] = scan
        for target, ln in scan.spawns:
            cm.entrypoints.setdefault(target, ln)
        # nested closures: scan each local def as "<meth>.<name>" and
        # each lambda as "<meth>.<lambda@line>" (a lambda body cannot
        # contain assignments, but its CALLS feed the thread-plane
        # closure when the lambda is a Thread/submit target)
        for node in ast.walk(meth):
            if isinstance(node, ast.FunctionDef) and node is not meth:
                sub = _MethodScan(cm, f"{meth.name}.<{node.name}>",
                                  assigns, lock_attrs)
                for stmt in node.body:
                    sub.visit(stmt)
                cm.methods[f"{meth.name}.<{node.name}>"] = sub
            elif isinstance(node, ast.Lambda):
                sub = _MethodScan(cm, f"{meth.name}.<lambda@{node.lineno}>",
                                  assigns, lock_attrs)
                sub.visit(node.body)
                cm.methods[f"{meth.name}.<lambda@{node.lineno}>"] = sub
    registry[cm.name] = cm
    return cm


class Model:
    """The parsed repo: classes by name, module locks, sources, allows."""

    def __init__(self):
        self.classes = {}       # class name -> _ClassModel
        self.files = {}         # class name -> filename
        self.allows = {}        # filename -> _Allow
        self.errors = []


def build_model(sources):
    """Parse ``{filename: source}`` into a :class:`Model`.

    Classes are registered under their BARE name when it is unique
    across the source set (so ``self.store = DistributedStore(...)``
    resolves cross-module), and under ``Name@file`` when two files
    define the same class name — a shadowed duplicate silently dropped
    from analysis would make the zero-findings gate vacuous for it
    (review finding); attribute resolution to an ambiguous name is
    skipped conservatively."""
    model = Model()
    parsed = []
    name_counts = {}
    for fname, src in sorted(sources.items()):
        model.allows[fname] = _Allow(src)
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            model.errors.append(f"{fname}: syntax error: {e}")
            continue
        parsed.append((fname, tree))
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                name_counts[node.name] = name_counts.get(node.name, 0) + 1
    for fname, tree in parsed:
        module_locks = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                ctor = _call_name(node.value.func)
                if ctor in LOCK_CTORS:
                    module_locks[node.targets[0].id] = (
                        LOCK_CTORS[ctor], node.lineno)
        for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
            reg_name = cls.name if name_counts.get(cls.name, 0) == 1 \
                else f"{cls.name}@{fname}"
            cm = _scan_class(cls, fname, module_locks, model.classes,
                             reg_name)
            model.files[cm.name] = fname
        # module-level functions form a pseudo-class so ``with _LOCK:``
        # nesting in module code still reaches the graph
        pseudo = _ClassModel(f"<module {fname}>", fname, module_locks)
        for fn in [n for n in tree.body if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
            scan = _MethodScan(pseudo, fn.name, _name_assigns(fn), set())
            for stmt in fn.body:
                scan.visit(stmt)
            pseudo.methods[fn.name] = scan
        model.classes[pseudo.name] = pseudo
        model.files[pseudo.name] = fname
    return model


# --------------------------------------------------------- the reachability

def _eventual_acquires(model):
    """method (cls, name) -> set of lock ids it may acquire, closed over
    same-class calls AND attribute calls resolved to other classes."""
    ev = {}
    for cname, cm in model.classes.items():
        for mname, scan in cm.methods.items():
            ev[(cname, mname)] = set(scan.acquires)
    changed = True
    while changed:
        changed = False
        for cname, cm in model.classes.items():
            for mname, scan in cm.methods.items():
                cur = ev[(cname, mname)]
                for callee in scan.self_calls:
                    extra = ev.get((cname, callee), set()) - cur
                    if extra:
                        cur |= extra
                        changed = True
                for attr, meth in scan.attr_calls:
                    tcls = cm.attr_classes.get(attr)
                    if tcls and (tcls, meth) in ev:
                        extra = ev[(tcls, meth)] - cur
                        if extra:
                            cur |= extra
                            changed = True
    return ev


def _eventual_blocking(model):
    """method (cls, name) -> {(desc, lineno)} of blocking calls
    reachable through same-class calls.  Facts propagate UNCHANGED —
    the finding names the immediate callee plus the blocking site's
    file:line, which is the provenance that matters; re-wrapping a
    chain tag per hop made the fixpoint non-monotone and looped forever
    on mutually recursive methods (review finding: a 14-line synthetic
    hung the tier-1 gate)."""
    ev = {}
    for cname, cm in model.classes.items():
        for mname, scan in cm.methods.items():
            ev[(cname, mname)] = set(scan.blocking)
    changed = True
    while changed:
        changed = False
        for cname, cm in model.classes.items():
            for mname, scan in cm.methods.items():
                cur = ev[(cname, mname)]
                for callee in scan.self_calls:
                    extra = ev.get((cname, callee), set()) - cur
                    if extra:
                        cur |= extra
                        changed = True
    return ev


def _caller_context_locks(cm):
    """{method -> frozenset of locks held at EVERY same-class call site
    of that method}, transitively (a helper only ever reached under a
    lock inherits it — ``_advance_unlocked``-style naming conventions
    become checked facts instead of hoped-for ones).  Methods with no
    in-class caller (public entry points, thread targets) inherit
    nothing."""
    all_locks = frozenset()
    for scan in cm.methods.values():
        all_locks |= frozenset(scan.acquires)
        for _, held in scan.site_calls:
            all_locks |= held
    # top = all locks; intersect downwards to a fixpoint
    eff = {m: all_locks for m in cm.methods}
    # entry points (no in-class caller) pin to empty
    called = {c for scan in cm.methods.values()
              for c, _ in scan.site_calls}
    for m in cm.methods:
        if m not in called:
            eff[m] = frozenset()
    changed = True
    while changed:
        changed = False
        for mname, scan in cm.methods.items():
            for callee, held in scan.site_calls:
                if callee not in eff:
                    continue
                ctx = held | eff[mname]
                new = eff[callee] & ctx
                if new != eff[callee]:
                    eff[callee] = new
                    changed = True
    return eff


def _thread_planes(cm):
    """{entrypoint -> set of methods reachable from it via self-calls}."""
    planes = {}
    for entry in cm.entrypoints:
        seen, stack = set(), [entry]
        while stack:
            m = stack.pop()
            if m in seen or m not in cm.methods:
                continue
            seen.add(m)
            stack.extend(cm.methods[m].self_calls)
        planes[entry] = seen
    return planes


# ----------------------------------------------------------------- findings

def _split_lock_id(lid):
    """('ClsOrModule', 'attr') — attr never contains a dot, so split on
    the LAST one (module pseudo-class names carry '.py')."""
    cls, _, attr = lid.rpartition(".")
    if attr.endswith("[*]"):
        attr = attr[:-3]
    return cls, attr


def _lock_site(model, lid):
    """'file:line' of a lock id's creation, for provenance."""
    cls, attr = _split_lock_id(lid)
    cm = model.classes.get(cls)
    if cm is None:
        return "?"
    if attr in cm.locks:
        return f"{cm.file}:{cm.locks[attr][1]}"
    if attr in cm.module_locks:
        return f"{cm.file}:{cm.module_locks[attr][1]}"
    return cm.file


def _lock_kind(model, lid):
    cls, attr = _split_lock_id(lid)
    cm = model.classes.get(cls)
    if cm is None:
        return None
    if attr in cm.locks:
        return cm.locks[attr][0]
    if attr in cm.module_locks:
        return cm.module_locks[attr][0]
    return None


def check_lock_graph(model):
    """ABBA cycles + non-reentrant re-entry over the global lock graph."""
    findings = []
    ev = _eventual_acquires(model)
    # order edges AND self-edges (re-entry candidates) keep EVERY site:
    # the allowlist is judged per site, never at a first-seen proxy —
    # a 'reentry-ok' marker on one re-entry cannot silence a different
    # unguarded one, and a 'lock-order-ok' marker only excuses an edge
    # when EVERY site producing it is annotated (an unannotated
    # duplicate site creates the same cycle on its own; review
    # findings: the shared-state per-pair rule, applied here too)
    edges = {}              # (a, b) -> [(file, lineno, allow), ...]
    reentries = []          # (lock id, file, lineno, allow) per site

    def note(a, b, fname, ln, allow):
        if a == b:
            reentries.append((a, fname, ln, allow))
        else:
            sites = edges.setdefault((a, b), [])
            if (fname, ln) not in [(f, l) for f, l, _ in sites]:
                sites.append((fname, ln, allow))

    for cname, cm in model.classes.items():
        allow = model.allows.get(cm.file)
        for mname, scan in cm.methods.items():
            for outer, inner, ln in scan.edges:
                note(outer, inner, cm.file, ln, allow)
            for entry in scan.calls_under:
                lid, wln, kind = entry[0], entry[1], entry[2]
                if lid is None or kind == "blocking":
                    continue
                if kind == "self":
                    key = (cname, entry[3][0])
                elif kind == "attr":
                    attr, meth = entry[3]
                    tcls = cm.attr_classes.get(attr)
                    if not tcls:
                        continue
                    key = (tcls, meth)
                else:
                    continue
                for inner in ev.get(key, ()):
                    note(lid, inner, cm.file, entry[4], allow)
    # a lock whose construction the inventory cannot see (handed in via
    # a parameter) has unknown kind: assume NON-reentrant — silently
    # skipping it would pass a guaranteed self-deadlock through the
    # zero-findings gate (review finding; the pre-ISSUE-14 ps/-local
    # pass defaulted unknown locks to Lock for exactly this reason)
    seen_sites = set()
    for lid, fname, ln, allow in reentries:
        kind = _lock_kind(model, lid)
        if kind in REENTRANT:
            continue
        if allow is not None and allow.ok("reentry-ok", ln):
            continue
        if (lid, fname, ln) in seen_sites:
            continue
        seen_sites.add((lid, fname, ln))
        desc = f"non-reentrant lock '{lid}' (created " \
            f"{_lock_site(model, lid)})" if kind is not None else \
            f"lock '{lid}' of unknown construction (assumed " \
            f"non-reentrant)"
        findings.append(
            f"{fname}:{ln}: lock-reentry: {desc} acquired "
            f"while already held (self-deadlock); use an RLock "
            f"or annotate '# lint: reentry-ok <reason>'")
    graph = {}
    for (a, b), sites in edges.items():
        graph.setdefault(a, set()).add(b)
    # cycle detection (DFS, white/grey/black), findings per distinct cycle
    color, stack, seen_cycles = {}, [], set()

    def dfs(n):
        color[n] = 1
        stack.append(n)
        for nxt in sorted(graph.get(n, ())):
            if color.get(nxt, 0) == 1:
                cyc = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key in seen_cycles:
                    continue
                seen_cycles.add(key)
                prov = []
                allowed = False
                for x, y in zip(cyc, cyc[1:]):
                    sites = edges[(x, y)]
                    fname, ln, _ = sites[0]
                    extra = f" (+{len(sites) - 1} more site(s))" \
                        if len(sites) > 1 else ""
                    prov.append(f"{x} -> {y} at {fname}:{ln}{extra}")
                    if all(allow is not None and
                           allow.ok("lock-order-ok", ln)
                           for _, ln, allow in sites):
                        allowed = True
                if not allowed:
                    findings.append(
                        "lock-order: acquisition-order cycle (ABBA "
                        "deadlock): " + "; ".join(prov) +
                        " — pick one order or annotate EVERY site of "
                        "one edge '# lint: lock-order-ok <reason>'")
            elif color.get(nxt, 0) == 0:
                dfs(nxt)
        stack.pop()
        color[n] = 2

    for n in sorted(graph):
        if color.get(n, 0) == 0:
            dfs(n)
    return findings


def check_blocking_under_lock(model):
    """Blocking calls (RPC, .result(), .join(), sleeps, transfers) made
    while any lock is held — directly or one call-chain away."""
    findings = []
    evb = _eventual_blocking(model)
    for cname, cm in model.classes.items():
        allow = model.allows.get(cm.file)
        for mname, scan in cm.methods.items():
            for entry in scan.calls_under:
                lid, wln, kind = entry[0], entry[1], entry[2]
                if kind == "blocking":
                    desc, ln = entry[3], entry[4]
                    if allow and allow.ok("held-rpc-ok", ln, wln):
                        continue
                    lname = lid if lid is not None else "a lock"
                    site = f" (created {_lock_site(model, lid)})" \
                        if lid is not None else ""
                    findings.append(
                        f"{cm.file}:{ln}: blocking-call-under-lock: "
                        f"'{desc}(...)' while holding {lname}{site} — "
                        f"an RPC/join under a lock stalls every thread "
                        f"contending for it (the refresh_stale bug "
                        f"class); move the call outside the critical "
                        f"section or annotate '# lint: held-rpc-ok "
                        f"<reason>'")
                elif kind == "self":
                    callee = entry[3][0]
                    for desc, bln in evb.get((cname, callee), ()):
                        if allow and allow.ok("held-rpc-ok",
                                              entry[4], wln, bln):
                            continue
                        lname = lid if lid is not None else "a lock"
                        findings.append(
                            f"{cm.file}:{entry[4]}: blocking-call-under-"
                            f"lock: '{callee}()' reaches blocking "
                            f"'{desc}' ({cm.file}:{bln}) while holding "
                            f"{lname} — move the round trip outside or "
                            f"annotate '# lint: held-rpc-ok <reason>'")
    return findings


def check_shared_state(model):
    """Mutable attributes written from a thread entrypoint's plane and
    from another plane with no common lock."""
    findings = []
    for cname, cm in model.classes.items():
        if not cm.entrypoints:
            continue
        allow = model.allows.get(cm.file)
        planes = _thread_planes(cm)
        # method -> set of plane tags ("main" or entrypoint name)
        plane_of = {}
        for m in cm.methods:
            tags = {e for e, ms in planes.items() if m in ms}
            plane_of[m] = tags or {"main"}
        eff_ctx = _caller_context_locks(cm)
        # attr -> [(plane tag, locks, lineno, method)]
        writes = {}
        for mname, scan in cm.methods.items():
            if mname == "__init__":
                continue    # construction precedes sharing
            inherited = eff_ctx.get(mname, frozenset())
            for attr, locks, ln in scan.writes:
                for tag in plane_of[mname]:
                    writes.setdefault(attr, []).append(
                        (tag, locks | inherited, ln, mname))
        for attr, ws in sorted(writes.items()):
            tags = {t for t, _, _, _ in ws}
            if len(tags) < 2 or tags == {"main"}:
                continue
            # conflicting pair: two writes on different planes sharing
            # no lock.  The allowlist applies PER PAIR — a marker on one
            # write must not silence a different unguarded pair on other
            # planes (review finding) — and the first non-allowlisted
            # pair is reported (one finding per attribute).
            hit = None
            for i, (t1, l1, ln1, m1) in enumerate(ws):
                for t2, l2, ln2, m2 in ws[i + 1:]:
                    if t1 != t2 and not (l1 & l2) and not (
                            allow and allow.ok("unlocked-ok", ln1, ln2)):
                        hit = (t1, ln1, m1, t2, ln2, m2, l1, l2)
                        break
                if hit:
                    break
            if hit is None:
                continue
            t1, ln1, m1, t2, ln2, m2, l1, l2 = hit
            ep = t1 if t1 != "main" else t2
            spawn_ln = cm.entrypoints.get(ep, 0)
            lockhint = ""
            owner = (l1 | l2)
            if owner:
                own = sorted(owner)[0]
                lockhint = (f"; its other write holds '{own}' "
                            f"(created {_lock_site(model, own)})")
            findings.append(
                f"{cm.file}:{ln1}: shared-state-without-lock: "
                f"{cname}.{attr} written in {m1}() [{t1}] and {m2}() "
                f"({cm.file}:{ln2}) [{t2}] with no common lock — "
                f"'{ep}' runs as a thread entrypoint (started "
                f"{cm.file}:{spawn_ln}){lockhint}; guard both writes "
                f"with one lock or annotate '# lint: unlocked-ok "
                f"<reason>'")
    return findings


def check_wait_loops(model):
    """Condition.wait sites outside a predicate-rechecking while loop."""
    findings = []
    for cname, cm in model.classes.items():
        allow = model.allows.get(cm.file)
        for mname, scan in cm.methods.items():
            for lid, ln, in_while in scan.waits:
                if in_while or lid is None:
                    continue
                kind = _lock_kind(model, lid)
                if kind is not None and kind != "Condition":
                    # Event.wait has no predicate to re-check; a plain
                    # Lock/RLock has no .wait at all (attr name reuse)
                    continue
                if kind is None and "cond" not in lid.lower() \
                        and "_cv" not in lid.lower():
                    continue    # inventory-less + not condition-named
                if allow and allow.ok("wait-loop-ok", ln):
                    continue
                name = lid or "a condition"
                findings.append(
                    f"{cm.file}:{ln}: wait-without-predicate-loop: "
                    f"'{name}.wait()' outside a while loop — a spurious "
                    f"or stolen wakeup proceeds on a false predicate; "
                    f"wrap in 'while not <predicate>:' (or wait_for) or "
                    f"annotate '# lint: wait-loop-ok <reason>'")
    return findings


def check_allowlist(model):
    """A marker with no reason silences nothing and is itself a finding."""
    findings = []
    for fname, allow in sorted(model.allows.items()):
        for ln, tok in allow.bad:
            findings.append(
                f"{fname}:{ln}: allowlist marker '# lint: {tok}' has no "
                f"reason text — intentional holds are documented, not "
                f"silenced")
    return findings


def check_concurrency(sources):
    """All detectors over ``{filename: source}`` — the entry point
    ``tools/hetu_lint.py --concurrency`` and the tier-1 gate call."""
    model = build_model(sources)
    findings = list(model.errors)
    findings += check_lock_graph(model)
    findings += check_blocking_under_lock(model)
    findings += check_shared_state(model)
    findings += check_wait_loops(model)
    findings += check_allowlist(model)
    return findings


def scan_package(root):
    """{relpath: source} over ``root``'s ``hetu_tpu`` tree (every plane:
    ps/, serving/, parallel/, graph/, obs/, data/ and the top-level
    modules)."""
    out = {}
    base = os.path.join(root, "hetu_tpu")
    for dirpath, _, files in os.walk(base):
        if "__pycache__" in dirpath:
            continue
        for f in sorted(files):
            if f.endswith(".py"):
                p = os.path.join(dirpath, f)
                with open(p, encoding="utf-8") as fh:
                    out[os.path.relpath(p, root)] = fh.read()
    return out


__all__ = ["check_concurrency", "build_model", "check_lock_graph",
           "check_blocking_under_lock", "check_shared_state",
           "check_wait_loops", "check_allowlist", "scan_package",
           "Model", "BLOCKING_CALLS", "LOCK_CTORS", "REENTRANT"]
