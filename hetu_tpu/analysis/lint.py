"""Graph lint: rule registry + actionable, provenance-carrying diagnostics.

Every rule sees the whole fetch subgraph with its static shapes (from
:mod:`hetu_tpu.analysis.shapes`) and yields :class:`Diagnostic`s that name
the offending node AND the user line that created it (``Op.creation_site``)
— so ``Executor(validate='error')`` fails fast with "your feed disagrees
with placeholder 'x' created at train.py:42", not an XLA trace dump.

Rule catalog (see README "Static analysis & graph validation"):

* ``uninferable`` (error) — a node's abstract lowering raised
* ``shape-rule-mismatch`` (error) — hand ``infer_shape`` disagrees with
  the abstract interpreter
* ``feed-mismatch`` (error) — fed value shape/dtype disagrees with the
  placeholder's declaration
* ``grad-nontrainable`` (error) — gradient requested w.r.t. a
  non-trainable / non-variable node
* ``duplicate-var-name`` (warn) — two variables share a checkpoint name
* ``ps-embedding-width`` (error) — declared embedding width != the PS
  table's actual width
* ``mesh-axis`` (warn) — an op / sharding names a mesh axis the
  executor's mesh does not have (silent fallback / silent replication)
* ``pipeline-stage`` (error/warn) — pipeline stages don't divide over the
  'pp' axis; ht.context placement chain fragments
* ``flash-fallback`` (warn) — attention config statically guaranteed to
  fall off the Pallas flash path on TPU (ragged causal mod-128,
  unsupported mask/bias broadcast shape)
* ``zero-sharding`` (warn) — ``Executor(zero=...)`` requested on a mesh
  with no usable 'dp' axis (silently replicated), or a slab bucket that
  needs zero-padding to shard over 'dp' (the ragged params are named;
  buckets whose total divides evenly are silent)
* ``train-only-op-in-serving`` (error/warn) — only under
  ``lint(serving=True)`` (the :class:`hetu_tpu.serving.InferenceExecutor`
  validation path): an optimizer update or gradient node reachable from a
  serving fetch set is an error (serving must never construct grad or
  optimizer subgraphs); a dropout node is a warning (it lowers to
  identity under ``training=False``, but its presence usually means the
  fetch set was lifted from a training head)
* ``decode-incompatible-op`` (error) — only under ``lint(decode=True)``
  (the ``InferenceExecutor(decode=True)`` validation path): an op whose
  lowering cannot run under incremental one-token decode — full-sequence
  attention (use ``sdpa_decode_op`` over a ``kv_cache_append_op`` cache)
  or batch-coupled statistics (BatchNorm — breaks the decode
  bitwise-stability guarantee under continuous batching)
* ``feed-schema-churn`` (warn, RUNTIME) — emitted by the executor's
  run-plan cache (``graph/run_plan.py``), not a static pass: successive
  ``run()`` calls keep missing the plan cache because a fed
  placeholder's shape ping-pongs (an unbucketed ragged batch) — every
  new schema re-plans the dispatch path AND retraces/compiles a fresh
  XLA program.  Same diagnostic shape as the static rules (rule name,
  offending node, creation site, concrete fix: bucket ragged batches,
  e.g. to the mod-128 buckets the flash kernel entry uses)
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..graph.node import Op, PlaceholderOp, format_site
from ..graph.gradients import GradientOp
from .shapes import GraphShapes, infer_graph, _normalize_feeds

#: rule name -> callable(GraphInfo) -> iterable[Diagnostic]
RULES = {}


def rule(name):
    def deco(fn):
        RULES[name] = fn
        fn.rule_name = name
        return fn
    return deco


@dataclass
class Diagnostic:
    rule: str
    severity: str          # 'error' | 'warn'
    message: str
    node: object = None    # offending Op, when one exists
    #: True for analyzer-internal problems (a rule crashed): reported,
    #: but never escalated to an exception — an analyzer bug must not
    #: reject a working graph
    internal: bool = False

    def __str__(self):
        loc = ""
        if self.node is not None:
            loc = (f" [node '{self.node.name}' created at "
                   f"{format_site(getattr(self.node, 'creation_site', None))}]")
        return f"{self.severity}[{self.rule}]: {self.message}{loc}"


class GraphInfo:
    """What a lint rule sees: topo + static shapes + executor config."""

    def __init__(self, shapes: GraphShapes, feeds, mesh=None, pipeline=None,
                 feed_values=None, zero=0, serving=False, remat="off",
                 plan=None, decode=False):
        self.shapes = shapes
        self.topo = shapes.topo
        self.feeds = feeds
        #: {node: actual fed array} for feeds given as VALUES (not bare
        #: shapes) — lets rules check value-level properties statically
        self.feed_values = feed_values or {}
        self.mesh = mesh
        self.pipeline = pipeline
        #: the auto-parallel ParallelPlan the executor will compile under
        #: (``Executor(plan=...)``) — enables the plan-coverage rule and
        #: escalates plan-managed mesh-axis findings to errors (an
        #: unrealizable plan must fail fast, not silently measure the
        #: wrong program)
        self.plan = plan
        #: requested ZeRO stage (Executor(zero=...)); 0 = off
        self.zero = int(zero or 0)
        #: True when linting a SERVING fetch set (InferenceExecutor):
        #: enables the train-only-op-in-serving rule
        self.serving = bool(serving)
        #: True when the fetch set is an incremental-DECODE step
        #: (InferenceExecutor(decode=True), hetu_tpu.serving.decode):
        #: enables the decode-incompatible-op rule
        self.decode = bool(decode)
        #: requested remat policy (Executor(remat=...)) — raw, NOT
        #: resolved: the remat-policy rule diagnoses unknown names
        self.remat = remat

    def shape(self, node):
        return self.shapes.shape(node)

    def struct(self, node):
        return self.shapes.struct(node)


class LintReport:
    """Diagnostics + the shape assignment they were derived from."""

    def __init__(self, shapes: GraphShapes, diagnostics):
        self.shapes = shapes
        order = {"error": 0, "warn": 1}
        self.diagnostics = sorted(diagnostics,
                                  key=lambda d: order.get(d.severity, 2))

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self):
        return [d for d in self.diagnostics if d.severity == "warn"]

    @property
    def ok(self):
        return not self.diagnostics

    @property
    def complete(self):
        """Every value-producing node got a static (shape, dtype)."""
        return self.shapes.complete

    def __bool__(self):
        return self.ok

    def __str__(self):
        if self.ok:
            return "lint: clean"
        return "\n".join(str(d) for d in self.diagnostics)

    def raise_errors(self, all_severities=False):
        bad = self.diagnostics if all_severities else self.errors
        bad = [d for d in bad if not d.internal]
        if bad:
            raise GraphValidationError(
                "graph validation failed:\n" +
                "\n".join(f"  {d}" for d in bad))


class GraphValidationError(ValueError):
    """Raised by ``Executor(validate='error')`` / ``LintReport.raise_errors``."""


# --------------------------------------------------------------------- rules

@rule("uninferable")
def _r_uninferable(gi):
    for node, why in gi.shapes.failed.items():
        yield Diagnostic(
            "uninferable", "error",
            f"abstract evaluation of {node.op_type} '{node.name}' failed: "
            f"{why}", node)


@rule("shape-rule-mismatch")
def _r_shape_rule(gi):
    """Cross-check hand-written shape rules against the interpreter."""
    for node in gi.topo:
        if node in gi.shapes.failed or node in gi.shapes.pending \
                or isinstance(node, (PlaceholderOp, GradientOp)):
            continue
        if not _has_hand_rule(node):
            continue
        in_shapes = [gi.shape(i) for i in node.inputs]
        if any(s is None for s in in_shapes):
            continue
        try:
            declared = node.infer_shape(in_shapes)
        except Exception as e:
            yield Diagnostic(
                "shape-rule-mismatch", "error",
                f"hand shape rule of {node.op_type} '{node.name}' raised "
                f"{type(e).__name__}: {e}", node)
            continue
        if declared is None:
            continue
        actual = gi.shape(node)
        if _norm_shape(declared) != _norm_shape(actual):
            yield Diagnostic(
                "shape-rule-mismatch", "error",
                f"hand shape rule of {node.op_type} '{node.name}' says "
                f"{_norm_shape(declared)} but its lowering produces "
                f"{_norm_shape(actual)}", node)


def _has_hand_rule(node):
    if getattr(node, "has_shape_rule", None) is not None:
        return bool(node.has_shape_rule)   # SimpleOp: explicit shape_fn
    # other subclasses: an overridden infer_shape method is a hand rule
    return type(node).infer_shape is not Op.infer_shape


def _norm_shape(s):
    if s is None:
        return None
    if isinstance(s, (tuple, list)):
        return tuple(_norm_shape(x) if isinstance(x, (tuple, list))
                     else int(x) for x in s)
    return s


@rule("feed-mismatch")
def _r_feed(gi):
    for node, st in gi.feeds.items():
        if isinstance(st, (tuple, list)):
            continue  # nested (multi-part) feed: no single shape to check
        if not isinstance(node, PlaceholderOp):
            yield Diagnostic(
                "feed-mismatch", "error",
                f"feed target '{getattr(node, 'name', node)}' is not a "
                f"placeholder (op type {getattr(node, 'op_type', '?')})",
                node if isinstance(node, Op) else None)
            continue
        if node.is_variable:
            yield Diagnostic(
                "feed-mismatch", "error",
                f"'{node.name}' is a variable, not a fed placeholder — "
                f"use executor.load_dict / set_value to change it", node)
            continue
        if node.shape is not None and tuple(st.shape) != tuple(node.shape):
            yield Diagnostic(
                "feed-mismatch", "error",
                f"feed for placeholder '{node.name}' has shape "
                f"{tuple(st.shape)} but the placeholder declares "
                f"{tuple(node.shape)}", node)
            continue
        # dtype: the executor ADOPTS the declared dtype (feeds are cast),
        # so a kind mismatch is only an error when the cast would destroy
        # actual values — checkable when the feed was given as values
        val = gi.feed_values.get(node)
        if node.dtype is not None and val is not None \
                and np.issubdtype(np.dtype(node.dtype), np.integer) \
                and np.issubdtype(np.asarray(val).dtype, np.floating) \
                and not np.all(np.mod(np.asarray(val), 1.0) == 0):
            yield Diagnostic(
                "feed-mismatch", "error",
                f"feed for placeholder '{node.name}' holds fractional "
                f"float values but the placeholder declares "
                f"{np.dtype(node.dtype)} — the executor's dtype adoption "
                f"would truncate them", node)


@rule("grad-nontrainable")
def _r_grad(gi):
    for node in gi.topo:
        if not isinstance(node, GradientOp):
            continue
        wrt = node.wrt
        if not (isinstance(wrt, PlaceholderOp) and wrt.is_variable):
            yield Diagnostic(
                "grad-nontrainable", "error",
                f"gradient requested w.r.t. '{wrt.name}' which is not a "
                f"variable ({wrt.op_type})", wrt)
        elif not wrt.trainable:
            yield Diagnostic(
                "grad-nontrainable", "error",
                f"gradient requested w.r.t. NON-TRAINABLE variable "
                f"'{wrt.name}' — the optimizer would silently train it "
                f"(mark trainable=True or drop it from the loss params)",
                wrt)


@rule("duplicate-var-name")
def _r_dup_names(gi):
    seen = {}
    for node in gi.topo:
        if isinstance(node, PlaceholderOp) and node.is_variable:
            first = seen.setdefault(node.name, node)
            if first is not node:
                yield Diagnostic(
                    "duplicate-var-name", "warn",
                    f"two variables share checkpoint name '{node.name}' "
                    f"(first created at "
                    f"{format_site(first.creation_site)}) — the executor "
                    f"renames the second to '{node.name}~1', making the "
                    f"checkpoint identity creation-order-dependent", node)


@rule("ps-embedding-width")
def _r_ps_width(gi):
    for node in gi.topo:
        if not getattr(node, "is_ps", False):
            continue
        store, table = node.store, node.table
        if not hasattr(store, "width"):
            continue
        try:
            actual = int(store.width(table))
        except Exception as e:
            yield Diagnostic(
                "ps-embedding-width", "error",
                f"PS embedding '{node.name}': table {table} is not "
                f"readable from its store ({type(e).__name__}: {e})", node)
            continue
        if node.width is not None and int(node.width) != actual:
            yield Diagnostic(
                "ps-embedding-width", "error",
                f"PS embedding '{node.name}' declares width {node.width} "
                f"but table {table} has width {actual} — every pulled row "
                f"would be mis-shaped", node)


#: graph ops whose lowering changes behavior based on a named mesh axis;
#: with a mesh lacking the axis they SILENTLY run the fallback path
_MESH_AXIS_OPS = {
    "AllToAll": ("ep",),
    "HAllToAll": ("ep", "ep_outer", "ep_inner"),
    "RingAttention": ("cp",),
    "RingAttentionMasked": ("cp",),
    "UlyssesAttention": ("cp",),
    "UlyssesAttentionMasked": ("cp",),
    "PipelineBlock": ("pp",),
}


#: axes the auto-parallel strategy space manages — a plan-validated graph
#: missing one of THESE is an illegal plan (error), while e.g. an 'ep'
#: sharding replicating on a dp-only plan mesh is the intended dense
#: fallback (stays a warning)
_PLAN_AXES = frozenset(("dp", "tp", "pp", "cp"))


@rule("mesh-axis")
def _r_mesh_axis(gi):
    if gi.mesh is None:
        return  # single-device run: fallback paths are the intended paths
    axes = set(gi.mesh.axis_names)

    plan_axes = frozenset()
    if gi.plan is not None:
        try:
            plan_axes = frozenset(a for a, s in gi.plan.mesh_axes().items()
                                  if s > 1) & _PLAN_AXES
        except Exception:
            plan_axes = _PLAN_AXES   # unpriceable plan: stay strict

    def sev(involved):
        # under Executor(plan=...): an axis the plan ACTUALLY USES going
        # silently replicated/fallback is an unrealizable plan — fail
        # fast.  Axes the plan sets to 1 stay warnings: a
        # pipeline_block-built model under a pp=1 plan (or ring
        # attention under cp=1) falls back to exactly the
        # single-stage/dense program the cost model priced.
        return "error" if set(involved) & plan_axes else "warn"

    for node in gi.topo:
        want = _MESH_AXIS_OPS.get(node.op_type)
        if want and not any(a in axes for a in want):
            yield Diagnostic(
                "mesh-axis", sev(want),
                f"{node.op_type} '{node.name}' expects mesh axis "
                f"'{want[0]}' but the executor mesh has axes "
                f"{sorted(axes)} — it will silently run its "
                f"non-distributed fallback", node)
        spec = getattr(node, "sharding", None)
        if spec is not None:
            missing = [a for a in spec
                       if a is not None and not isinstance(a, tuple)
                       and a not in axes]
            if missing:
                yield Diagnostic(
                    "mesh-axis", sev(missing),
                    f"sharding of '{node.name}' names mesh axes "
                    f"{missing} absent from the executor mesh "
                    f"{sorted(axes)} — those dims will be REPLICATED",
                    node)


@rule("pipeline-stage")
def _r_pipeline(gi):
    # (a) PipelineBlock stages must divide over the mesh 'pp' axis
    if gi.mesh is not None and "pp" in gi.mesh.axis_names:
        pp = gi.mesh.shape["pp"]
        for node in gi.topo:
            if node.op_type != "PipelineBlock":
                continue
            n = getattr(node, "n_stages", None)
            if n and pp > 1 and n % pp != 0:
                yield Diagnostic(
                    "pipeline-stage", "error",
                    f"PipelineBlock '{node.name}' has {n} stages over a "
                    f"'pp' axis of size {pp} — stages must divide evenly "
                    f"across pipeline ranks", node)
    # (b) interop placement contiguity: run-length segmentation over topo
    # order must not fragment (each alternation = one boundary transfer +
    # a separate jit)
    segments, prev = [], None
    for node in gi.topo:
        if isinstance(node, (PlaceholderOp, GradientOp)) \
                or node.raw_ctx is None:
            continue
        key = repr(node.raw_ctx)
        if key != prev:
            segments.append((key, node))
            prev = key
    distinct = len({k for k, _ in segments})
    if distinct and len(segments) > 2 * distinct:
        first_bounce = segments[distinct][1]
        yield Diagnostic(
            "pipeline-stage", "warn",
            f"ht.context placement fragments into {len(segments)} "
            f"segments over {distinct} device groups — ops per device "
            f"are not contiguous in graph order (first bounce at "
            f"'{first_bounce.name}'); group each stage's ops together",
            first_bounce)


@rule("plan-coverage")
def _r_plan_coverage(gi):
    """An ``Executor(plan=...)`` graph must actually REALIZE the plan:
    tp directives need 'tp' shardings on some kernel (``plan.apply`` /
    ``plan.bind``), pp needs a ``ht.pipeline_block``-built model, cp
    needs ring/ulysses attention ops, fsdp needs either the ZeRO slab
    route (``zero>=1``) or 'dp' param shardings.  Anything less silently
    executes (and measures!) a different program than the plan the
    search costed."""
    plan = gi.plan
    if plan is None:
        return
    try:
        need = plan.mesh_axes()
        directives = plan.layer_specs()
    except Exception as e:
        yield Diagnostic(
            "plan-coverage", "error",
            f"plan is not executable as a single mesh: {e}")
        return
    axes = set(gi.mesh.axis_names) if gi.mesh is not None else set()
    missing = sorted(a for a, s in need.items() if s > 1 and a not in axes)
    if missing:
        yield Diagnostic(
            "plan-coverage", "error",
            f"plan needs mesh axes {missing} but the executor mesh has "
            f"{sorted(axes)} — pass the plan's own mesh "
            f"(ParallelPlan.make_mesh) or rebuild the executor without "
            f"an explicit mesh=")

    def _axes_of(spec):
        out = set()
        for a in spec or ():
            if isinstance(a, (tuple, list)):
                out.update(a)
            elif a is not None:
                out.add(a)
        return out

    annotated = set()
    for node in gi.topo:
        annotated |= _axes_of(getattr(node, "sharding", None))

    def _layers(pred):
        names = [d["name"] for d in directives if pred(d)]
        more = f" (+{len(names) - 3} more)" if len(names) > 3 else ""
        return ", ".join(names[:3]) + more

    if any(d["tp"] > 1 for d in directives) and "tp" not in annotated:
        yield Diagnostic(
            "plan-coverage", "error",
            f"plan assigns tp>1 to layer(s) [{_layers(lambda d: d['tp'] > 1)}] but no "
            f"graph node carries a 'tp' sharding — the plan was never "
            f"applied; bind the model layers (plan.bind(layers)) or call "
            f"plan.apply(layers) before building the executor")
    if max(s.pp for s in plan.strategies) > 1 \
            and not any(n.op_type == "PipelineBlock" for n in gi.topo):
        yield Diagnostic(
            "plan-coverage", "error",
            f"plan assigns {max(s.pp for s in plan.strategies)} pipeline "
            f"stages but the graph has no PipelineBlock — build the "
            f"model with ht.pipeline_block and the plan's stage "
            f"assignment")
    if max(s.cp for s in plan.strategies) > 1 \
            and not any(n.op_type.startswith(("RingAttention",
                                              "UlyssesAttention"))
                        for n in gi.topo):
        yield Diagnostic(
            "plan-coverage", "error",
            f"plan assigns cp={max(s.cp for s in plan.strategies)} "
            f"context parallelism to layer(s) [{_layers(lambda d: d['cp'] > 1)}] but the "
            f"graph has no ring/ulysses attention — build attention with "
            f"context_parallel='ring' (or 'ulysses')")
    # fires for ANY unrealized fsdp directive — including tp>1 plans
    # (wants_zero() False, so the slab route never covers them): without
    # zero or 'dp' param shardings the params replicate and the search's
    # memory feasibility verdict silently does not hold
    if any(d["fsdp"] for d in directives) and not gi.zero \
            and "dp" not in annotated:
        yield Diagnostic(
            "plan-coverage", "error",
            f"plan assigns fsdp to layer(s) [{_layers(lambda d: d['fsdp'])}] but "
            f"zero= is off and no param carries a 'dp' sharding — the "
            f"fsdp memory verdict would not hold at runtime; pass "
            f"Executor(zero=3) (the default when plan= sets the "
            f"strategy) or apply the plan's param specs")


#: attention op types -> (index of k input, index of mask input or None,
#: index of bias input or None)
_ATTN_OPS = {
    "ScaledDotProductAttention": (1, None, None),
    "ScaledDotProductAttentionVarlen": (1, None, None),
    "ScaledDotProductAttentionMasked": (1, 3, None),
    "ScaledDotProductAttentionBias": (1, None, 3),
    "ScaledDotProductAttentionMaskedBias": (1, 3, 4),
    "RingAttention": (1, None, 3),
    "UlyssesAttention": (1, None, 3),
    "RingAttentionMasked": (1, 3, 4),
    "UlyssesAttentionMasked": (1, 3, 4),
}


@rule("flash-fallback")
def _r_flash(gi):
    """Static predictor of the attention dispatchers'
    ``flash_fallback_reason``: configs that are GUARANTEED to leave the
    Pallas fast path on TPU are flagged before anything runs (ragged
    causal mod-128 bucketing, unsupported mask/bias broadcast shapes)."""
    from ..ops.attention import (_FLASH_MIN_LEN, _broadcastable_extra,
                                 _causal_bucketable)
    for node in gi.topo:
        spec = _ATTN_OPS.get(node.op_type)
        if spec is None:
            continue
        k_i, m_i, b_i = spec
        q = gi.struct(node.inputs[0])
        k = gi.struct(node.inputs[k_i]) if k_i < len(node.inputs) else None
        if q is None or k is None:
            continue
        if q.shape[-2] < _FLASH_MIN_LEN:
            # below the empirical dispatch gate the einsum path is the
            # INTENDED path (XLA fusion wins at short seq) — nothing to
            # warn about
            continue
        causal = bool(node.attrs.get("causal", False))
        if not _causal_bucketable(q, k, causal):
            yield Diagnostic(
                "flash-fallback", "warn",
                f"{node.op_type} '{node.name}': causal attention with "
                f"ragged lengths (q={q.shape[-2]}, kv={k.shape[-2]}) — "
                f"{q.shape[-2] % 128} != {k.shape[-2] % 128} (mod 128), "
                f"so on TPU this falls back to einsum attention "
                f"(reason 'causal_ragged_mismatch'); pad q/kv to matching "
                f"mod-128 lengths", node)
        for what, idx in (("mask", m_i), ("bias", b_i)):
            if idx is None or idx >= len(node.inputs):
                continue
            extra = gi.struct(node.inputs[idx])
            if extra is not None and hasattr(extra, "shape") \
                    and not _broadcastable_extra(q, k, extra):
                yield Diagnostic(
                    "flash-fallback", "warn",
                    f"{node.op_type} '{node.name}': {what} shape "
                    f"{tuple(extra.shape)} is outside the flash kernel's "
                    f"broadcast support (1|B, 1|H, 1|S_q, S_kv) — on TPU "
                    f"this falls back to einsum attention (reason "
                    f"'{what}_shape')", node)


@rule("zero-sharding")
def _r_zero(gi):
    """ZeRO weight-update sharding preconditions (parallel/zero.py):
    the plan shards every optimizer param over the mesh 'dp' axis, so a
    missing/size-1 axis silently degrades to the replicated update, and
    a bucket whose total element count does not divide ``dp`` falls back
    to zero-padded sharding (correct, but the pad is wasted collective
    bytes — ``zero_pad_bytes`` counts it at run time).  The check
    reproduces the executor's real bucketing, so ragged params absorbed
    by co-bucketed neighbours do not warn."""
    if not gi.zero:
        return
    from ..optim.optimizer import OptimizerOp
    from ..parallel.zero import ZERO_AXIS
    opt_ops = [n for n in gi.topo if isinstance(n, OptimizerOp)]
    if not opt_ops:
        return
    dp = None
    if gi.mesh is not None and ZERO_AXIS in gi.mesh.axis_names:
        dp = int(gi.mesh.shape[ZERO_AXIS])
    if not dp or dp < 2:
        have = sorted(gi.mesh.axis_names) if gi.mesh is not None else None
        yield Diagnostic(
            "zero-sharding", "warn",
            f"zero={gi.zero} requested but the executor mesh "
            f"{'has axes ' + str(have) if have else 'is absent'} — no "
            f"'{ZERO_AXIS}' axis of size >= 2 to shard the weight update "
            f"over, so the update runs fully REPLICATED (no memory win)",
            opt_ops[0])
        return
    from ..parallel.zero import build_plan, ineligible_reason
    for op in opt_ops:
        # the executor's eligibility filter (_build_zero_plans), via the
        # SHARED predicate zero.ineligible_reason: an ineligible param
        # makes its WHOLE optimizer fall back to the replicated update —
        # zero= silently has no effect there, which is exactly what this
        # rule exists to surface (and building a plan for it would warn
        # about pad bytes of collectives that will never exist)
        ineligible = None
        for p in op.params:
            dt = getattr(p, "dtype", None) or gi.shapes.dtype(p)
            why = ineligible_reason(p, dt)
            if why is not None:
                ineligible = (p, why)
                break
        if ineligible:
            p, why = ineligible
            yield Diagnostic(
                "zero-sharding", "warn",
                f"zero={gi.zero}: optimizer '{op.name}' stays on the "
                f"fully REPLICATED update path because parameter "
                f"'{p.name}' {why} — no ZeRO memory win for its params "
                f"or moments", p)
            continue
        items, by_key = [], {}
        for i, p in enumerate(op.params):
            shape = p.shape if getattr(p, "shape", None) is not None \
                else gi.shape(p)
            if shape is None:
                continue
            dt = getattr(p, "dtype", None) or gi.shapes.dtype(p) \
                or np.float32
            key = f"p{i}"
            items.append((key, tuple(shape), np.dtype(dt).name))
            by_key[key] = p
        if not items:
            continue
        # reproduce the executor's ACTUAL bucketing (same order, same
        # byte cap, per-param for LAMB): padding is decided per BUCKET,
        # so a ragged param co-bucketed with others often shards with
        # zero waste — warning on numel % dp alone would spam biases and
        # layernorms about a non-problem
        plan = build_plan(items, dp, gi.zero,
                          per_param=bool(getattr(op.optimizer, "lamb",
                                                 False)))
        for b in plan.buckets:
            if not b.pad:
                continue
            # pad > 0 guarantees at least one member is ragged: a bucket
            # of all-divisible params would total a dp multiple itself
            ragged = [k for k, shape in zip(b.param_keys, b.shapes)
                      if (int(np.prod(shape, dtype=np.int64))
                          if shape else 1) % dp]
            names = [by_key[k].name for k in ragged]
            pad_bytes = b.pad * np.dtype(b.dtype).itemsize
            yield Diagnostic(
                "zero-sharding", "warn",
                f"ZeRO bucket of {len(b.param_keys)} param(s) "
                f"({', '.join(repr(n) for n in names[:4])}"
                f"{', ...' if len(names) > 4 else ''} not divisible by "
                f"the '{ZERO_AXIS}' axis) totals {b.numel} elements — "
                f"zero-padded to {b.padded} ({b.pad} wasted elements, "
                f"{pad_bytes} B per collective; see zero_pad_bytes)",
                by_key[ragged[0]])


@rule("remat-policy")
def _r_remat(gi):
    """Selective-remat policy preconditions (``parallel/remat.py``,
    ISSUE 13): an unknown policy name is an error (for direct
    ``ht.lint(remat=...)`` callers — ``Executor(remat=...)`` fails fast
    at construction like ``pipeline=``), a policy on a graph with no
    recomputable segment (forward-only, or no matmul-family anchors to
    segment at) is a silent no-op worth a warning, and ``'auto'`` with
    no resolvable HBM budget remats EVERY segment — the memory-
    conservative default, but almost never what the user budgeted for."""
    from ..parallel import remat as remat_mod
    pol = gi.remat
    if pol in (None, False, 0, "off"):
        return
    if pol is True:
        pol = "dots"
    anchor_node = next((n for n in gi.topo
                        if remat_mod._is_anchor(n)), None)
    site_node = anchor_node or next(
        (n for n in gi.topo
         if not isinstance(n, (PlaceholderOp, GradientOp))), None)
    if pol not in remat_mod.POLICIES:
        yield Diagnostic(
            "remat-policy", "error",
            f"unknown remat policy {pol!r} — expected one of "
            f"{'|'.join(remat_mod.POLICIES)} (True == 'dots')",
            site_node)
        return
    grads = [n for n in gi.topo if isinstance(n, GradientOp)]
    if not grads:
        yield Diagnostic(
            "remat-policy", "warn",
            f"remat={pol!r} on a forward-only graph — nothing "
            f"differentiates, so there is no backward pass to "
            f"rematerialize into (remat is a silent no-op here)",
            site_node)
    elif anchor_node is None:
        yield Diagnostic(
            "remat-policy", "warn",
            f"remat={pol!r} on a graph with NO recomputable segment — "
            f"no matmul-family/attention anchors to segment at, so the "
            f"policy frees (almost) nothing and 'full'/'auto' build an "
            f"empty plan", site_node)
    if pol == "auto":
        budget, _src = remat_mod.resolve_budget()
        if budget is None:
            yield Diagnostic(
                "remat-policy", "warn",
                "remat='auto' with no resolvable HBM budget — "
                "HETU_HBM_BUDGET_MB is unset and this backend reports "
                "no memory limit, so auto remats EVERY segment (acts "
                "like 'full'); set HETU_HBM_BUDGET_MB to get the "
                "budget-fitted plan", site_node)


#: op types whose semantics exist only for TRAINING — a serving fetch set
#: reaching them is either outright wrong (optimizer, gradient: the whole
#: point of a compile-once inference program is that these subgraphs are
#: never built) or a smell (dropout: inert under training=False, but its
#: presence usually means the fetch set was lifted straight off a
#: training head instead of the model's inference output)
_TRAIN_ONLY_ERRORS = {"OptimizerUpdate"}
_TRAIN_ONLY_WARNS = {"Dropout", "Dropout2d"}


@rule("train-only-op-in-serving")
def _r_train_only_serving(gi):
    """Serving graphs must never construct grad/optimizer subgraphs
    (``hetu_tpu.serving.InferenceExecutor`` compiles fetch subgraphs
    without a backward pass; an optimizer or gradient fetch would
    silently train — or crash — inside the request path)."""
    if not gi.serving:
        return
    for node in gi.topo:
        if isinstance(node, GradientOp):
            yield Diagnostic(
                "train-only-op-in-serving", "error",
                f"gradient node '{node.name}' (w.r.t. "
                f"'{getattr(node.wrt, 'name', node.wrt)}') is reachable "
                f"from a serving fetch set — serving must never build a "
                f"backward pass; fetch the model's inference output "
                f"instead", node)
        elif node.op_type in _TRAIN_ONLY_ERRORS:
            yield Diagnostic(
                "train-only-op-in-serving", "error",
                f"{node.op_type} '{node.name}' is reachable from a "
                f"serving fetch set — a weight update inside the request "
                f"path would train the serving replica; drop the "
                f"optimizer from the serving fetches", node)
        elif node.op_type in _TRAIN_ONLY_WARNS:
            yield Diagnostic(
                "train-only-op-in-serving", "warn",
                f"{node.op_type} '{node.name}' is reachable from a "
                f"serving fetch set — it lowers to identity under "
                f"training=False, but a dropout in an inference graph "
                f"usually means the fetch set came from a training head",
                node)


#: op types whose lowering cannot run under INCREMENTAL decode — they
#: consume the full sequence axis in one shot (the decode step sees one
#: token; a full-sequence attention in the step graph would attend over
#: whatever single token it was handed and silently emit garbage) — with
#: the incremental replacement to name in the diagnostic
_DECODE_INCOMPATIBLE_SEQ = {
    "ScaledDotProductAttention",
    "ScaledDotProductAttentionMasked",
    "ScaledDotProductAttentionBias",
    "ScaledDotProductAttentionMaskedBias",
    "ScaledDotProductAttentionVarlen",
    "RingAttention",
    "RingAttentionMasked",
    "UlyssesAttention",
    "UlyssesAttentionMasked",
}
#: op types that carry BATCH-coupled running state — under continuous
#: batching the batch composition changes every token, so their
#: statistics would depend on which sequences happen to share the step
#: (breaking the bitwise-stability guarantee: same sequence, different
#: batch mates, different tokens)
_DECODE_INCOMPATIBLE_STATE = {"BatchNorm"}


@rule("decode-incompatible-op")
def _r_decode_incompatible(gi):
    """An incremental-decode step graph
    (``InferenceExecutor(decode=True)``) must be runnable one token at a
    time: full-sequence attention ops and batch-statistics ops are
    rejected at construction with their creation site, naming the
    incremental replacement."""
    if not gi.decode:
        return
    for node in gi.topo:
        if node.op_type in _DECODE_INCOMPATIBLE_SEQ:
            yield Diagnostic(
                "decode-incompatible-op", "error",
                f"{node.op_type} '{node.name}' consumes the full "
                f"sequence axis in one shot — an incremental decode "
                f"step sees ONE token per call and would silently "
                f"attend over nothing; use sdpa_decode_op over a KV "
                f"cache maintained by kv_cache_append_op instead", node)
        elif node.op_type in _DECODE_INCOMPATIBLE_STATE:
            yield Diagnostic(
                "decode-incompatible-op", "error",
                f"{node.op_type} '{node.name}' computes batch-coupled "
                f"statistics — under continuous batching the batch "
                f"composition changes every token, so its output would "
                f"depend on which sequences share the step (the "
                f"bitwise-stability guarantee cannot hold); use "
                f"LayerNorm (per-row statistics) instead", node)


# ----------------------------------------------------------------- entry

def lint(fetches, feeds=None, mesh=None, pipeline=None, training=True,
         num_microbatches=None, rules=None, zero=0, serving=False,
         remat="off", plan=None, decode=False):
    """Statically verify a fetch subgraph; returns a :class:`LintReport`.

    ``feeds``: example values (or bare shapes) for placeholders declared
    without a static shape, e.g. ``ht.lint([loss], feeds={x: (32, 784)})``.
    ``mesh`` / ``pipeline`` / ``num_microbatches`` / ``zero`` /
    ``remat``: the executor configuration the graph will compile under
    (enables the mesh-axis, pipeline-stage, zero-sharding and
    remat-policy rules, and keeps schedule-sensitive lowering on the
    same path the executor uses).
    ``plan``: the auto-parallel :class:`ParallelPlan` the executor will
    compile under (``Executor(plan=...)``) — enables the plan-coverage
    rule and escalates plan-managed mesh-axis findings to errors.
    ``serving=True``: lint the fetches as a SERVING set (enables the
    train-only-op-in-serving rule — what
    ``InferenceExecutor(validate=...)`` runs; pair with
    ``training=False``).
    ``decode=True``: the fetch set is an incremental-decode STEP
    (``InferenceExecutor(decode=True)``) — enables the
    decode-incompatible-op rule.
    ``rules``: optional iterable of rule names to run (default: all
    registered rules).
    """
    if isinstance(fetches, Op):
        fetches = [fetches]
    shapes = infer_graph(fetches, feeds=feeds, mesh=mesh, training=training,
                         num_microbatches=num_microbatches,
                         pipeline=pipeline)
    feed_values = {}
    if feeds:
        by_name = {n.name: n for n in shapes.topo
                   if isinstance(n, PlaceholderOp)}
        for k, v in feeds.items():
            node = by_name.get(k) if isinstance(k, str) else k
            if node is not None and hasattr(v, "dtype") \
                    and hasattr(v, "shape"):
                feed_values[node] = v
    gi = GraphInfo(shapes, _normalize_feeds(feeds, shapes.topo),
                   mesh=mesh, pipeline=pipeline, feed_values=feed_values,
                   zero=zero, serving=serving, remat=remat, plan=plan,
                   decode=decode)
    diags = []
    selected = RULES if rules is None else {
        name: RULES[name] for name in rules}
    for name, fn in selected.items():
        try:
            diags.extend(fn(gi))
        except Exception as e:
            # one rule crashing must not take down the report (the
            # analyzer can never be the thing that breaks a graph)
            diags.append(Diagnostic(
                name, "warn",
                f"lint rule crashed: {type(e).__name__}: {e} — "
                f"report it; the rule was skipped", internal=True))
    return LintReport(shapes, diags)
