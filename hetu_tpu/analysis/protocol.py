"""Explicit-state model checking of the repo's distributed protocols
(ISSUE 20 tentpole), plus the trace-conformance layer that ties the
models back to the real implementation.

Three executable protocol models, each a guarded-transition system over
canonical tuple states, exhaustively explored by :func:`check` (BFS with
deduped states and shortest counterexample traces):

* :class:`PSReplicationModel` — epoch-fenced PS replication/failover
  (ISSUEs 4/8): clients with (client, seq) dedup windows, per-shard
  primary/backup with apply+mirror-before-ack, promotion with the
  synced-copy gate and the ``max(cur+1, want)`` epoch bump, demotion,
  the healed-split-brain lineage probe, and environment kill /
  partition / heal / retry actions.
* :class:`DecodeRecoveryModel` — exactly-once in-flight decode stream
  migration (ISSUE 19): seat / emit / detach / adopt with the stream
  replay-epoch fence and the front door's retry budget.
* :class:`ElasticResizeModel` — elastic dp resize (ISSUE 12):
  step-boundary polls vs the async in-flight window, heartbeat
  wait-window liveness, unreachable-HOLD, and the ``min_dp`` floor.

Checked invariants are the claims the docs already make: exactly-once
apply per (client, seq) across promotion; no ack'd write lost by
failover (the single-fault claim k=2 replication actually makes); at
most one serving lineage per shard at quiescence with monotone epochs;
a demoted or unsynced copy never serves; every token index resolved
exactly once with no journal gaps; fenced zombies never mutate
post-detach; recovery terminates within its budget.

:data:`SEEDED_MUTATIONS` re-introduces three historical bug classes as
model mutations (promotion without the synced-copy gate, promotion
without the epoch bump, zombie emission without the stream-epoch
fence); the checker must produce a counterexample naming the violated
invariant for each — the verifier's synthetic-violation tests.

The model-vs-code gap is closed by the trace-conformance layer: the
:data:`PROTO` recorder collects ``protocol_event()`` records emitted at
the real transition sites (``ps/dist_store.py``, ``serving/decode.py``,
``serving/fleet.py``, ``parallel/elastic.py`` — flag-guarded, ISSUE 10
tracer discipline: one attribute load when off), and
:func:`check_conformance` replays a recorded run against the models'
transition relations.  ``bench.py`` gates the failover / partition /
decode-recovery chaos legs on it, so every committed fault-injection
artifact is also a machine-checked trace of the verified model.

Stdlib-only BY DESIGN (the `analysis.concurrency` convention):
``tools/hetu_lint.py`` and ``tools/verify_protocols.py`` load this
module by file path, so it must import without jax; the lazy
``..metrics`` import degrades to a no-op outside the package.
"""
from __future__ import annotations

import os
import threading
from collections import deque

# ---------------------------------------------------------------- recorder

_record_protocol = None


def _record(kind, n=1):
    """Lazy bridge to ``metrics.record_protocol`` — resolved on first
    use so this module stays importable by file path (lint, CLI)
    without pulling the package (and jax) in."""
    global _record_protocol
    if _record_protocol is None:
        try:
            from ..metrics import record_protocol
        except ImportError:
            record_protocol = None
        _record_protocol = record_protocol or (lambda kind, n=1: None)
    _record_protocol(kind, n)


def _env_on():
    return os.environ.get("HETU_PROTO_TRACE", "0").lower() not in (
        "", "0", "false", "off")


#: hard cap on buffered events — a runaway chaos loop must not OOM the
#: process through its own verifier
_REC_CAP = 200_000


class _ProtoRecorder:
    """Process-wide protocol-event recorder (module singleton
    :data:`PROTO`).  ``on`` is the ONE hot flag — instrumentation sites
    read it directly (``if _PROTO.on: _PROTO.emit(...)``), so a
    disabled recorder costs one attribute load per site (the ISSUE 10
    tracer discipline; default off, env ``HETU_PROTO_TRACE=1`` or
    :meth:`start` enables)."""

    __slots__ = ("on", "_lock", "_events", "dropped")

    def __init__(self):
        self.on = _env_on()
        self._lock = threading.Lock()
        self._events = []
        self.dropped = 0

    def start(self):
        """Begin a fresh recording (clears the buffer, flips ``on``)."""
        with self._lock:
            self._events = []
            self.dropped = 0
        self.on = True

    def stop(self):
        """Flip ``on`` off and return the recorded events (drained)."""
        self.on = False
        return self.drain()

    def drain(self):
        """Return and clear the buffered events (arrival order)."""
        with self._lock:
            ev, self._events = self._events, []
        return ev

    def emit(self, plane, kind, **fields):
        """Record one protocol transition event.  Callers gate on
        ``.on`` themselves (the whole point of the flag)."""
        ev = fields
        ev["plane"] = plane
        ev["kind"] = kind
        with self._lock:
            if len(self._events) >= _REC_CAP:
                self.dropped += 1
                _record("protocol_events_dropped")
                return
            ev["i"] = len(self._events)
            self._events.append(ev)
        _record("protocol_events")


PROTO = _ProtoRecorder()


def protocol_event(plane, kind, **fields):
    """Convenience wrapper for cold call sites (hot sites inline the
    ``PROTO.on`` guard instead)."""
    if PROTO.on:
        PROTO.emit(plane, kind, **fields)


# ------------------------------------------------------------------ engine

class Violation:
    """One invariant violation with its shortest counterexample trace
    (BFS guarantees minimality in transition count)."""

    __slots__ = ("invariant", "message", "trace", "state", "depth")

    def __init__(self, invariant, message, trace, state, depth):
        self.invariant = invariant
        self.message = message
        self.trace = trace          # list of rendered transition labels
        self.state = state          # rendered violating state
        self.depth = depth

    def render(self):
        lines = [f"invariant violated: {self.invariant}",
                 f"  {self.message}",
                 f"  counterexample ({len(self.trace)} steps):"]
        for i, lab in enumerate(self.trace):
            lines.append(f"    {i + 1:2d}. {lab}")
        lines.append(f"  state: {self.state}")
        return "\n".join(lines)

    def to_dict(self):
        return {"invariant": self.invariant, "message": self.message,
                "trace": list(self.trace), "state": self.state,
                "depth": self.depth}


class CheckResult:
    """Outcome of one :func:`check` run: state/transition counts, the
    exploration completeness flag, and (at most one) violation."""

    __slots__ = ("model", "states", "transitions", "depth", "complete",
                 "violations")

    def __init__(self, model, states, transitions, depth, complete,
                 violations):
        self.model = model
        self.states = states
        self.transitions = transitions
        self.depth = depth
        self.complete = complete
        self.violations = violations

    @property
    def ok(self):
        return not self.violations

    def to_dict(self):
        return {"model": self.model, "states": self.states,
                "transitions": self.transitions, "depth": self.depth,
                "complete": self.complete, "ok": self.ok,
                "violations": [v.to_dict() for v in self.violations]}


def check(model, max_states=500_000, max_depth=None):
    """Exhaustive BFS over ``model``'s reachable state space.

    The model contract (duck-typed, like the lint rule registry):
    ``init()`` -> canonical hashable state; ``actions(state)`` ->
    iterable of ``(label, next_state)``; ``invariants`` /
    ``edge_invariants`` / ``quiescent_invariants`` /
    ``terminal_invariants`` -> iterables of ``(name, fn)`` where ``fn``
    returns an error string (violated) or None; ``quiescent(state)`` ->
    bool.  Stops at the FIRST violation (BFS order ⇒ the returned trace
    is a shortest counterexample); ``complete`` is False when the
    ``max_states`` / ``max_depth`` budget truncated exploration."""
    init = model.init()
    seen = {init: (None, None, 0)}      # state -> (parent, label, depth)
    q = deque([init])
    states = transitions = maxd = 0
    complete = True

    def trace_to(state, extra=None):
        labels = []
        while True:
            parent, label, _ = seen[state]
            if parent is None:
                break
            labels.append(model.render_label(label))
            state = parent
        labels.reverse()
        if extra is not None:
            labels.append(model.render_label(extra))
        return labels

    def done(states, complete, violations):
        _record("protocol_states_explored", states)
        if violations:
            _record("protocol_violations", len(violations))
        return CheckResult(model.name, states, transitions, maxd,
                           complete, violations)

    while q:
        s = q.popleft()
        d = seen[s][2]
        maxd = max(maxd, d)
        states += 1
        for name, fn in model.invariants:
            err = fn(s)
            if err:
                return done(states, complete, [Violation(
                    name, err, trace_to(s), model.render_state(s), d)])
        acts = list(model.actions(s))
        if model.quiescent(s):
            for name, fn in model.quiescent_invariants:
                err = fn(s)
                if err:
                    return done(states, complete, [Violation(
                        name, err, trace_to(s), model.render_state(s),
                        d)])
        if not acts:
            for name, fn in model.terminal_invariants:
                err = fn(s)
                if err:
                    return done(states, complete, [Violation(
                        name, err, trace_to(s), model.render_state(s),
                        d)])
            continue
        for label, s2 in acts:
            transitions += 1
            for name, fn in model.edge_invariants:
                err = fn(s, label, s2)
                if err:
                    return done(states, complete, [Violation(
                        name, err, trace_to(s, extra=label),
                        model.render_state(s2), d + 1)])
            if s2 not in seen:
                if len(seen) >= max_states or \
                        (max_depth is not None and d + 1 > max_depth):
                    complete = False
                    continue
                seen[s2] = (s, label, d + 1)
                q.append(s2)
    return done(states, complete, [])


class _ModelBase:
    """Shared defaults for the model contract."""

    name = "model"
    invariants = ()
    edge_invariants = ()
    quiescent_invariants = ()
    terminal_invariants = ()

    def quiescent(self, state):
        return False

    def render_state(self, state):
        return repr(state)

    def render_label(self, label):
        if isinstance(label, tuple):
            return label[0] + "(" + ", ".join(str(x) for x in label[1:]) \
                + ")"
        return str(label)


# -------------------------------------------------- model: PS replication

# client-op statuses (one non-idempotent write per client, retried with
# a PINNED (client, seq) — the dedup window's whole point)
_IDLE, _WAIT, _RESEND, _CONN, _WPROM, _ACKED, _FAILED = (
    "idle", "wait", "resend", "conn", "wait_promote", "acked", "failed")


class PSReplicationModel(_ModelBase):
    """Epoch-fenced PS replication/failover as a guarded-transition
    system.

    Topology mirrors ``dist_store``'s k=2 ring: shard ``s`` is
    home-served by rank ``s`` with its backup on rank ``s+1`` (mod
    world); ``unsynced`` shards start with their backup MID-SYNC
    (copy exists, ``promotable`` False until the ``sync_done``
    transition — the OP_SYNC / OP_SYNC_PUT plane collapsed to its
    promotability effect).  One write op per client, client ``i`` ->
    shard ``shards[i]``; retries resend the SAME (client, seq).

    The apply+mirror-before-ack critical section (``_repl_lock``) is
    one atomic ``deliver_push`` transition: fence -> dedup -> local
    apply -> synchronous OP_REPLICATE forward (with the peer's
    ``_fence_or_adopt`` gate, ``refuse_equal_if_serving``) -> ack.
    Environment actions: one fault (kill OR partition episode — the
    single-fault claim k=2 replication makes), heal, the rate-limited
    lineage probe (``_probe_lineage`` — how a healed stale ex-primary
    learns it was deposed), and ``sync_done``.

    ``mutation`` re-introduces historical bugs: ``promote_unsynced``
    (PR 4 review: promotion skips the synced-copy gate) and
    ``promote_no_epoch_bump`` (PR 8 split-brain: promotion reuses the
    current epoch, so the deposed primary's frames stay unfenceable).
    """

    name = "ps_replication"

    def __init__(self, n_ranks=3, shards=(0, 1), unsynced=(1,),
                 max_sends=3, max_promotes=2, fault_budget=1,
                 mutation=None):
        assert mutation in (None, "promote_unsynced",
                            "promote_no_epoch_bump"), mutation
        self.world = int(n_ranks)
        self.shards = tuple(shards)
        self.unsynced = frozenset(unsynced)
        self.max_sends = int(max_sends)
        self.max_promotes = int(max_promotes)
        self.fault_budget = int(fault_budget)
        self.mutation = mutation
        self.n_ops = len(self.shards)        # op i = client i -> shards[i]
        self.slots = []                      # (rank, shard) copy slots
        for s in self.shards:
            self.slots.append((s % self.world, s))
            self.slots.append(((s + 1) % self.world, s))
        self.slot_ix = {rs: i for i, rs in enumerate(self.slots)}
        self.invariants = (
            ("exactly-once-apply", self._inv_exactly_once),
            ("demoted-or-unsynced-never-serves", self._inv_gate),
        )
        self.edge_invariants = (
            ("epoch-monotonicity", self._inv_epoch_monotone),
        )
        self.quiescent_invariants = (
            ("single-serving-lineage", self._inv_single_lineage),
            ("no-acked-write-lost", self._inv_no_lost_write),
        )
        self.terminal_invariants = (
            ("ops-terminate", self._inv_ops_terminate),
        )

    # copy tuple layout: (epoch, serving, promotable, fwd_ok, syncing,
    #                     applied: per-op counts, seen: per-op bools)

    def holders(self, s):
        return (s % self.world, (s + 1) % self.world)

    def other_holder(self, s, r):
        a, b = self.holders(s)
        return b if r == a else a

    def init(self):
        zeros = (0,) * self.n_ops
        falses = (False,) * self.n_ops
        copies = []
        for r, s in self.slots:
            if r == s % self.world:          # home primary: serving
                copies.append((1, True, True, True, False, zeros, falses))
            elif s in self.unsynced:         # backup mid-sync
                copies.append((1, False, False, False, True, zeros,
                               falses))
            else:                            # synced standby backup
                copies.append((1, False, True, True, False, zeros,
                               falses))
        ops = tuple((_IDLE, 0, 0, s % self.world, 1, 0)
                    for s in self.shards)
        # op tuple: (status, sends, promotes, route, epoch, flip_epoch)
        return (ops, tuple(copies), (True,) * self.world,
                (False,) * self.world, (), self.fault_budget)

    # -- tuple surgery helpers --------------------------------------------

    @staticmethod
    def _upd(tup, i, val):
        return tup[:i] + (val,) + tup[i + 1:]

    def _demoted(self, copy, epoch):
        """The ``_demote`` effect: adopt the newer epoch, stop serving,
        drop promotability, stop forwarding."""
        return (max(copy[0], epoch), False, False, False, copy[4],
                copy[5], copy[6])

    # -- transition relation ----------------------------------------------

    def actions(self, state):
        ops, copies, alive, parts, msgs, fault = state
        out = []

        def emit(label, nops=None, ncopies=None, nalive=None,
                 nparts=None, nmsgs=None, nfault=None):
            out.append((label, (
                ops if nops is None else nops,
                copies if ncopies is None else ncopies,
                alive if nalive is None else nalive,
                parts if nparts is None else nparts,
                msgs if nmsgs is None else tuple(sorted(nmsgs)),
                fault if nfault is None else nfault)))

        def unreachable(r):
            return not alive[r] or parts[r]

        # client actions --------------------------------------------------
        for i, op in enumerate(ops):
            st, sends, proms, route, epoch, flip = op
            s = self.shards[i]
            if st == _IDLE or st == _RESEND:
                if sends < self.max_sends:
                    nop = (_WAIT, sends + 1, proms, route, epoch, flip)
                    emit(("send", f"c{i}", f"r{route}"),
                         nops=self._upd(ops, i, nop),
                         nmsgs=msgs + (("PUSH", i, route, epoch),))
                elif st == _RESEND:
                    emit(("give_up", f"c{i}"), nops=self._upd(
                        ops, i, (_FAILED,) + op[1:]))
            elif st == _CONN:
                # conn-failed route: client-side failover — promote the
                # shard's other holder with want = our epoch + 1
                if proms < self.max_promotes:
                    alt = self.other_holder(s, route)
                    nop = (_WPROM, sends, proms + 1, route, epoch, flip)
                    emit(("failover", f"c{i}", f"r{alt}"),
                         nops=self._upd(ops, i, nop),
                         nmsgs=msgs + (("PROMOTE", i, alt, epoch + 1),))
                else:
                    emit(("give_up", f"c{i}"), nops=self._upd(
                        ops, i, (_FAILED,) + op[1:]))

        # message deliveries ----------------------------------------------
        for m in msgs:
            rest = tuple(x for x in msgs if x != m)
            i = m[1]
            op = ops[i]
            st, sends, proms, route, epoch, flip = op
            s = self.shards[i]
            if m[0] == "PUSH":
                _, _, dst, e = m
                label = ("deliver_push", f"c{i}", f"r{dst}")
                if unreachable(dst):
                    emit(label, nops=self._upd(
                        ops, i, (_CONN, sends, proms, route, epoch,
                                 flip)), nmsgs=rest)
                    continue
                ci = self.slot_ix.get((dst, s))
                copy = copies[ci] if ci is not None else None
                if copy is None or not copy[1]:
                    emit(label, nmsgs=rest + (("NSERV", i, dst),))
                    continue
                cur = copy[0]
                if e < cur:          # stale client: teach it our epoch
                    emit(label,
                         nmsgs=rest + (("FENCE", i, dst, cur, True),))
                    continue
                if e > cur:          # we missed a promotion: demote
                    emit(label, ncopies=self._upd(
                        copies, ci, self._demoted(copy, e)),
                        nmsgs=rest + (("FENCE", i, dst, e, False),))
                    continue
                if copy[6][i]:       # (client, seq) dedup window hit
                    emit(("dedup_ack", f"c{i}", f"r{dst}"),
                         nmsgs=rest + (("ACK", i, dst, cur),))
                    continue
                ncopies = list(copies)
                peer = self.other_holder(s, dst)
                pi = self.slot_ix.get((peer, s))
                pc = copies[pi] if pi is not None else None
                if not copy[3] and pc is not None and \
                        not unreachable(peer) and pc[0] > cur:
                    # degraded-serving deposed-check (_probe_lineage
                    # before the apply): refuse instead of acking onto
                    # the losing lineage
                    emit(("probe_fenced", f"c{i}", f"r{dst}"),
                         ncopies=self._upd(
                             copies, ci, self._demoted(copy, pc[0])),
                         nmsgs=rest + (("FENCE", i, dst, pc[0],
                                        False),))
                    continue
                applied = self._upd(copy[5], i, copy[5][i] + 1)
                seen = self._upd(copy[6], i, True)
                ncopy = (cur, True, copy[2], copy[3], copy[4], applied,
                         seen)
                fenced = False
                if pc is not None and not pc[4] and copy[3]:
                    # synchronous mirror (apply+mirror-before-ack): the
                    # peer's _fence_or_adopt gate runs refuse_equal_if_
                    # serving — an equal-epoch second primary is refused
                    if unreachable(peer):
                        ncopy = ncopy[:3] + (False,) + ncopy[4:]
                    elif pc[0] > cur or (pc[0] == cur and pc[1]):
                        ncopies[ci] = self._demoted(ncopy, pc[0])
                        emit(("fwd_fenced", f"c{i}", f"r{dst}"),
                             ncopies=tuple(ncopies),
                             nmsgs=rest + (("FENCE", i, dst, pc[0],
                                            False),))
                        fenced = True
                    else:
                        papp = pc[5] if pc[6][i] else \
                            self._upd(pc[5], i, pc[5][i] + 1)
                        ncopies[pi] = (max(pc[0], cur), pc[1], pc[2],
                                       pc[3], pc[4], papp,
                                       self._upd(pc[6], i, True))
                if not fenced:
                    ncopies[ci] = ncopy
                    emit(("apply_ack", f"c{i}", f"r{dst}"),
                         ncopies=tuple(ncopies),
                         nmsgs=rest + (("ACK", i, dst, cur),))
            elif m[0] == "ACK":
                _, _, src, e = m
                if unreachable(src):     # ack lost with the connection
                    nop = (_CONN, sends, proms, route, epoch, flip)
                else:
                    nop = (_ACKED, sends, proms, route, max(epoch, e),
                           flip)
                emit(("deliver_ack", f"c{i}"),
                     nops=self._upd(ops, i, nop), nmsgs=rest)
            elif m[0] == "FENCE":
                _, _, src, cur, serving = m
                if unreachable(src):
                    nop = (_CONN, sends, proms, route, epoch, flip)
                else:
                    # _note_fence: locked max-merge + at-most-one route
                    # flip per epoch, only on a refusal at least as new
                    # as what we know and only when the refuser no
                    # longer serves
                    ne = max(epoch, cur)
                    nroute, nflip = route, flip
                    if not serving and cur == ne and flip != cur:
                        nroute = self.other_holder(s, route)
                        nflip = cur
                    nop = (_RESEND, sends, proms, nroute, ne, nflip)
                emit(("deliver_fence", f"c{i}"),
                     nops=self._upd(ops, i, nop), nmsgs=rest)
            elif m[0] == "NSERV":
                # stale route hit a non-serving holder: failover-worthy
                emit(("deliver_nserv", f"c{i}"), nops=self._upd(
                    ops, i, (_CONN, sends, proms, route, epoch, flip)),
                    nmsgs=rest)
            elif m[0] == "PROMOTE":
                _, _, dst, want = m
                label = ("deliver_promote", f"c{i}", f"r{dst}")
                if unreachable(dst):
                    emit(label, nops=self._upd(
                        ops, i, (_FAILED,) + op[1:]), nmsgs=rest)
                    continue
                ci = self.slot_ix.get((dst, s))
                copy = copies[ci] if ci is not None else None
                if copy is None:
                    emit(label, nmsgs=rest + (("PFAIL", i, dst),))
                elif copy[1]:        # idempotent re-promote: adopt want
                    ep = max(copy[0], want)
                    emit(label, ncopies=self._upd(
                        copies, ci, (ep,) + copy[1:]),
                        nmsgs=rest + (("PROMOTED", i, dst, ep),))
                elif not copy[2] and self.mutation != "promote_unsynced":
                    # the synced-copy gate: a never-synced (or demoted)
                    # copy would resurrect stale state — refuse loudly
                    emit(label, nmsgs=rest + (("PFAIL", i, dst),))
                else:
                    if self.mutation == "promote_no_epoch_bump":
                        ep = copy[0]
                    else:
                        ep = max(copy[0] + 1, want)
                    ncopy = (ep, True, copy[2], False, False, copy[5],
                             copy[6])
                    emit(label, ncopies=self._upd(copies, ci, ncopy),
                         nmsgs=rest + (("PROMOTED", i, dst, ep),))
            elif m[0] == "PROMOTED":
                _, _, src, ep = m
                if unreachable(src):
                    nop = (_FAILED, sends, proms, route, epoch, flip)
                else:
                    # the promotion IS this epoch's route change
                    nop = (_RESEND, sends, proms, src, max(epoch, ep),
                           max(epoch, ep))
                emit(("deliver_promoted", f"c{i}"),
                     nops=self._upd(ops, i, nop), nmsgs=rest)
            elif m[0] == "PFAIL":
                emit(("deliver_pfail", f"c{i}"), nops=self._upd(
                    ops, i, (_FAILED,) + op[1:]), nmsgs=rest)

        # environment -----------------------------------------------------
        if fault > 0:
            for r in range(self.world):
                if alive[r]:
                    emit(("kill", f"r{r}"),
                         nalive=self._upd(alive, r, False),
                         nfault=fault - 1)
                    if not parts[r]:
                        emit(("partition", f"r{r}"),
                             nparts=self._upd(parts, r, True),
                             nfault=fault - 1)
        for r in range(self.world):
            if parts[r]:
                emit(("heal", f"r{r}"), nparts=self._upd(parts, r,
                                                         False))
        for label, ncopies in self._converge_actions(state):
            emit(label, ncopies=ncopies)
        return out

    def _converge_actions(self, state):
        """sync_done + lineage-probe transitions — separated so
        :meth:`quiescent` can ask "is any convergence step still
        enabled?" without re-deriving the guards."""
        ops, copies, alive, parts, msgs, fault = state
        out = []

        def reachable(r):
            return alive[r] and not parts[r]

        for ci, (r, s) in enumerate(self.slots):
            copy = copies[ci]
            if copy is None:
                continue
            if copy[4] and reachable(r):
                # sync completion: snapshot + op-log catch-up land, the
                # copy becomes promotable and live forwarding resumes
                src_ix = self.slot_ix[(self.other_holder(s, r), s)]
                src = copies[src_ix]
                if src is not None and src[1] and \
                        reachable(self.slots[src_ix][0]):
                    ncopies = self._upd(copies, ci, (
                        src[0], False, True, True, False, src[5],
                        src[6]))
                    ncopies = self._upd(ncopies, src_ix,
                                        src[:3] + (True,) + src[4:])
                    out.append((("sync_done", f"r{r}", f"s{s}"),
                                ncopies))
            if copy[1] and reachable(r):
                # lineage probe: any reachable peer copy with a newer
                # epoch means we were deposed — demote (OP_EPOCH probe
                # / refused forward / fenced traffic all teach this)
                peer = self.other_holder(s, r)
                pi = self.slot_ix.get((peer, s))
                pc = copies[pi] if pi is not None else None
                if pc is not None and reachable(peer) and \
                        pc[0] > copy[0]:
                    out.append((("probe_demote", f"r{r}", f"s{s}"),
                                self._upd(copies, ci, self._demoted(
                                    copy, pc[0]))))
        return out

    # -- invariants --------------------------------------------------------

    def _inv_exactly_once(self, state):
        ops, copies, alive, parts, msgs, fault = state
        for ci, copy in enumerate(copies):
            if copy is None:
                continue
            for i, n in enumerate(copy[5]):
                if n > 1:
                    r, s = self.slots[ci]
                    return (f"op c{i} applied {n}x on rank {r}'s copy "
                            f"of shard {s} (dedup window breached)")
        return None

    def _inv_gate(self, state):
        ops, copies, alive, parts, msgs, fault = state
        for ci, copy in enumerate(copies):
            if copy is not None and copy[1] and not copy[2]:
                r, s = self.slots[ci]
                return (f"rank {r} SERVES shard {s} from a copy that "
                        f"is not promotable (unsynced or demoted)")
        return None

    def _inv_epoch_monotone(self, s0, label, s1):
        for ci in range(len(self.slots)):
            c0, c1 = s0[1][ci], s1[1][ci]
            if c0 is not None and c1 is not None and c1[0] < c0[0]:
                r, sh = self.slots[ci]
                return (f"rank {r} shard {sh} epoch went backwards "
                        f"{c0[0]} -> {c1[0]}")
        for i in range(self.n_ops):
            if s1[0][i][4] < s0[0][i][4]:
                return (f"client c{i} epoch went backwards "
                        f"{s0[0][i][4]} -> {s1[0][i][4]}")
        return None

    def _inv_single_lineage(self, state):
        ops, copies, alive, parts, msgs, fault = state
        for s in self.shards:
            serving = [r for (r, sh), ci in self.slot_ix.items()
                       if sh == s and alive[r]
                       and copies[ci] is not None and copies[ci][1]]
            if len(serving) > 1:
                return (f"shard {s} has {len(serving)} live serving "
                        f"copies (ranks {sorted(serving)}) at "
                        f"quiescence — split brain")
        return None

    def _inv_no_lost_write(self, state):
        ops, copies, alive, parts, msgs, fault = state
        for i, op in enumerate(ops):
            if op[0] != _ACKED:
                continue
            s = self.shards[i]
            for (r, sh), ci in self.slot_ix.items():
                copy = copies[ci]
                if sh == s and alive[r] and copy is not None \
                        and copy[1] and copy[5][i] < 1:
                    return (f"acked op c{i} missing from the serving "
                            f"copy of shard {s} on rank {r} — failover "
                            f"lost an acknowledged write")
        return None

    def _inv_ops_terminate(self, state):
        for i, op in enumerate(state[0]):
            if op[0] not in (_ACKED, _FAILED):
                return (f"stuck state: op c{i} is '{op[0]}' with no "
                        f"enabled transition")
        return None

    def quiescent(self, state):
        ops, copies, alive, parts, msgs, fault = state
        return (not msgs and not any(parts)
                and all(op[0] in (_ACKED, _FAILED) for op in ops)
                and not self._converge_actions(state))

    def render_state(self, state):
        ops, copies, alive, parts, msgs, fault = state
        bits = []
        for i, op in enumerate(ops):
            bits.append(f"c{i}:{op[0]}@e{op[4]}->r{op[3]}")
        for ci, (r, s) in enumerate(self.slots):
            c = copies[ci]
            if c is None:
                continue
            flags = ("S" if c[1] else "-") + ("P" if c[2] else "-") + \
                ("F" if c[3] else "-") + ("y" if c[4] else "-")
            bits.append(f"r{r}s{s}:e{c[0]}{flags}{list(c[5])}")
        bits.append("alive=" + "".join("1" if a else "0" for a in alive))
        if any(parts):
            bits.append("cut=" + "".join(
                "1" if p else "0" for p in parts))
        if msgs:
            bits.append(f"msgs={list(msgs)}")
        return " ".join(bits)


# ------------------------------------------------ model: decode recovery

class DecodeRecoveryModel(_ModelBase):
    """Exactly-once in-flight decode stream migration (ISSUE 19) as a
    guarded-transition system.

    Streams carry a replay epoch, a journal prefix (per-index delivered
    counts), and a retry count; replicas are ok / dead / wedged.  The
    sweep detaches a stream seated on a non-ok replica (atomic epoch
    bump + journal snapshot — ``DecodeStream._detach``), the front door
    re-seats it on a survivor (``adopt`` + chunked-prefill
    continuation) or fails it fast once ``retries`` exceeds the budget
    or no survivor remains.  A WEDGED replica's engine keeps running:
    after detach its emissions arrive with the stale epoch and must be
    dropped by the stream fence (``zombie_emit`` — a no-op at HEAD).

    ``mutation='zombie_emit_unfenced'`` re-introduces the PR 19 bug
    class: the stale emission lands in the journal anyway.
    """

    name = "decode_recovery"

    def __init__(self, n_streams=2, n_replicas=2, max_tokens=2,
                 retry_budget=1, fault_budget=2, mutation=None):
        assert mutation in (None, "zombie_emit_unfenced"), mutation
        self.n_streams = int(n_streams)
        self.n_replicas = int(n_replicas)
        self.max_tokens = int(max_tokens)
        self.retry_budget = int(retry_budget)
        self.fault_budget = int(fault_budget)
        self.mutation = mutation
        self.invariants = (
            ("exactly-once-token", self._inv_exactly_once),
            ("no-journal-gaps", self._inv_gaps),
            ("retry-budget", self._inv_budget),
        )
        self.edge_invariants = (
            ("fenced-zombie-never-mutates", self._inv_zombie),
            ("stream-epoch-monotone", self._inv_epoch),
        )
        self.terminal_invariants = (
            ("recovery-terminates", self._inv_terminates),
        )

    # stream tuple: (phase, seat, epoch, nxt, counts, retries)
    # zombie tuple: (sid, replica, stale_epoch, frozen_next)

    def init(self):
        streams = tuple(("q", -1, 0, 0, (0,) * self.max_tokens, 0)
                        for _ in range(self.n_streams))
        return (streams, (), ("ok",) * self.n_replicas,
                self.fault_budget)

    @staticmethod
    def _upd(tup, i, val):
        return tup[:i] + (val,) + tup[i + 1:]

    def actions(self, state):
        streams, zombies, reps, fault = state
        out = []
        any_ok = any(st == "ok" for st in reps)
        for sid, stream in enumerate(streams):
            phase, seat, epoch, nxt, counts, retries = stream
            if phase == "q":
                for r, st in enumerate(reps):
                    if st == "ok":
                        out.append((("seat", f"s{sid}", f"r{r}"), (
                            self._upd(streams, sid,
                                      ("s", r, epoch, nxt, counts,
                                       retries)),
                            zombies, reps, fault)))
                if not any_ok:
                    # recovery gate: zero survivors — fail FAST with the
                    # partial journal instead of queueing forever
                    out.append((("fail_no_survivor", f"s{sid}"), (
                        self._upd(streams, sid,
                                  ("failed", -1, epoch, nxt, counts,
                                   retries)),
                        zombies, reps, fault)))
            elif phase == "s":
                if reps[seat] == "ok":
                    nc = self._upd(counts, nxt, counts[nxt] + 1)
                    nphase = "done" if nxt + 1 >= self.max_tokens \
                        else "s"
                    nseat = -1 if nphase == "done" else seat
                    out.append((("emit", f"s{sid}", f"t{nxt}"), (
                        self._upd(streams, sid,
                                  (nphase, nseat, epoch, nxt + 1, nc,
                                   retries)),
                        zombies, reps, fault)))
                else:
                    # sweep detach: atomic epoch bump + journal
                    # snapshot; a wedged replica's engine lives on as a
                    # fenced zombie
                    nz = zombies + ((sid, seat, epoch, nxt),) \
                        if reps[seat] == "wedged" else zombies
                    if retries >= self.retry_budget:
                        ns = ("failed", -1, epoch, nxt, counts, retries)
                        out.append((("detach_exhausted", f"s{sid}"), (
                            self._upd(streams, sid, ns),
                            tuple(sorted(nz)), reps, fault)))
                    else:
                        ns = ("q", -1, epoch + 1, nxt, counts,
                              retries + 1)
                        out.append((("detach", f"s{sid}"), (
                            self._upd(streams, sid, ns),
                            tuple(sorted(nz)), reps, fault)))
        for zi, (sid, r, ze, zn) in enumerate(zombies):
            if reps[r] == "wedged":
                rest = zombies[:zi] + zombies[zi + 1:]
                if self.mutation == "zombie_emit_unfenced" and \
                        zn < self.max_tokens:
                    st = streams[sid]
                    nc = self._upd(st[4], zn, st[4][zn] + 1)
                    nstreams = self._upd(
                        streams, sid, st[:4] + (nc, st[5]))
                else:
                    nstreams = streams   # fenced: journal untouched
                out.append((("zombie_emit", f"s{sid}", f"r{r}",
                             f"t{zn}"),
                            (nstreams, rest, reps, fault)))
        if fault > 0:
            for r, st in enumerate(reps):
                if st == "ok":
                    out.append((("kill", f"r{r}"), (
                        streams, zombies,
                        self._upd(reps, r, "dead"), fault - 1)))
                    out.append((("wedge", f"r{r}"), (
                        streams, zombies,
                        self._upd(reps, r, "wedged"), fault - 1)))
        return out

    def _inv_exactly_once(self, state):
        for sid, st in enumerate(state[0]):
            for idx, n in enumerate(st[4]):
                if n > 1:
                    return (f"stream s{sid} token index {idx} "
                            f"delivered {n}x")
        return None

    def _inv_gaps(self, state):
        for sid, st in enumerate(state[0]):
            nxt, counts = st[3], st[4]
            for idx, n in enumerate(counts):
                want = 1 if idx < nxt else 0
                if n != want:
                    return (f"stream s{sid} journal gap at index "
                            f"{idx}: delivered {n}, next={nxt}")
        return None

    def _inv_budget(self, state):
        for sid, st in enumerate(state[0]):
            if st[5] > self.retry_budget:
                return (f"stream s{sid} recovered {st[5]}x — past the "
                        f"retry budget {self.retry_budget}")
        return None

    def _inv_zombie(self, s0, label, s1):
        if label[0] == "zombie_emit" and s1[0] != s0[0]:
            return (f"stale-epoch emission {label} mutated a stream's "
                    f"journal — the replay-epoch fence did not hold")
        return None

    def _inv_epoch(self, s0, label, s1):
        for sid in range(self.n_streams):
            if s1[0][sid][2] < s0[0][sid][2]:
                return f"stream s{sid} replay epoch went backwards"
        return None

    def _inv_terminates(self, state):
        for sid, st in enumerate(state[0]):
            if st[0] not in ("done", "failed"):
                return (f"stuck state: stream s{sid} is '{st[0]}' with "
                        f"no enabled transition")
        return None

    def render_state(self, state):
        streams, zombies, reps, fault = state
        bits = [f"s{sid}:{st[0]}@e{st[2]}n{st[3]}{list(st[4])}"
                f"x{st[5]}" for sid, st in enumerate(streams)]
        bits.append("reps=" + ",".join(reps))
        if zombies:
            bits.append(f"zombies={list(zombies)}")
        return " ".join(bits)


# ------------------------------------------------- model: elastic resize

class ElasticResizeModel(_ModelBase):
    """Elastic dp resize (ISSUE 12) as a guarded-transition system.

    Ranks are (alive, reachable, hb_missed, held); ``poll`` runs only
    at a step boundary (async in-flight window drained to zero) and
    applies the controller's decision function: shrink ranks that are
    dead AND heartbeat-silent for the full wait window (unless the
    survivors would drop below ``min_dp`` — refused), HOLD ranks that
    are alive-but-unreachable (partition is fencing's problem, not a
    shrink), re-admit healed/rejoining ranks.  Environment: one kill,
    one partition episode, heartbeat misses, async launches/drains.
    """

    name = "elastic_resize"

    def __init__(self, n_ranks=3, min_dp=2, hb_threshold=2, window=2,
                 kill_budget=1, cut_budget=1):
        self.world = int(n_ranks)
        self.min_dp = int(min_dp)
        self.th = int(hb_threshold)
        self.window = int(window)
        self.kill_budget = int(kill_budget)
        self.cut_budget = int(cut_budget)
        self.invariants = (
            ("min-dp-floor", self._inv_floor),
        )
        self.edge_invariants = (
            ("resize-at-step-boundary", self._inv_boundary),
            ("held-unreachable-never-shrunk", self._inv_held),
        )
        self.quiescent_invariants = (
            ("heartbeat-wait-window-liveness", self._inv_liveness),
        )

    # rank tuple: (alive, reachable, missed, held)

    def init(self):
        ranks = tuple((True, True, 0, False)
                      for _ in range(self.world))
        return (ranks, tuple(range(self.world)), 0, self.kill_budget,
                self.cut_budget)

    @staticmethod
    def _upd(tup, i, val):
        return tup[:i] + (val,) + tup[i + 1:]

    def _poll_result(self, state):
        """The controller's deterministic decision at a boundary; None
        when poll would be a no-op."""
        ranks, active, inflight, kb, cb = state
        nranks = list(ranks)
        act = set(active)
        for r, (alv, reach, missed, held) in enumerate(ranks):
            if not alv and held:
                # the hold set tracks alive-but-unreachable ranks; a
                # held rank that dies graduates to the shrink path
                nranks[r] = (alv, reach, missed, False)
                held = False
            if r in act and missed >= self.th:
                if not alv:
                    if len(act) - 1 >= self.min_dp:
                        act.discard(r)           # shrink the dead rank
                elif not reach and not held:
                    nranks[r] = (alv, reach, missed, True)   # HOLD
            if alv and reach and r not in act:
                act.add(r)                       # rejoin / grow back
                nranks[r] = (alv, reach, 0, False)
            if alv and reach and held:
                nranks[r] = (alv, reach, 0, False)
        nstate = (tuple(nranks), tuple(sorted(act)), inflight, kb, cb)
        return None if nstate == state else nstate

    def actions(self, state):
        ranks, active, inflight, kb, cb = state
        out = []
        if inflight < self.window:
            out.append((("launch_async",),
                        (ranks, active, inflight + 1, kb, cb)))
        if inflight > 0:
            out.append((("drain_async",),
                        (ranks, active, inflight - 1, kb, cb)))
        for r, (alv, reach, missed, held) in enumerate(ranks):
            if alv and kb > 0:
                out.append((("kill", f"r{r}"), (
                    self._upd(ranks, r, (False, reach, missed, held)),
                    active, inflight, kb - 1, cb)))
            if alv and reach and cb > 0:
                out.append((("partition", f"r{r}"), (
                    self._upd(ranks, r, (alv, False, missed, held)),
                    active, inflight, kb, cb - 1)))
            if alv and not reach:
                out.append((("heal", f"r{r}"), (
                    self._upd(ranks, r, (alv, True, missed, held)),
                    active, inflight, kb, cb)))
            if (not alv or not reach) and missed < self.th:
                out.append((("hb_miss", f"r{r}"), (
                    self._upd(ranks, r, (alv, reach, missed + 1,
                                         held)),
                    active, inflight, kb, cb)))
        if inflight == 0:
            ns = self._poll_result(state)
            if ns is not None:
                out.append((("poll",), ns))
        return out

    def _inv_floor(self, state):
        if len(state[1]) < self.min_dp:
            return (f"active dp {len(state[1])} fell below the "
                    f"min_dp={self.min_dp} floor")
        return None

    def _inv_boundary(self, s0, label, s1):
        if s0[1] != s1[1]:
            if label[0] != "poll":
                return (f"active set changed on a non-poll transition "
                        f"{label}")
            if s0[2] != 0:
                return (f"resize ran with {s0[2]} async steps still "
                        f"in flight — not a step boundary")
        return None

    def _inv_held(self, s0, label, s1):
        removed = set(s0[1]) - set(s1[1])
        for r in removed:
            alv, reach, missed, held = s0[0][r]
            if alv:
                return (f"rank {r} was shrunk out while still ALIVE "
                        f"({'held ' if held else ''}unreachable ranks "
                        f"must be HELD, not shrunk)")
            if missed < self.th:
                return (f"rank {r} was shrunk out after only {missed} "
                        f"heartbeat misses (wait window is {self.th})")
        return None

    def quiescent(self, state):
        ranks, active, inflight, kb, cb = state
        if inflight != 0 or self._poll_result(state) is not None:
            return False
        return all(alv and reach or missed >= self.th
                   for alv, reach, missed, held in ranks)

    def _inv_liveness(self, state):
        ranks, active, inflight, kb, cb = state
        act = set(active)
        for r, (alv, reach, missed, held) in enumerate(ranks):
            if not alv and r in act:
                survivors = len(act) - sum(
                    1 for rr in act if not ranks[rr][0])
                if survivors >= self.min_dp:
                    return (f"dead rank {r} still active at quiescence "
                            f"though the shrink was admissible")
            if alv and reach and r not in act:
                return (f"rank {r} is alive+reachable but excluded at "
                        f"quiescence — grow-back never happened")
            if held and not (alv and r in act):
                return f"rank {r} held but not an active alive rank"
        return None

    def render_state(self, state):
        ranks, active, inflight, kb, cb = state
        bits = []
        for r, (alv, reach, missed, held) in enumerate(ranks):
            bits.append(f"r{r}:{'A' if alv else 'd'}"
                        f"{'R' if reach else 'u'}m{missed}"
                        f"{'H' if held else ''}")
        bits.append(f"active={list(active)} inflight={inflight}")
        return " ".join(bits)


# ------------------------------------------------- mutations + registry

#: the three historical bug classes, re-introduced as model mutations —
#: the checker must produce a counterexample naming each one's invariant
SEEDED_MUTATIONS = {
    "promote_unsynced": {
        "model": "ps_replication",
        "invariant": "demoted-or-unsynced-never-serves",
        "history": "PR 4 review: promotion without the synced-copy "
                   "gate silently serves seed-initialized state",
    },
    "promote_no_epoch_bump": {
        "model": "ps_replication",
        "invariant": "single-serving-lineage",
        "history": "PR 8 split-brain: a promotion that reuses the "
                   "current epoch leaves the deposed primary "
                   "unfenceable",
    },
    "zombie_emit_unfenced": {
        "model": "decode_recovery",
        "invariant": "fenced-zombie-never-mutates",
        "history": "PR 19: a migrated-away replica's stale emission "
                   "lands in the journal without the replay-epoch "
                   "fence",
    },
}


def build_model(name, mutation=None, deep=False):
    """Model factory for the CLI / tests.  ``deep`` widens the budgets
    (more sends, a second fault) for the slow exhaustive sweep."""
    if name == "ps_replication":
        if deep:
            return PSReplicationModel(n_ranks=4, shards=(0, 1, 2),
                                      unsynced=(1,), max_sends=4,
                                      mutation=mutation)
        return PSReplicationModel(mutation=mutation)
    if name == "decode_recovery":
        if deep:
            return DecodeRecoveryModel(n_streams=2, n_replicas=3,
                                       max_tokens=3, retry_budget=2,
                                       fault_budget=3,
                                       mutation=mutation)
        return DecodeRecoveryModel(mutation=mutation)
    if name == "elastic_resize":
        assert mutation is None, mutation
        if deep:
            return ElasticResizeModel(n_ranks=4, window=3,
                                      kill_budget=2)
        return ElasticResizeModel()
    raise ValueError(f"unknown protocol model {name!r}")


MODELS = ("ps_replication", "decode_recovery", "elastic_resize")


def verify_all(deep=False, max_states=500_000):
    """Check every model at HEAD (expect zero violations) and every
    seeded mutation (expect a counterexample naming its invariant).
    Returns a JSON-able report — the core of
    ``artifacts/protocol_verify.json``."""
    report = {"models": {}, "mutations": {}, "ok": True}
    for name in MODELS:
        res = check(build_model(name, deep=deep), max_states=max_states)
        report["models"][name] = res.to_dict()
        report["ok"] &= res.ok and res.complete
    for mname, spec in SEEDED_MUTATIONS.items():
        res = check(build_model(spec["model"], mutation=mname,
                                deep=False), max_states=max_states)
        got = res.violations[0].invariant if res.violations else None
        hit = got == spec["invariant"]
        report["mutations"][mname] = {
            "model": spec["model"], "expected": spec["invariant"],
            "violated": got, "ok": hit,
            "trace_len": len(res.violations[0].trace)
            if res.violations else 0,
            "history": spec["history"],
        }
        report["ok"] &= hit
    return report


# ------------------------------------------ opcode alphabet (drift gate)

#: PS wire opcodes the replication model gives semantics to — the
#: message alphabet the lint drift gate checks ``ps/opcodes``' registry
#: against (a new replication-relevant opcode must land here or in the
#: allowlist below, with a reason)
PS_MESSAGE_ALPHABET = {
    "OP_PUSH": "client write: the deliver_push transition "
               "(fence -> dedup -> apply+mirror-before-ack)",
    "OP_PUSH_PULL": "fused write+read: its push half is deliver_push; "
                    "the pull half is the unfenced read plane",
    "OP_SET_DATA": "whole-table write: same fence/dedup/mirror path as "
                   "OP_PUSH (deliver_push)",
    "OP_REPLICATE": "the synchronous mirror inside deliver_push, with "
                    "the peer's _fence_or_adopt gate "
                    "(refuse_equal_if_serving)",
    "OP_PROMOTE": "the deliver_promote transition: synced-copy gate + "
                  "max(cur+1, want) epoch bump",
    "OP_INIT": "replica table creation rides the replica-plane "
               "_fence_or_adopt gate; collapsed into the model's "
               "initial copy placement",
    "OP_SYNC": "re-replication source half; collapsed into the "
               "sync_done transition (promotability gate)",
    "OP_SYNC_PUT": "re-replication sink half; completion IS the "
                   "sync_done transition that earns promotability",
    "OP_EPOCH": "lineage introspection: the probe_demote transition "
                "(healed split-brain convergence)",
}

#: PS opcodes deliberately OUTSIDE the replication model, each with the
#: reason it does not carry replicated-state-mutation semantics
PS_OPCODE_ALLOWLIST = {
    "OP_PULL": "read plane: deliberately unfenced bounded-staleness "
               "reads; fencing guards the write plane only",
    "OP_VERSIONS": "read plane: per-row version introspection, no "
                   "mutation",
    "OP_CLOCK": "SSP clock tick: rides shard-0 replication with the "
                "SAME (client, seq) dedup + forward path the model "
                "checks for OP_PUSH — no separate protocol arm",
    "OP_CLOCKS": "read plane: SSP clock-vector snapshot",
    "OP_SSP_SYNC": "scheduler plane: bounded server-side wait, no "
                   "replicated-state mutation",
    "OP_SSP_INIT": "scheduler plane: idempotent channel init, mirrored "
                   "via the modeled forward path",
    "OP_HEARTBEAT": "liveness plane: modeled abstractly by the elastic "
                    "model's hb_miss/poll transitions",
    "OP_ALIVE": "liveness read: mask snapshot, no mutation",
    "OP_SHUTDOWN": "admin plane: connection teardown",
    "OP_CHECKSUM": "fsck read plane: state digest of a held copy, no "
                   "mutation",
}


# ------------------------------------------------------ trace conformance

#: divergence rules accepted with a documented reason (the ISSUE 20
#: triage outlet: a REAL divergence found on a committed chaos bench is
#: either fixed with a regression test or allowlisted here)
CONFORMANCE_ALLOWLIST = {}


class ConformanceReport:
    """Per-plane replay verdict: events checked, divergences (each a
    dict naming the violated rule + the event index), allowlisted
    divergences."""

    __slots__ = ("plane", "checked", "divergences", "allowlisted")

    def __init__(self, plane):
        self.plane = plane
        self.checked = 0
        self.divergences = []
        self.allowlisted = []

    @property
    def ok(self):
        return not self.divergences

    def to_dict(self):
        return {"plane": self.plane, "checked": self.checked,
                "ok": self.ok, "divergences": list(self.divergences),
                "allowlisted": list(self.allowlisted)}

    def flag(self, rule, ev, detail, allowlist):
        d = {"plane": self.plane, "rule": rule,
             "event": ev.get("i", -1), "detail": detail}
        if rule in allowlist:
            d["reason"] = allowlist[rule]
            self.allowlisted.append(d)
            _record("protocol_divergences_allowlisted")
        else:
            self.divergences.append(d)
            _record("protocol_divergences")


class _PSMonitor:
    """Replays recorded ``ps`` events against the replication model's
    transition relation: per-copy epoch monotonicity, promote-bumps-
    epoch, the fence gates' stale-only refusal discipline, demoted
    copies never serving another apply, and per-copy exactly-once
    (client, seq) application."""

    def __init__(self, report, allowlist):
        self.rep = report
        self.allow = allowlist
        self.epoch = {}          # (rank, shard) -> last seen epoch
        self.serving = {}        # (rank, shard) -> True/False/unknown
        self.applied = set()     # (rank, shard, client, seq)

    def _epoch_ok(self, key, epoch, ev):
        last = self.epoch.get(key)
        if last is not None and epoch < last:
            self.rep.flag("epoch-monotonicity", ev,
                          f"copy r{key[0]}/s{key[1]} epoch {last} -> "
                          f"{epoch}", self.allow)
        self.epoch[key] = max(epoch, last if last is not None else 0)

    def feed(self, ev):
        kind = ev["kind"]
        key = (ev.get("rank"), ev.get("shard"))
        if kind == "promote":
            old, new = ev["old"], ev["new"]
            if new <= old:
                self.rep.flag("promote-bumps-epoch", ev,
                              f"promotion of r{key[0]}/s{key[1]} kept "
                              f"epoch {old} -> {new}", self.allow)
            if new < ev.get("want", 0):
                self.rep.flag("promote-bumps-epoch", ev,
                              f"promotion epoch {new} below the "
                              f"client's want={ev['want']}", self.allow)
            self._epoch_ok(key, new, ev)
            self.serving[key] = True
        elif kind == "demote":
            self._epoch_ok(key, ev["epoch"], ev)
            self.serving[key] = False
        elif kind == "adopt":
            self._epoch_ok(key, ev["new"], ev)
        elif kind == "apply":
            self._epoch_ok(key, ev["epoch"], ev)
            if self.serving.get(key) is False:
                self.rep.flag("demoted-copy-served", ev,
                              f"serving-side apply on r{key[0]}/"
                              f"s{key[1]} after its demotion",
                              self.allow)
            self._once(key, ev)
        elif kind == "apply_replica":
            self._once(key, ev)
        elif kind == "fence_refused":
            cur, got = ev["cur"], ev["got"]
            if ev.get("gate") == "repl":
                if got > cur:
                    self.rep.flag("fence-refuses-stale-only", ev,
                                  f"replica gate refused a NEWER epoch "
                                  f"{got} > {cur}", self.allow)
            elif got == cur:
                self.rep.flag("fence-refuses-stale-only", ev,
                              f"serving gate refused an equal-epoch "
                              f"frame (epoch {cur})", self.allow)
        elif kind == "sync_done":
            self.serving.setdefault(key, False)
        # client-plane kinds (client_failover, client_promoted,
        # route_flip, dedup_hit) are counted, not constrained: the
        # server-side gates above are where the model's claims live

    def _once(self, key, ev):
        k = key + (ev.get("client"), ev.get("seq"))
        if None in k:
            return
        if k in self.applied:
            self.rep.flag("exactly-once-apply", ev,
                          f"(client={k[2]}, seq={k[3]}) applied twice "
                          f"on r{key[0]}/s{key[1]} — dedup window "
                          f"breached", self.allow)
        self.applied.add(k)


class _DecodeMonitor:
    """Replays recorded ``decode`` events: per-stream journal
    contiguity + exactly-once token indices, accepted emissions carry
    the CURRENT replay epoch (a stale accepted emission is the PR 19
    zombie bug), detach bumps the epoch by one, fences drop only stale
    epochs, retries stay within the budget."""

    def __init__(self, report, allowlist):
        self.rep = report
        self.allow = allowlist
        self.epoch = {}
        self.nxt = {}

    def feed(self, ev):
        kind, sid = ev["kind"], ev.get("sid")
        if kind == "seat":
            if sid not in self.epoch:
                self.epoch[sid] = ev["epoch"]
                self.nxt[sid] = ev.get("n", 0)
            else:
                if ev["epoch"] != self.epoch[sid]:
                    self.rep.flag("stream-epoch-monotone", ev,
                                  f"s{sid} seated at epoch "
                                  f"{ev['epoch']}, tracked "
                                  f"{self.epoch[sid]}", self.allow)
                n = ev.get("n")
                if n is not None and n != self.nxt[sid]:
                    self.rep.flag("no-journal-gaps", ev,
                                  f"s{sid} reseated with journal {n}, "
                                  f"expected {self.nxt[sid]}",
                                  self.allow)
        elif kind == "emit":
            cur = self.epoch.setdefault(sid, ev["epoch"])
            if ev["epoch"] != cur:
                self.rep.flag("fenced-zombie-never-mutates", ev,
                              f"s{sid} ACCEPTED an emission at stale "
                              f"epoch {ev['epoch']} (current {cur})",
                              self.allow)
            want = self.nxt.setdefault(sid, ev["idx"])
            if ev["idx"] != want:
                self.rep.flag("exactly-once-token", ev,
                              f"s{sid} emitted index {ev['idx']}, "
                              f"expected {want} — duplicate or gap",
                              self.allow)
            self.nxt[sid] = max(want, ev["idx"] + 1)
        elif kind == "fenced":
            cur = self.epoch.get(sid)
            if cur is not None and ev["got"] >= cur:
                self.rep.flag("fence-only-stale", ev,
                              f"s{sid} fenced a CURRENT-epoch emission "
                              f"({ev['got']} >= {cur})", self.allow)
        elif kind == "detach":
            old, new = ev["old"], ev["new"]
            cur = self.epoch.get(sid)
            if new != old + 1 or (cur is not None and old != cur):
                self.rep.flag("stream-epoch-monotone", ev,
                              f"s{sid} detach epoch {old} -> {new} "
                              f"(tracked {cur})", self.allow)
            self.epoch[sid] = new
            budget = ev.get("budget")
            if budget is not None and ev.get("retries", 0) > budget:
                self.rep.flag("retry-budget", ev,
                              f"s{sid} requeued with retries="
                              f"{ev['retries']} past budget {budget}",
                              self.allow)
        # finish / fail / exhausted are terminal markers: counted only


class _ElasticMonitor:
    """Replays recorded ``elastic`` events: shrinks remove only ranks
    reported dead (never held-unreachable ones), the active set stays
    at or above ``min_dp``, refusals happen only below the floor."""

    def __init__(self, report, allowlist):
        self.rep = report
        self.allow = allowlist
        self.dead = set()
        self.held = set()

    def feed(self, ev):
        kind = ev["kind"]
        if kind == "dead":
            self.dead.add(ev["rank"])
            self.held.discard(ev["rank"])
        elif kind == "hold":
            self.held.add(ev["rank"])
        elif kind == "resize":
            removed = set(ev.get("removed", ()))
            for r in removed & self.held:
                self.rep.flag("held-unreachable-never-shrunk", ev,
                              f"rank {r} was HELD (alive, unreachable) "
                              f"yet shrunk out", self.allow)
            for r in removed - self.dead:
                self.rep.flag("shrink-only-dead", ev,
                              f"rank {r} shrunk without a preceding "
                              f"dead verdict", self.allow)
            if len(ev.get("active", ())) < ev.get("min_dp", 0):
                self.rep.flag("min-dp-floor", ev,
                              f"resize left dp="
                              f"{len(ev['active'])} below min_dp="
                              f"{ev['min_dp']}", self.allow)
            for r in ev.get("added", ()):
                self.dead.discard(r)
                self.held.discard(r)
        elif kind == "refused":
            if ev.get("survivors", 0) >= ev.get("min_dp", 0):
                self.rep.flag("refuse-only-below-floor", ev,
                              f"shrink refused with survivors="
                              f"{ev['survivors']} >= min_dp="
                              f"{ev['min_dp']}", self.allow)


def check_conformance(events, allowlist=None):
    """Replay a recorded run (:data:`PROTO` events, arrival order)
    against the models' transition relations.  Returns a JSON-able
    report with per-plane verdicts; ``ok`` is False iff any
    non-allowlisted divergence was found."""
    allowlist = CONFORMANCE_ALLOWLIST if allowlist is None else allowlist
    reports = {p: ConformanceReport(p)
               for p in ("ps", "decode", "elastic")}
    monitors = {"ps": _PSMonitor(reports["ps"], allowlist),
                "decode": _DecodeMonitor(reports["decode"], allowlist),
                "elastic": _ElasticMonitor(reports["elastic"],
                                           allowlist)}
    for ev in events:
        mon = monitors.get(ev.get("plane"))
        if mon is None:
            continue
        reports[ev["plane"]].checked += 1
        mon.feed(ev)
    _record("protocol_conformance_checks", len(events))
    out = {p: r.to_dict() for p, r in reports.items()}
    out["events"] = len(events)
    out["ok"] = all(r.ok for r in reports.values())
    return out


__all__ = [
    "PROTO", "protocol_event", "Violation", "CheckResult", "check",
    "PSReplicationModel", "DecodeRecoveryModel", "ElasticResizeModel",
    "SEEDED_MUTATIONS", "build_model", "MODELS", "verify_all",
    "PS_MESSAGE_ALPHABET", "PS_OPCODE_ALLOWLIST",
    "CONFORMANCE_ALLOWLIST", "ConformanceReport", "check_conformance",
]
