"""Abstract shape/dtype interpretation of fetch subgraphs — zero FLOPs.

The GSPMD lesson (PAPERS.md): whole-graph static propagation of shapes is
what makes errors *local* — a mis-shaped feed should fail at the node that
disagrees, not as an opaque XLA tracing error minutes into compilation.

Every op here already carries the ground truth: its ``lower`` rule.
``jax.eval_shape`` evaluates that rule over ``jax.ShapeDtypeStruct``
inputs, so every node gets a static ``(shape, dtype)`` without executing
anything — no hand-written per-op shape rules needed (where hand rules
exist they are CROSS-CHECKED against this interpreter by the
``shape-rule-mismatch`` lint).

Two paths:

* :func:`infer_graph` — whole-subgraph inference: one ``eval_shape`` trace
  over a topo walk (fast path), with a per-node fallback that isolates the
  failing node when the single trace dies.
* :func:`abstract_infer_shape` — the ``Op.infer_shape`` fallback: derive
  one node's output shape from input *shapes only* (dtypes are guessed,
  float32 first), so legacy shape consumers (ONNX export, planners) see
  real shapes for every op instead of ``None`` holes.
"""
from __future__ import annotations

import numpy as np

from ..graph.node import LowerCtx, PlaceholderOp, topo_sort
from ..graph.gradients import GradientOp

#: pending reason marker for nodes downstream of a shapeless feed — these
#: are "unknown until run time", not errors (the run-time feed check in the
#: executor covers them); FAILED nodes raised during abstract lowering.
PENDING, FAILED = "pending", "failed"


def _shape_of(struct):
    """Pytree of structs -> pytree of plain shape tuples."""
    if struct is None:
        return None
    if isinstance(struct, (tuple, list)):
        return tuple(_shape_of(s) for s in struct)
    return tuple(struct.shape)


def _dtype_of(struct):
    if struct is None:
        return None
    if isinstance(struct, (tuple, list)):
        return tuple(_dtype_of(s) for s in struct)
    return np.dtype(struct.dtype)


def _as_struct(val, default_dtype=np.float32):
    """array | ShapeDtypeStruct | bare shape tuple -> ShapeDtypeStruct."""
    import jax
    if val is None:
        return None
    if isinstance(val, jax.ShapeDtypeStruct):
        return val
    if hasattr(val, "shape") and hasattr(val, "dtype"):
        return jax.ShapeDtypeStruct(tuple(val.shape), np.dtype(val.dtype))
    if isinstance(val, (tuple, list)):
        if len(val) and isinstance(val[0], (tuple, list)):
            return tuple(_as_struct(v, default_dtype) for v in val)
        return jax.ShapeDtypeStruct(tuple(int(d) for d in val),
                                    np.dtype(default_dtype))
    if np.isscalar(val):
        return jax.ShapeDtypeStruct((), np.asarray(val).dtype)
    raise TypeError(f"cannot derive a ShapeDtypeStruct from {type(val)}")


class GraphShapes:
    """Static ``(shape, dtype)`` assignment for one fetch subgraph.

    ``structs``: node -> ShapeDtypeStruct (or a tuple of them for
    multi-output ops).  ``pending``: node -> reason, for nodes whose shape
    depends on a feed with no static shape (resolved at run time, not an
    error).  ``failed``: node -> reason, for nodes whose abstract lowering
    raised — a real graph bug, surfaced by the ``uninferable`` lint rule.
    ``markers``: side-effect nodes (optimizer updates) that produce no
    tensor value.
    """

    def __init__(self, topo):
        self.topo = topo
        self.structs = {}
        self.pending = {}
        self.failed = {}
        self.markers = []

    @property
    def complete(self):
        """Every value-producing node has a static (shape, dtype)."""
        return not self.pending and not self.failed

    def struct(self, node):
        return self.structs.get(node)

    def shape(self, node):
        return _shape_of(self.structs.get(node))

    def dtype(self, node):
        return _dtype_of(self.structs.get(node))


def _normalize_feeds(feeds, topo):
    """{node-or-name: array/shape/struct} -> {PlaceholderOp: struct}."""
    out = {}
    if not feeds:
        return out
    by_name = {}
    for n in topo:
        if isinstance(n, PlaceholderOp):
            by_name.setdefault(n.name, n)
    for k, v in feeds.items():
        node = by_name.get(k) if isinstance(k, str) else k
        if node is None:
            continue
        dt = getattr(node, "dtype", None) or np.float32
        out[node] = _as_struct(v, default_dtype=dt)
    return out


def _ps_struct(node, feeds, structs):
    """PS-embedding leaf: rows for the ids batch -> ids.shape + (width,)."""
    import jax
    idn = node.ids_node
    ids = structs.get(idn) or feeds.get(idn)
    if ids is None:
        ids = _leaf_struct(idn, feeds) \
            if isinstance(idn, PlaceholderOp) else None
    if ids is None:
        return None
    width = node.width
    if width is None and hasattr(node.store, "width"):
        width = int(node.store.width(node.table))
    if width is None:
        return None
    return jax.ShapeDtypeStruct(tuple(ids.shape) + (int(width),),
                                np.float32)


def _leaf_struct(node, feeds):
    """Struct for a placeholder/variable leaf, or None when unknowable."""
    import jax
    if node in feeds:
        st = feeds[node]
        # feeds dominate for FED placeholders; a declared-shape mismatch
        # is the feed-mismatch rule's job, not silent adoption
        if not node.is_variable:
            return st
    shape = node.shape
    if shape is None and hasattr(node, "shape_from"):
        ref = node.shape_from
        shape = getattr(ref, "shape", None)
    if shape is None:
        return None
    dt = node.dtype or np.float32
    if np.dtype(dt) == np.float64:  # executor downcasts f64 feeds/params
        dt = np.float32
    return jax.ShapeDtypeStruct(tuple(int(d) for d in shape), np.dtype(dt))


def _node_eval(node, in_structs, mesh=None, training=True,
               num_microbatches=None, pipeline=None):
    """eval_shape one node's lowering over input structs."""
    import jax
    from ..metrics import suppress_perf_counters

    def f(*xs):
        ctx = LowerCtx(training, jax.random.key(0), mesh,
                       num_microbatches=num_microbatches, pipeline=pipeline)
        return node.lower(ctx, *xs)

    with suppress_perf_counters():
        return jax.eval_shape(f, *in_structs)


def infer_graph(fetches, feeds=None, mesh=None, training=True,
                num_microbatches=None, pipeline=None):
    """Assign a static ``(shape, dtype)`` to every node of the fetch
    subgraph without executing it.

    ``feeds``: optional {placeholder-node-or-name: array | shape | struct}
    supplying shapes for placeholders declared without one.  ``mesh`` /
    ``num_microbatches`` / ``pipeline``: the executor's configuration,
    threaded into lowering contexts so schedule-sensitive ops
    (PipelineBlock, collectives) abstract-evaluate the SAME path they
    would compile — a different microbatch count could otherwise fail the
    abstract trace on a graph that compiles fine.
    """
    from ..optim.optimizer import OptimizerOp

    if isinstance(fetches, dict):
        fetches = [n for fl in fetches.values() for n in fl]
    elif not isinstance(fetches, (list, tuple)):
        fetches = [fetches]
    topo = topo_sort([f for f in fetches if f is not None])
    gs = GraphShapes(topo)
    feeds = _normalize_feeds(feeds, topo)

    compute = []
    for node in topo:
        if isinstance(node, OptimizerOp):
            gs.markers.append(node)
        elif isinstance(node, GradientOp):
            continue  # resolved after its wrt leaf below
        elif isinstance(node, PlaceholderOp):
            try:
                st = _ps_struct(node, feeds, gs.structs) \
                    if getattr(node, "is_ps", False) \
                    else _leaf_struct(node, feeds)
            except Exception as e:  # corrupt store/feed metadata
                gs.failed[node] = f"{type(e).__name__}: {e}"
                continue
            if st is None:
                gs.pending[node] = (
                    "no static shape: declare shape= or pass a feed "
                    "example to ht.lint(feeds=...)")
            else:
                gs.structs[node] = st
        else:
            compute.append(node)

    # GradientOp mirrors its wrt leaf; do a fixpoint-free single pass
    # (wrt is always a leaf, resolved above)
    for node in topo:
        if isinstance(node, GradientOp):
            st = gs.structs.get(node.wrt)
            if st is not None:
                gs.structs[node] = st
            else:
                gs.pending[node] = f"wrt {node.wrt.name} has no static shape"

    # collect the computable set in topo order, propagating pending-ness
    runnable = []
    have = set(gs.structs)
    for node in compute:
        bad = next((i for i in node.inputs if i not in have), None)
        if bad is None:
            runnable.append(node)
            have.add(node)
        elif bad in gs.failed:
            gs.pending[node] = f"input '{bad.name}' failed abstract eval"
        else:
            gs.pending[node] = f"input '{bad.name}' has no static shape"

    if runnable:
        # fast path: ONE eval_shape trace over the whole runnable set
        import jax
        from ..metrics import suppress_perf_counters
        run_set = set(runnable)
        leaf_nodes = [n for n in topo if n in gs.structs
                      and n not in run_set]

        def fwd(leaf_vals):
            ctx = LowerCtx(training, jax.random.key(0), mesh,
                           num_microbatches=num_microbatches,
                           pipeline=pipeline)
            env = dict(zip(leaf_nodes, leaf_vals))
            outs = {}
            for node in runnable:
                env[node] = node.lower(ctx, *[env[i] for i in node.inputs])
                outs[str(node.id)] = env[node]
            return outs

        try:
            with suppress_perf_counters():
                out = jax.eval_shape(fwd, [gs.structs[n]
                                           for n in leaf_nodes])
            for node in runnable:
                gs.structs[node] = out[str(node.id)]
        except Exception:
            # isolate the failing node(s): per-node abstract evaluation,
            # downstream nodes of a failure flip to pending
            for node in runnable:
                bad = next((i for i in node.inputs
                            if i not in gs.structs), None)
                if bad is not None:
                    gs.pending[node] = \
                        f"input '{bad.name}' could not be inferred"
                    continue
                try:
                    gs.structs[node] = _node_eval(
                        node, [gs.structs[i] for i in node.inputs],
                        mesh, training, num_microbatches, pipeline)
                except Exception as e:
                    gs.failed[node] = f"{type(e).__name__}: {e}"
    return gs


def _nested(shape):
    return bool(shape) and isinstance(shape[0], (tuple, list))


def _structs_for(input_shapes, dtypes):
    import jax
    out = []
    for s, dt in zip(input_shapes, dtypes):
        if _nested(s):
            out.append(tuple(jax.ShapeDtypeStruct(tuple(x), np.float32)
                             for x in s))
        else:
            out.append(jax.ShapeDtypeStruct(tuple(int(d) for d in s),
                                            np.dtype(dt)))
    return out


def abstract_infer_shape(node, input_shapes, mesh=None):
    """Best-effort static output shape for ONE node from input shapes only.

    This is the ``Op.infer_shape`` fallback.  Input dtypes are unknown at
    this API (the legacy rule signature carries shapes only), so a small
    ladder of guesses is tried: all-float32, then one-int32 flips (index
    operands: embedding ids, gather indices), then all-int32.  Returns a
    shape tuple (or tuple of shape tuples for multi-output ops), or
    ``None`` when the inputs are unknown / the rule needs runtime context.
    """
    if input_shapes is None:
        input_shapes = []
    input_shapes = list(input_shapes)
    if any(s is None for s in input_shapes):
        return None
    key = tuple(tuple(s) if not _nested(s) else tuple(map(tuple, s))
                for s in input_shapes)
    cache = node.__dict__.setdefault("_abs_shape_cache", {})
    if key in cache:
        return cache[key]
    n = len(input_shapes)
    combos = [[np.float32] * n]
    for i in range(n):
        flip = [np.float32] * n
        flip[i] = np.int32
        combos.append(flip)
    if n > 1:
        combos.append([np.int32] * n)
    result = None
    for dts in combos:
        try:
            out = _node_eval(node, _structs_for(input_shapes, dts),
                             mesh, training=False)
        except Exception:
            continue
        result = _shape_of(out)
        break
    cache[key] = result
    return result
