"""hetu_tpu.autoparallel — Galvatron-parity hybrid-parallel strategy search.

Workflow (reference ``tools/Galvatron/README.md:15-100``):

1. **profile** — measure device flops + collective bandwidths
   (:class:`hetu_tpu.profiler.CollectiveProfiler`) or supply a
   :class:`HardwareSpec`;
2. **search** — :func:`search` runs the layerwise DP algorithm
   (:class:`DPAlg`) over (pp, tp, dp, fsdp) candidates under the memory
   budget;
3. **train** — :meth:`ParallelPlan.strategy` + :meth:`ParallelPlan.apply`
   hand the result to the executor as a mesh + GSPMD sharding annotations.
"""
from .cost_model import (HardwareSpec, LayerSpec, MemoryCostModel, Strategy,
                         TimeCostModel, transformer_layer_spec,
                         attention_layer_spec, mlp_layer_spec,
                         embedding_layer_spec, model_layer_specs,
                         swin_layer_specs, graph_layer_spec,
                         graph_layer_specs, bert_split)
from .search import DPAlg, candidate_strategies, search, search_graph
from .plan import ParallelPlan
from .measure import (PlanMeasurement, measure_plan, measure_plans,
                      plan_diff, format_plan_diff)


def calibrate_hardware(mesh=None, mem_bytes=None,
                       matmul_dim=4096, chain=64,
                       probe_bytes=1 << 22, **overrides):
    """Measure a HardwareSpec from the live devices (profile step of the
    Galvatron workflow): matmul-probe flops + collective bandwidth."""
    import time

    import jax
    import jax.numpy as jnp

    from ..profiler import CollectiveProfiler

    n = matmul_dim

    def probe(a, length):
        # data-dependent matmul chain returning a SCALAR: remote platforms
        # (axon tunnel) don't honor block_until_ready, and reading a full
        # result array back is transfer-dominated — a 4-byte scalar read
        # is the only reliable sync
        def body(y, _):
            return y @ a, None
        y, _ = jax.lax.scan(body, a, None, length=length)
        return jnp.float32(jnp.sum(y))

    if chain < 2:
        raise ValueError("calibrate_hardware needs chain >= 2 (the probe "
                         "subtracts a 1-matmul latency baseline)")
    x = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16) * 0.01
    f = jax.jit(probe, static_argnums=1)
    float(f(x, chain))  # warm both lengths
    float(f(x, 1))
    reps = 3

    def timed(length):
        # best-of-reps suppresses scheduler noise (a single noisy sample
        # can otherwise make dt < lat and nonsense flops)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(f(x, length))
            best = min(best, time.perf_counter() - t0)
        return best

    lat = timed(1)
    dt = timed(chain)
    per_matmul = (dt - lat) / (chain - 1)
    if per_matmul <= 0:  # noise floor: fall back to the un-baselined rate
        per_matmul = dt / chain
    flops = 2 * n ** 3 / per_matmul
    prof = CollectiveProfiler(mesh=mesh, repeats=3)
    width = prof.mesh.shape[prof.axis]
    if width > 1:
        ar = prof.profile_allreduce(probe_bytes)
        ici_bw = (probe_bytes * 2 * (width - 1) / width / ar) if ar > 0 \
            else HardwareSpec.ici_bw
        overlap = measure_overlap(prof.mesh, prof.axis, probe_bytes,
                                  matmul_dim=min(matmul_dim, 1024))
    else:  # bandwidth unmeasurable on a 1-wide axis; keep the defaults
        ici_bw = HardwareSpec.ici_bw
        overlap = HardwareSpec.overlap
    dev = jax.local_devices()[0]
    if mem_bytes is None:
        stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
        mem_bytes = (stats or {}).get("bytes_limit", 16e9)
    kw = dict(flops=flops, mem_bytes=float(mem_bytes),
              ici_bw=float(ici_bw), overlap=float(overlap))
    kw.update(overrides)
    return HardwareSpec(**kw)


def measure_overlap(mesh, axis, probe_bytes=1 << 22, matmul_dim=1024,
                    repeats=3):
    """Measured compute/communication overlap coefficient ∈ [0, 1]
    (Galvatron profiles this as overlap_coe, ``utils/cost_model.py:38``;
    the round-2 spec used a guessed constant).

    Times three jitted shard_map programs — compute-only (matmul chain),
    comm-only (psum), and both with independent dataflow so XLA may
    schedule them concurrently — and reports what fraction of the shorter
    phase was hidden: ``(t_comp + t_comm - t_both) / min(t_comp, t_comm)``.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    elems = max(128, probe_bytes // 4)
    buf = jax.device_put(jnp.zeros((n, elems), jnp.float32),
                         NamedSharding(mesh, P(axis, None)))
    a = jax.device_put(
        jnp.full((n, matmul_dim, matmul_dim), 1e-3, jnp.bfloat16),
        NamedSharding(mesh, P(axis, None, None)))

    def compute(v):                       # per-device matmul chain
        y = v
        for _ in range(4):
            y = y @ v
        return jnp.sum(y, dtype=jnp.float32).reshape(1)

    def comm(b):
        return jnp.sum(jax.lax.psum(b, axis)[:1],
                       dtype=jnp.float32).reshape(1)

    f_comp = jax.jit(jax.shard_map(
        lambda v, b: compute(v), mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None)), out_specs=P(axis)))
    f_comm = jax.jit(jax.shard_map(
        lambda v, b: comm(b), mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None)), out_specs=P(axis)))
    f_both = jax.jit(jax.shard_map(
        lambda v, b: compute(v) + comm(b), mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None)), out_specs=P(axis)))

    def sync(out):
        # remote platforms (axon tunnel) do not honor block_until_ready —
        # a host read is the only reliable sync (same discipline as the
        # flops probe above)
        return float(np.asarray(out).ravel()[0])

    def timed(f):
        sync(f(a, buf))
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            sync(f(a, buf))
            best = min(best, time.perf_counter() - t0)
        return best

    t_comp, t_comm, t_both = timed(f_comp), timed(f_comm), timed(f_both)
    hidden = t_comp + t_comm - t_both
    denom = min(t_comp, t_comm)
    if denom <= 0:
        return HardwareSpec.overlap
    return float(np.clip(hidden / denom, 0.0, 1.0))


def long_context_cp_plan(n_devices, mem_bytes=2.5e9, hw=None, layers=4,
                         hidden=512, seq=262144):
    """The canonical long-context cp search: batch 1 caps dp, so only
    sequence sharding can spread one sequence's activations — the regime
    the cp axis exists for (shared by the dryrun config D and
    examples/autoparallel/search_and_train.py --long-context so the two
    demonstrations cannot drift)."""
    from .cost_model import HardwareSpec, attention_layer_spec
    from .search import search
    if hw is None:
        hw = HardwareSpec(mem_bytes=mem_bytes)
    spec = attention_layer_spec(hidden=hidden, seq=seq, batch=1,
                                count=layers)
    plan = search([spec], n_devices=n_devices, hw=hw, allow_pp=False,
                  max_tp=1, max_dp=1, allow_cp=True)
    axes = plan.mesh_axes()
    axes.setdefault("dp", 1)
    return plan, axes


__all__ = ["HardwareSpec", "LayerSpec", "MemoryCostModel", "TimeCostModel",
           "long_context_cp_plan", "Strategy", "transformer_layer_spec", "attention_layer_spec",
           "mlp_layer_spec", "embedding_layer_spec", "model_layer_specs",
           "swin_layer_specs", "graph_layer_spec", "graph_layer_specs",
           "bert_split", "DPAlg", "candidate_strategies", "search", "search_graph",
           "ParallelPlan", "PlanMeasurement", "measure_plan",
           "measure_plans", "plan_diff", "format_plan_diff",
           "calibrate_hardware", "measure_overlap"]
