"""Memory and time cost models for hybrid-parallel strategy search.

Capability parity with Galvatron (reference ``tools/Galvatron/utils/
cost_model.py:3`` MemoryCostModel, ``:38`` TimeCostModel_with_overlap),
re-targeted at TPU meshes: a *strategy* is ``(pp, tp, dp, fsdp)`` — pipeline
stages, tensor-parallel width, data-parallel width, and whether optimizer
state + params are fully sharded over dp (ZeRO-3 semantics, which is how the
"PS/fsdp" capability maps to synchronous TPU training).

All byte counts are per-device; bandwidths come from a measured
:class:`hetu_tpu.profiler.CollectiveProfiler` table or caller-supplied
constants.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Strategy:
    """One per-layer parallelization choice.

    ``cp`` (net-new vs Galvatron, whose dims are pp/tp/dp/fsdp only —
    ``utils/cost_model.py:13-16``): context/sequence parallelism over the
    'cp' mesh axis — tokens shard over cp everywhere, attention runs the
    ring schedule (``parallel/ring_attention.py``).  Params replicate over
    cp, so gradient sync spans dp x cp."""
    pp: int = 1
    tp: int = 1
    dp: int = 1
    fsdp: bool = False
    cp: int = 1

    @property
    def world(self):
        return self.pp * self.tp * self.dp * self.cp

    def __str__(self):
        tag = f"pp{self.pp}-tp{self.tp}-dp{self.dp}"
        if self.cp > 1:
            tag += f"-cp{self.cp}"
        return tag + ("-fsdp" if self.fsdp else "")


@dataclass
class LayerSpec:
    """Static per-layer workload description (Galvatron profiles these;
    we derive them from model config or HLO cost analysis).

    * ``param_bytes`` — parameter bytes of one layer replica
    * ``fwd_flops`` — forward FLOPs for the whole (global) batch
    * ``act_bytes`` — activation bytes for the whole batch (what pipeline
      p2p moves, and what remat trades)
    * ``count`` — how many identical layers share this spec
    * ``attn`` — contains self-attention: under cp the layer pays the ring
      K/V rotation (token-parallel layers without attention do not)
    """
    name: str
    param_bytes: float
    fwd_flops: float
    act_bytes: float
    count: int = 1
    attn: bool = False
    #: K+V bytes for the whole batch (what the cp ring actually rotates);
    #: act_bytes carries a ~6-12x liveset multiplier and must not be used
    #: for ring volume.  0 → approximated as act_bytes / 3.
    kv_bytes: float = 0.0


@dataclass
class HardwareSpec:
    """Device + interconnect model.

    ``flops``: sustained per-device FLOP/s (not peak — calibrate with a
    matmul probe). Bandwidths in bytes/s. ``overlap`` ∈ [0,1]: fraction of
    dp grad-allreduce hidden behind backward compute (Galvatron's
    overlap_coe).
    """
    flops: float = 100e12          # ~bf16 sustained on one v5e core
    mem_bytes: float = 16e9
    ici_bw: float = 4.5e10         # allreduce algo-bandwidth over ICI
    dcn_bw: float = 2.5e9
    overlap: float = 0.7

    def coll_bw(self, width):
        """Bandwidth for a collective of given participant count; >8-wide
        groups are assumed to cross DCN (multi-host)."""
        return self.ici_bw if width <= 8 else self.dcn_bw

    @classmethod
    def from_artifact(cls, path=None, **overrides):
        """The committed on-chip calibration (tools/calibrate_tpu.py →
        ``artifacts/tpu_calibration.json``), or None when absent/invalid —
        so searches are grounded in MEASURED hardware even when the TPU
        tunnel is unreachable at search time."""
        import json
        import os
        if path is None:
            path = os.path.join(os.path.dirname(__file__), os.pardir,
                                os.pardir, "artifacts",
                                "tpu_calibration.json")
        import dataclasses
        try:
            with open(path) as f:
                data = json.load(f)
            kw = dict(data["spec"])
        except (OSError, KeyError, ValueError, TypeError):
            return None
        kw.update(overrides)
        fields = {f.name for f in dataclasses.fields(cls)}
        try:   # tolerate unknown/extra keys — invalid artifact means None
            return cls(**{k: v for k, v in kw.items() if k in fields})
        except (TypeError, ValueError):
            return None

    @classmethod
    def measure(cls, mesh=None, probe_bytes=1 << 22, matmul_dim=1024,
                **overrides):
        """Calibrated spec from THIS machine — delegates to
        :func:`hetu_tpu.autoparallel.calibrate_hardware` (the profile step
        of the Galvatron workflow) with test-friendly probe sizes."""
        from . import calibrate_hardware
        return calibrate_hardware(mesh=mesh, matmul_dim=matmul_dim,
                                  chain=8, probe_bytes=probe_bytes,
                                  **overrides)


OPT_STATE_MULT = 3.0   # param + adam m + v, fp32 master (bytes ×3 of fp32)
GRAD_MULT = 1.0


class MemoryCostModel:
    """Per-device memory of running one layer under a strategy
    (Galvatron MemoryCostModel: model states ×1/dp under fsdp:18-23).

    ``remat`` here is the SEARCH-level boolean knob (does the strategy
    assume activation recompute at all); the executor-side realization
    is the graded policy ladder in ``parallel/remat.py``, whose planner
    prices real graphs with this module's :func:`matmul_flops` /
    :data:`MATMUL_OPS` tables — one FLOP model for both."""

    def __init__(self, hw: HardwareSpec, microbatches: int = 1,
                 remat: bool = False):
        self.hw = hw
        self.microbatches = max(1, microbatches)
        self.remat = remat

    def layer_bytes(self, spec: LayerSpec, s: Strategy):
        shard = s.tp  # params shard over tp always
        params = spec.param_bytes / shard
        states = params * OPT_STATE_MULT
        grads = params * GRAD_MULT
        if s.fsdp:
            states /= s.dp
            params /= s.dp  # gathered transiently; steady-state sharded
            grads /= s.dp   # reduce-scattered
        acts = spec.act_bytes / (s.dp * s.tp * s.cp) / self.microbatches
        if self.remat:
            acts = acts / 4 + spec.act_bytes * 0.01  # boundary stashes
        return params + states + grads + acts

    def stage_bytes(self, specs, strategies):
        """Total per-device bytes when each layer i runs strategy[i] —
        layers divide over pp stages, so each stage holds 1/pp of them."""
        per_stage = {}
        for spec, s in zip(specs, strategies):
            b = self.layer_bytes(spec, s) * spec.count / s.pp
            per_stage[s.pp] = per_stage.get(s.pp, 0.0) + b
        return max(per_stage.values()) if per_stage else 0.0

    def fits(self, specs, strategies):
        return self.stage_bytes(specs, strategies) <= self.hw.mem_bytes


class TimeCostModel:
    """Per-layer step time under a strategy (Galvatron
    TimeCostModel_with_overlap:38): compute + tp collectives + un-overlapped
    dp gradient sync + pp bubble amortization."""

    def __init__(self, hw: HardwareSpec, microbatches: int = 1):
        self.hw = hw
        self.microbatches = max(1, microbatches)

    @classmethod
    def calibrated(cls, mesh=None, microbatches=1, **probe_kw):
        """Construct over THIS machine's measured constants: matmul-probe
        FLOP/s, allreduce bandwidth and the measured compute/comm overlap
        coefficient from :func:`~hetu_tpu.autoparallel.calibrate_hardware`
        — the profile leg of the Galvatron workflow wired directly into
        cost-model construction (previously callers had to plumb the
        measured spec by hand, so defaults were what actually priced
        searches)."""
        spec = HardwareSpec.measure(mesh=mesh, **probe_kw)
        return cls(spec, microbatches=microbatches)

    def layer_time(self, spec: LayerSpec, s: Strategy):
        hw = self.hw
        # fwd+bwd ≈ 3× fwd flops, spread over tp*dp*cp devices (batch over
        # dp, matmul width over tp, tokens over cp)
        compute = 3.0 * spec.fwd_flops / (s.tp * s.dp * s.cp) / hw.flops
        # TP: 2 allreduces fwd + 2 bwd per transformer layer over the
        # activation bytes (Megatron pattern), ring cost ×2(n-1)/n
        tp_comm = 0.0
        if s.tp > 1:
            vol = 4.0 * spec.act_bytes / (s.dp * s.tp * s.cp)
            tp_comm = vol * 2 * (s.tp - 1) / s.tp / hw.coll_bw(s.tp)
        # CP: the ring rotates each rank's local K+V chunk (cp-1) times;
        # the schedule overlaps permute with blockwise compute, so only
        # the un-overlapped fraction is charged.  Token-parallel layers
        # without attention pay nothing.
        cp_comm = 0.0
        if s.cp > 1 and spec.attn:
            kv_total = spec.kv_bytes or (spec.act_bytes / 3.0)
            kv = kv_total / (s.dp * s.tp * s.cp)
            cp_comm = kv * (s.cp - 1) / hw.coll_bw(s.cp) \
                * (1.0 - hw.overlap)
        # DP: grad allreduce (or reduce-scatter+all-gather for fsdp — same
        # ring volume), partly overlapped with backward.  Params replicate
        # over cp, so the sync ring spans dp*cp participants.
        dp_comm = 0.0
        n_sync = s.dp * s.cp
        if n_sync > 1:
            vol = (spec.param_bytes / s.tp) * 2 * (n_sync - 1) / n_sync
            dp_comm = vol / hw.coll_bw(n_sync) * (1.0 - hw.overlap)
        if s.fsdp and s.dp > 1:
            # extra fwd all-gather of sharded params (not overlappable fully)
            vol = (spec.param_bytes / s.tp) * (s.dp - 1) / s.dp
            dp_comm += vol / hw.coll_bw(s.dp) * 0.5
        # PP: p2p activations between stages + bubble overhead factor
        pp_cost = 0.0
        if s.pp > 1:
            p2p = spec.act_bytes / (s.dp * s.tp * s.cp) / hw.coll_bw(2)
            bubble = (s.pp - 1) / self.microbatches
            pp_cost = p2p + compute * bubble
        return compute + tp_comm + cp_comm + dp_comm + pp_cost

    def total(self, specs, strategies):
        return sum(self.layer_time(sp, st) * sp.count
                   for sp, st in zip(specs, strategies))


def transformer_layer_spec(hidden, seq, batch, ffn_mult=4, dtype_bytes=2,
                           name="layer", count=1):
    """Derive a LayerSpec for one transformer block from model dims."""
    params = (4 * hidden * hidden + 2 * ffn_mult * hidden * hidden) \
        * dtype_bytes
    tokens = batch * seq
    flops = 2 * tokens * (4 * hidden * hidden + 2 * ffn_mult * hidden
                          * hidden) + 2 * 2 * batch * seq * seq * hidden
    acts = tokens * hidden * dtype_bytes * 12  # rough per-block liveset
    return LayerSpec(name, float(params), float(flops), float(acts), count,
                     attn=True, kv_bytes=float(2 * tokens * hidden
                                               * dtype_bytes))


# -- per-type specs (Galvatron multi-layer-type DP, dp_utils.py:259) --------

def attention_layer_spec(hidden, seq, batch, dtype_bytes=2, name="attn",
                         count=1):
    """Self-attention sublayer: 4 h×h projections + the s² score term."""
    tokens = batch * seq
    params = 4 * hidden * hidden * dtype_bytes
    flops = 2 * tokens * 4 * hidden * hidden \
        + 2 * 2 * batch * seq * seq * hidden
    acts = tokens * hidden * dtype_bytes * 6
    return LayerSpec(name, float(params), float(flops), float(acts), count,
                     attn=True, kv_bytes=float(2 * tokens * hidden
                                               * dtype_bytes))


def mlp_layer_spec(hidden, seq, batch, ffn_mult=4, dtype_bytes=2,
                   name="mlp", count=1):
    """FFN sublayer: up/down projections."""
    tokens = batch * seq
    params = 2 * ffn_mult * hidden * hidden * dtype_bytes
    flops = 2 * tokens * 2 * ffn_mult * hidden * hidden
    acts = tokens * hidden * dtype_bytes * (2 + ffn_mult)
    return LayerSpec(name, float(params), float(flops), float(acts), count)


def embedding_layer_spec(vocab, hidden, seq, batch, dtype_bytes=2,
                         name="embed", tied_head=True, count=1):
    """Token embedding (+ tied LM head): parameter-dominated, nearly
    FLOP-free on lookup; the head matmul carries the vocab FLOPs."""
    tokens = batch * seq
    params = vocab * hidden * dtype_bytes
    flops = (2 * tokens * vocab * hidden) if tied_head else tokens * hidden
    acts = tokens * max(hidden, vocab if tied_head else hidden) \
        * dtype_bytes
    return LayerSpec(name, float(params), float(flops), float(acts), count)


def model_layer_specs(n_layers, hidden, seq, batch, vocab, ffn_mult=4,
                      dtype_bytes=2):
    """Interleaved multi-type chain for the joint DP search: embedding,
    then (attention, mlp) per block — the reference searches these types
    JOINTLY rather than one uniform per-block spec
    (``tools/Galvatron/utils/dp_utils.py:259`` multi-layer-type)."""
    specs = [embedding_layer_spec(vocab, hidden, seq, batch, dtype_bytes)]
    for i in range(n_layers):
        specs.append(attention_layer_spec(hidden, seq, batch, dtype_bytes,
                                          name=f"attn{i}"))
        specs.append(mlp_layer_spec(hidden, seq, batch, ffn_mult,
                                    dtype_bytes, name=f"mlp{i}"))
    return specs


def swin_layer_specs(image_size, patch_size, embed_dim, depths, num_heads,
                     window_size, batch, mlp_ratio=4, dtype_bytes=2):
    """Hierarchical swin chain for the multi-layer-type DP search — the
    reference's fourth Galvatron runtime family (``tools/Galvatron/swin/``
    profiles these same per-layer costs from torch; here they derive from
    the geometry of ``models/swin.py``).

    Swin's cost structure differs from the uniform-transformer chain in
    two ways the search must see: (1) attention is WINDOWED — the s² score
    term runs at seq=w² over batch·nW windows, so it stays cheap while the
    projection/MLP cost tracks the full token count; (2) the stage ladder
    halves tokens and doubles width at each patch-merge, so early stages
    are activation-heavy (pipeline-split-expensive) while late stages are
    parameter-heavy (fsdp/tp-friendly).
    """
    import dataclasses
    del num_heads  # head count does not change FLOPs/bytes at this level
    assert image_size % patch_size == 0
    specs = []
    res = image_size // patch_size
    in_dim = 3 * patch_size * patch_size
    specs.append(LayerSpec(
        "patch_embed", float(in_dim * embed_dim * dtype_bytes),
        float(2 * batch * res * res * in_dim * embed_dim),
        float(batch * res * res * embed_dim * dtype_bytes * 2)))
    dim = embed_dim
    for si, depth in enumerate(depths):
        w = min(window_size, res)
        # mirror the model's build-time geometry contract
        # (models/swin.py SwinConfig): silently floor-dividing here would
        # price a model that cannot be built
        assert res % w == 0, (
            f"stage {si}: resolution {res} not divisible by window {w}")
        tokens = batch * res * res            # == (batch·nW) · w²
        for bi in range(depth):
            spec = attention_layer_spec(
                hidden=dim, seq=w * w, batch=tokens // (w * w),
                dtype_bytes=dtype_bytes, name=f"s{si}.attn{bi}")
            shifted = bi % 2 == 1 and w < res  # models/swin.py shift rule
            if not shifted:
                # unshifted windows are mutually independent: a cp shard
                # aligned to window boundaries exchanges NO K/V, so the
                # ring charge (TimeCostModel attn path) must not apply
                spec = dataclasses.replace(spec, attn=False, kv_bytes=0.0)
            else:
                # SHIFTED windows straddle any window-aligned shard cut:
                # each shard swaps a w/2-row halo strip (both H and W
                # rolls) with ONE neighbour.  Keep attn=True with
                # kv_bytes = the halo volume; the ring formula's (cp-1)
                # multiplier overcounts a single-neighbour exchange, so
                # this prices cp PESSIMISTICALLY on shifted blocks —
                # the safe direction for an un-modeled halo schedule.
                halo = 2 * batch * res * (w // 2) * dim * dtype_bytes
                spec = dataclasses.replace(spec, kv_bytes=float(2 * halo))
            specs.append(spec)
            specs.append(mlp_layer_spec(
                hidden=dim, seq=res * res, batch=batch,
                ffn_mult=mlp_ratio, dtype_bytes=dtype_bytes,
                name=f"s{si}.mlp{bi}"))
        if si + 1 < len(depths):
            assert res % 2 == 0, f"stage {si}: odd resolution {res}"
            merged = tokens // 4
            specs.append(LayerSpec(
                f"s{si}.merge", float(4 * dim * 2 * dim * dtype_bytes),
                float(2 * merged * 4 * dim * 2 * dim),
                float(merged * 4 * dim * dtype_bytes)))
            res //= 2
            dim *= 2
    return specs


#: matmul-family op -> index of the LEFT matrix operand (Addmm/Baddbmm
#: carry the additive input first).  Public surface: the selective-remat
#: planner (``parallel/remat.py``) prices per-SEGMENT recompute FLOPs
#: with exactly this table + :func:`matmul_flops`, so the remat plan and
#: the strategy search can never disagree about what a matmul costs.
MATMUL_OPS = {"MatrixMult": 0, "Linear": 0, "BatchMatrixMult": 0,
              "Addmm": 1, "Baddbmm": 1}
_MATMUL_OPS = MATMUL_OPS          # original (private) alias, kept
_ATTN_OPS = ("ScaledDotProductAttention", "RingAttention",
             "UlyssesAttention")


def matmul_flops(node, gs, out_shape):
    """2·(output elements)·(contracted size) for one matmul-family node,
    or None when shapes are unknown."""
    import numpy as np
    t = node.op_type
    if t == "Einsum":
        eq = node.attrs.get("subscripts", "")
        if "->" not in eq:
            return None
        lhs, out = eq.split("->")
        terms = lhs.split(",")
        shapes = [gs.shape(i) for i in node.inputs]
        sizes = {}
        for term, shp in zip(terms, shapes):
            if shp is None or len(term) != len(shp):
                return None
            sizes.update(zip(term, shp))
        contracted = [sizes[lab] for lab in set("".join(terms)) - set(out)]
        if not contracted:
            return None
        return 2.0 * float(np.prod(out_shape)) * float(np.prod(contracted))
    a_idx = _MATMUL_OPS[t]
    if a_idx >= len(node.inputs):
        return None
    a = gs.shape(node.inputs[a_idx])
    if not a:
        return None
    k = a[-2] if node.attrs.get("trans_A", False) else a[-1]
    return 2.0 * float(np.prod(out_shape)) * float(k)


_matmul_flops = matmul_flops      # original (private) alias, kept


#: groups "<prefix>.layer<N>.<rest>" node names into one bucket per layer
#: (the ``models/`` naming convention: bert.layer3.ffn1, gpt2.layer0.attn)
_LAYER_NAME_RE = None   # compiled lazily (re import stays function-local)


def _default_split(node_name):
    """Bucket key for :func:`graph_layer_specs`' default segmentation, or
    None to stay in the current bucket."""
    global _LAYER_NAME_RE
    if _LAYER_NAME_RE is None:
        import re
        _LAYER_NAME_RE = re.compile(r"^(.*?\.layer\d+)(?:\.|$)")
    m = _LAYER_NAME_RE.match(node_name or "")
    return m.group(1) if m else None


def bert_split(node_name):
    """:func:`graph_layer_specs` ``split`` for bert-style graphs: the
    ``<prefix>.layer<N>`` anchors plus explicit stem/head routing —
    the default split alone merges the trailing MLM head (and pooler)
    into the LAST encoder layer and the embeddings into the stem."""
    if not node_name:
        return None
    if ".embeddings" in node_name:
        return "embeddings"
    if ".mlm_" in node_name or ".pooler" in node_name:
        return "head"
    return _default_split(node_name)


def graph_layer_specs(fetches, feeds=None, split=None, name="graph",
                      dtype_bytes=4):
    """Per-layer :class:`LayerSpec` chain from a REAL fetch subgraph —
    the end-to-end pricing path (callers previously hand-assembled layer
    lists from model dims; this walks the graph that will actually
    compile).

    Uses the static shape assignment from
    :func:`hetu_tpu.analysis.infer_graph` (every node's ``(shape, dtype)``
    with zero FLOPs — no ``None`` holes).  Per bucket:

    * ``param_bytes`` — sum over trainable variable leaves,
    * ``fwd_flops`` — 2·M·N·K over every matmul-family node (attention
      score/value contractions counted from q/k shapes),
    * ``act_bytes`` — sum of output bytes over compute nodes (the
      activation liveset upper bound that remat/pipeline p2p trade in).

    ``split``: callable ``node_name -> bucket key | None`` (None = no
    opinion).  The default groups by the ``<prefix>.layer<N>`` naming
    convention the ``models/`` builders follow.  Auto-named compute
    nodes INHERIT the bucket of their inputs (a matmul consuming
    ``bert.layer0.ffn1.weight`` belongs to ``bert.layer0``; downstream
    elementwise ops follow their producers) — layer params are the
    naming anchors, so attribution tracks dataflow, not topo accidents.
    A node whose inputs span several buckets joins the latest-created
    one (a residual add of layer i-1's output and layer i's branch is
    layer i work); nodes with no named ancestor land in
    ``"<name>.stem"``.  Pass forward fetches (the loss), not the
    optimizer op — :class:`TimeCostModel` applies the fwd+bwd
    multiplier itself.

    Returns the buckets as LayerSpecs in first-seen topo order; a graph
    with no matching names collapses to one whole-graph spec (exactly
    :func:`graph_layer_spec`)."""
    import numpy as np
    from ..analysis.shapes import infer_graph
    from ..graph.node import PlaceholderOp

    if split is None:
        split = _default_split
    gs = infer_graph(fetches, feeds=feeds)
    stem = f"{name}.stem"
    order = []                   # bucket keys, first-seen topo order
    acc = {}                     # key -> [params, flops, acts, attn]
    node_bucket = {}             # node -> its bucket key

    def _acc_of(key):
        if key not in acc:
            order.append(key)
            acc[key] = [0.0, 0.0, 0.0, False]
        return acc[key]

    def _assign(node):
        key = split(getattr(node, "name", None))
        if key is None:
            # inherit from inputs: the latest-created NAMED bucket wins
            # (stem is the no-opinion bucket — a mask reshape feeding
            # every attention layer must not capture them)
            best = -1
            for inp in getattr(node, "inputs", ()) or ():
                k = node_bucket.get(inp)
                if k is not None and k != stem:
                    idx = order.index(k)
                    if idx > best:
                        best, key = idx, k
        if key is None:
            key = stem
        node_bucket[node] = key
        return key

    for node in gs.topo:
        st = gs.struct(node)
        if st is None or isinstance(st, (tuple, list)):
            continue
        nbytes = float(np.prod(st.shape)) * dtype_bytes if st.shape \
            else float(dtype_bytes)
        if isinstance(node, PlaceholderOp):
            if node.is_variable and getattr(node, "trainable", False):
                key = _assign(node)
                _acc_of(key)[0] += nbytes
            else:
                # non-variable placeholders (feeds) anchor nothing: let
                # compute inherit from params, not from input ids
                node_bucket[node] = None
            continue
        b = _acc_of(_assign(node))
        b[2] += nbytes
        if node.op_type in _MATMUL_OPS or node.op_type == "Einsum":
            f = _matmul_flops(node, gs, st.shape)
            if f:
                b[1] += f
        elif node.op_type.startswith(_ATTN_OPS) and len(node.inputs) >= 2:
            q = gs.shape(node.inputs[0])
            kv = gs.shape(node.inputs[1])
            if q and kv:
                b_h = float(np.prod(q[:-2]))
                s_q, d = float(q[-2]), float(q[-1])
                s_kv = float(kv[-2])
                b[3] = True
                b[1] += 2.0 * 2.0 * b_h * s_q * s_kv * d  # scores + values
    if not acc:
        return [LayerSpec(name, 0.0, 0.0, 0.0)]
    return [LayerSpec(k, *acc[k][:3], count=1, attn=acc[k][3])
            for k in order]


def graph_layer_spec(fetches, feeds=None, name="graph", dtype_bytes=4,
                     count=1):
    """One fused :class:`LayerSpec` for a REAL fetch subgraph — the
    single-bucket view of :func:`graph_layer_specs` (same walk, same
    numbers; ``obs.graph_flops`` and the remat planner read this)."""
    specs = graph_layer_specs(fetches, feeds=feeds,
                              split=lambda _n: None, name=name,
                              dtype_bytes=dtype_bytes)
    merged = specs[0]
    merged.name = name
    merged.count = count
    return merged


__all__ = ["Strategy", "LayerSpec", "HardwareSpec", "MemoryCostModel",
           "TimeCostModel", "transformer_layer_spec",
           "attention_layer_spec", "mlp_layer_spec",
           "embedding_layer_spec", "model_layer_specs",
           "swin_layer_specs", "graph_layer_spec", "graph_layer_specs",
           "bert_split", "MATMUL_OPS", "matmul_flops"]
