"""Measurement feedback for searched plans — the loop-closing leg.

The search (``search.py``) predicts; this module RUNS the top-k candidate
plans for a few steps each and feeds the measurements back:

* :func:`measure_plans` — one ``Executor(plan=candidate)`` per candidate
  through the process-wide compiled-step cache (one compile per distinct
  candidate, reused thereafter — re-measuring a plan hits the cache,
  counted as ``autoparallel_candidate_cache_hits``), per-step wall times
  forced honest by a scalar host read (the only reliable sync — the
  calibration probes' discipline), published into the PR 10 registry as
  per-plan ``step_time_us`` histogram observations and per-plan MFU
  gauges;
* :func:`plan_diff` — per-layer predicted-vs-measured cost table for one
  measured plan (the cost model's end-to-end error, attributed per layer);
* :meth:`ParallelPlan.rerank <hetu_tpu.autoparallel.ParallelPlan.rerank>`
  consumes the measurement list and re-orders candidates by measured step
  time, so a mispriced cost model cannot pin the deployment to a slow
  plan.

The per-plan step time is the MIN over this run's measured steps (PR 9
convention: shared-host contention only ever inflates a step, so min is
the least-noise estimator).  The same per-step observations are
published to the registry histogram under ``label:plan.tag()`` — what
``metrics_dump()``/Prometheus expose — but the measurement itself never
reads back through the process-wide registry, so an earlier run under
the same tag (a different build, different feeds) cannot masquerade as
this one's min.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class PlanMeasurement:
    """One candidate plan's measured run."""
    plan: object
    label: str
    #: histogram-min step wall time, microseconds (PR 9 discipline)
    step_time_us: float
    #: every measured step's wall, microseconds (the distribution behind
    #: the min)
    walls_us: list = field(default_factory=list)
    #: the search's predicted step time, microseconds (None when the plan
    #: was constructed by hand without an estimate)
    predicted_us: float = None
    #: model-FLOPs utilization gauge published for this plan (None when
    #: graph FLOPs could not be inferred)
    mfu: float = None
    #: True when this candidate's executable was built fresh (a step-cache
    #: miss); False = reused a previously compiled candidate
    compiled: bool = True

    @property
    def seconds(self):
        return self.step_time_us / 1e6


def _peak_flops():
    """Per-device peak FLOP/s for the MFU gauge — the shared
    ``obs.device_peak_flops`` table ``bench.py`` resolves through (one
    table, so a new device kind lands once).  Non-TPU backends get its
    nominal placeholder: MFU becomes a relative gauge there, still
    monotone in step time for one workload."""
    from ..obs import device_peak_flops
    return device_peak_flops()[0]


class _CandidateRun:
    """One candidate's live executor + measurement state."""

    def __init__(self, plan, build, label):
        from ..metrics import record_autoparallel, step_cache_counts
        self.plan = plan
        self.tag = f"{label}:{plan.tag()}"
        before = step_cache_counts()
        built = build(plan)
        self.ex, self.fd = built[0], built[1]
        self.name = built[2] if len(built) > 2 \
            else next(iter(self.ex.eval_node_dict))
        self.walls = []
        self.step()                    # the compile step — never counted
        self.walls.clear()
        after = step_cache_counts()
        self.compiled = (after.get("step_cache_miss", 0)
                         + after.get("step_cache_uncachable", 0)) \
            > (before.get("step_cache_miss", 0)
               + before.get("step_cache_uncachable", 0))
        if self.compiled:
            record_autoparallel("autoparallel_plans_compiled")
        if after.get("step_cache_hit", 0) > before.get("step_cache_hit", 0):
            record_autoparallel("autoparallel_candidate_cache_hits")

    def step(self, record=False):
        import numpy as np
        from ..metrics import record_step_time
        t0 = time.perf_counter()
        out = self.ex.run(self.name, feed_dict=self.fd)
        v = out[0]
        # host scalar read: the only reliable sync (async dispatch makes
        # run() return before the device finishes; materializing one
        # output of the jitted step waits for the whole executable)
        float(np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
              .ravel()[0])
        dt = time.perf_counter() - t0
        self.walls.append(dt * 1e6)
        if record:
            record_step_time(dt * 1e6, label=self.tag)
        return dt

    def finalize(self, peak_flops=None):
        from ..metrics import record_autoparallel
        record_autoparallel("autoparallel_plans_measured")
        # min over THIS run's walls — the registry histogram under the
        # same tag is process-wide (it may hold an earlier measurement's
        # steps), so the per-candidate verdict never reads back through it
        step_us = min(self.walls)
        mfu = None
        try:
            from ..obs import graph_flops, record_mfu
            # the FORWARD fetch only (the loss, out[0] by the build
            # contract): the optimizer fetch carries the backward
            # matmuls, which graph_flops' train=True 3x multiplier
            # already prices — including it would double-count
            flops = graph_flops([self.ex.eval_node_dict[self.name][0]],
                                feeds=self.fd)
            # the step spans every device in the executor's mesh; peak
            # is per-device (bench.py's mfu divides by peak * n_dev too)
            mesh = getattr(self.ex, "mesh", None)
            n_dev = mesh.size if mesh is not None else 1
            mfu = record_mfu(self.tag, flops, step_us / 1e6,
                             (peak_flops or _peak_flops()) * n_dev)
        except Exception:
            pass  # MFU is best-effort evidence; the step time is the verdict
        est = getattr(self.plan, "est_time", None)
        self.plan.measured_time = step_us / 1e6
        return PlanMeasurement(
            plan=self.plan, label=self.tag, step_time_us=step_us,
            walls_us=list(self.walls),
            predicted_us=None if est is None else est * 1e6, mfu=mfu,
            compiled=self.compiled)


def measure_plan(plan, build, steps=4, warmup=1, label="autoparallel",
                 peak_flops=None):
    """Run one candidate for ``steps`` measured steps; returns a
    :class:`PlanMeasurement`.

    ``build``: ``plan -> (executor, feed_dict[, subgraph_name])`` — must
    construct a FRESH graph for each call (plans annotate graph nodes in
    place, so candidates cannot share one graph).  The executor should be
    built with ``Executor(plan=plan)`` so the candidate's fingerprint
    keys the compiled-step cache.
    """
    run = _CandidateRun(plan, build, label)
    for _ in range(max(0, warmup)):
        run.step()
    run.walls.clear()
    for _ in range(max(1, steps)):
        run.step(record=True)
    return run.finalize(peak_flops)


def measure_plans(candidates, build, steps=4, warmup=1,
                  label="autoparallel", peak_flops=None):
    """Measure every candidate (``plan.candidates`` order); returns the
    :class:`PlanMeasurement` list ``ParallelPlan.rerank`` consumes.

    All candidates are built (and compiled) FIRST, then the measured
    steps run in interleaved rounds — candidate A step, candidate B
    step, ... — so allocator warm-up, page-cache state and background
    load perturb every candidate alike instead of flattering whichever
    ran last (the interleaved-rounds discipline of the host-overhead
    bench)."""
    runs = [_CandidateRun(p, build, label) for p in candidates]
    for _ in range(max(0, warmup)):
        for r in runs:
            r.step()
    for r in runs:
        r.walls.clear()
    for _ in range(max(1, steps)):
        for r in runs:
            r.step(record=True)
    return [r.finalize(peak_flops) for r in runs]


def plan_diff(plan, measured=None, hw=None, microbatches=None):
    """Per-layer predicted-vs-measured cost report for one plan.

    ``measured``: seconds, or a :class:`PlanMeasurement` (falls back to
    ``plan.measured_time``).  Per-layer predicted microseconds come from
    re-pricing each layer with :class:`TimeCostModel` under the plan's
    own HardwareSpec; the measured total is attributed per layer by
    predicted share — the finest honest attribution a fused XLA step
    allows (no per-layer timers survive fusion) — so ``model_error``
    (= measured_total / predicted_total) is the cost model's end-to-end
    miss and each row's predicted-vs-measured gap scales with it."""
    from .cost_model import HardwareSpec, TimeCostModel
    hw = hw or getattr(plan, "hw", None) or HardwareSpec.from_artifact() \
        or HardwareSpec()
    tm = TimeCostModel(hw, microbatches or plan.microbatches)
    if measured is None:
        measured = plan.measured_time
    if isinstance(measured, PlanMeasurement):
        measured = measured.seconds
    rows = []
    for spec, s in zip(plan.specs, plan.strategies):
        t = tm.layer_time(spec, s) * spec.count
        rows.append({"layer": spec.name, "count": spec.count,
                     "strategy": str(s), "predicted_us": t * 1e6})
    ptotal = sum(r["predicted_us"] for r in rows)
    out = {"plan": plan.tag(), "layers": rows,
           "predicted_total_us": ptotal,
           "measured_total_us": None, "model_error": None}
    if measured is not None and ptotal > 0:
        mtotal = float(measured) * 1e6
        scale = mtotal / ptotal
        for r in rows:
            r["measured_us"] = r["predicted_us"] * scale
        out["measured_total_us"] = mtotal
        out["model_error"] = scale
    return out


def format_plan_diff(diff):
    """Human table for a :func:`plan_diff` report."""
    lines = [f"plan {diff['plan']}  predicted "
             f"{diff['predicted_total_us']:.0f}us  measured "
             + (f"{diff['measured_total_us']:.0f}us  (model error "
                f"{diff['model_error']:.2f}x)"
                if diff["measured_total_us"] is not None else "—"),
             f"  {'layer':<28}{'strategy':<22}{'predicted':>12}"
             f"{'measured':>12}"]
    for r in diff["layers"]:
        meas = f"{r['measured_us']:.0f}us" if "measured_us" in r else "—"
        lines.append(f"  {r['layer']:<28}{r['strategy']:<22}"
                     f"{r['predicted_us']:>10.0f}us{meas:>12}")
    return "\n".join(lines)


__all__ = ["PlanMeasurement", "measure_plan", "measure_plans",
           "plan_diff", "format_plan_diff"]
