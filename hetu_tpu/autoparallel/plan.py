"""ParallelPlan: the search result, emitted as mesh axes + shardings.

Where Galvatron emits per-layer NCCL process groups + Megatron module
wrappers (``tools/Galvatron/*/hybrid_parallel_model.py``), the TPU plan is
declarative: a mesh ``{'pp','dp','tp'}`` plus per-layer GSPMD sharding
directives that :func:`apply` attaches to a model's layers through the
existing ``ht.dispatch`` / ``pipeline_block`` machinery.
"""
from __future__ import annotations

from .cost_model import Strategy


class ParallelPlan:
    def __init__(self, specs, strategies, n_devices, est_time=None,
                 microbatches=1, hw=None):
        self.specs = list(specs)
        self.strategies = list(strategies)
        self.n_devices = n_devices
        self.est_time = est_time
        self.microbatches = microbatches
        #: HardwareSpec the search priced this plan under (plan_diff's
        #: default when re-pricing per layer)
        self.hw = hw
        #: alternate plans from the same search (``search(topk=)``),
        #: est_time-ordered with this plan first; :meth:`rerank` re-orders
        #: them from measurements
        self.candidates = None
        #: measured step seconds (set by rerank / autoparallel.measure)
        self.measured_time = None
        self._layers = None

    # -- executor integration ------------------------------------------------
    def bind(self, layers):
        """Remember the model layers this plan should annotate, so
        ``Executor(plan=...)`` can apply the per-layer directives itself
        (zero-composition-aware: the executor knows the resolved ZeRO
        stage, the caller usually does not).  Returns self (chainable)."""
        self._layers = list(layers)
        return self

    def realize(self, zero=0, strict=True):
        """Executor hook: annotate the bound layers (no-op when nothing
        is bound — dp/fsdp-only plans need no per-layer annotations, and
        a caller may have applied the plan by hand)."""
        if self._layers is not None:
            self.apply(self._layers, strict=strict, zero=zero)

    def wants_zero(self):
        """True when this plan's ``fsdp`` sharding should be realized by
        the ZeRO slab machinery (``Executor(zero=3)``, parallel/zero.py)
        rather than per-param GSPMD annotations: every fsdp directive is
        tp-unsharded, so no kernel needs a combined (dp, tp) spec.  (A
        tp-sharded kernel carries an explicit dispatch annotation, which
        makes its optimizer ineligible for slab packing — those plans
        keep the GSPMD fsdp path.)"""
        return any(s.fsdp for s in self.strategies) \
            and max(s.tp for s in self.strategies) == 1

    def fingerprint(self):
        """Content hash of everything that makes this plan THIS plan
        (specs, per-layer strategies, device count, microbatches) — keyed
        into the compiled-step-cache signature so two executors differing
        only in plan never alias one executable."""
        import hashlib
        h = hashlib.sha256()
        h.update(f"{self.n_devices}|{self.microbatches}".encode())
        for spec, s in zip(self.specs, self.strategies):
            h.update(f"|{spec.name}x{spec.count}:{s}".encode())
        return h.hexdigest()[:16]

    def tag(self):
        """Short human tag: the uniform strategy string (``pp1-tp1-dp8``),
        or ``mixed-<fingerprint>`` for heterogeneous plans — labels the
        per-plan ``step_time_us`` histograms and MFU gauges."""
        if self.uniform:
            return str(self.strategies[0])
        return f"mixed-{self.fingerprint()[:8]}"

    def rerank(self, measurements):
        """Re-order :attr:`candidates` by MEASURED step time and return
        the measured-best plan — the feedback leg that lets the search
        correct a mispriced cost model.

        ``measurements``: the ``autoparallel.measure.measure_plans``
        result list (matched to candidates by plan identity, falling back
        to position), a ``{index: seconds}`` dict, or a list of seconds
        aligned with :attr:`candidates`.  Unmeasured candidates sort
        after measured ones by predicted time.  Records
        ``autoparallel_rerank_flips`` when the measured best differs from
        the predicted best."""
        from ..metrics import record_autoparallel
        cands = self.candidates or [self]
        secs = {}
        if isinstance(measurements, dict):
            secs = {int(i): float(s) for i, s in measurements.items()}
        else:
            for i, m in enumerate(measurements):
                plan = getattr(m, "plan", None)
                s = getattr(m, "seconds", None)
                if s is None and not hasattr(m, "plan"):
                    s = float(m)
                idx = next((j for j, c in enumerate(cands) if c is plan),
                           i if i < len(cands) else None)
                if idx is not None and s is not None:
                    secs[idx] = float(s)
        for i, s in secs.items():
            cands[i].measured_time = s
        order = sorted(
            range(len(cands)),
            key=lambda i: (0, secs[i]) if i in secs
            else (1, cands[i].est_time or 0.0))
        reordered = [cands[i] for i in order]
        if reordered[0] is not cands[0]:
            record_autoparallel("autoparallel_rerank_flips")
        best = reordered[0]
        best.candidates = reordered
        self.candidates = reordered
        return best

    def make_mesh(self, devices=None):
        """The plan's mesh over the first ``n_devices`` devices (what
        ``Executor(plan=...)`` compiles against)."""
        import jax

        from ..context import make_mesh
        axes = self.mesh_axes()
        n = 1
        for v in axes.values():
            n *= v
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < n:
            raise ValueError(
                f"plan mesh {axes} needs {n} devices, "
                f"got {len(devices)}")
        return make_mesh(axes, devices[:n])

    # -- mesh emission -------------------------------------------------------
    @property
    def uniform(self):
        return len(set(self.strategies)) == 1

    def mesh_axes(self):
        """Axis sizes for ``ht.make_mesh``. For non-uniform plans the mesh
        uses the max width per axis; narrower layers replicate over the
        leftover (GSPMD handles specs that omit an axis)."""
        pp = max(s.pp for s in self.strategies)
        tp = max(s.tp for s in self.strategies)
        cp = max(s.cp for s in self.strategies)
        if pp * tp * cp > self.n_devices:
            raise ValueError(
                f"mixed plan needs a pp{pp} x tp{tp} x cp{cp} mesh but "
                f"only {self.n_devices} devices exist; re-search with "
                "uniform=True (one strategy for all layers) or restrict "
                "candidates (allow_pp/max_tp/max_cp)")
        dp = self.n_devices // (pp * tp * cp)
        axes = {}
        if pp > 1:
            axes["pp"] = pp
        if dp > 1:
            axes["dp"] = dp
        if tp > 1:
            axes["tp"] = tp
        if cp > 1:
            axes["cp"] = cp
        return axes or {"dp": 1}

    def strategy(self):
        """An executor-ready distribution strategy for this plan."""
        from ..parallel.strategies import DataParallel, ModelParallel
        axes = self.mesh_axes()
        if set(axes) <= {"dp"}:
            return DataParallel(num_devices=self.n_devices)
        return ModelParallel(axes)

    # -- layer sharding directives ------------------------------------------
    def layer_specs(self):
        """Per-layer sharding directives:
        ``[{'stage': int, 'tp': int, 'fsdp': bool,
            'kernel_spec': P(None,'tp'), 'out_spec': P('tp',None)}, ...]``

        ``kernel_spec``/``out_spec`` are the canonical Megatron pair —
        column-parallel then row-parallel — to hand to ``ht.dispatch`` for a
        layer's two linear kernels.
        """
        from jax.sharding import PartitionSpec as P
        pp = max(s.pp for s in self.strategies)
        # expand by spec.count: one directive per ACTUAL model layer, so
        # apply() lines up with the model's layer list and the pp-stage
        # split weights repeated blocks correctly
        expanded = [(spec, s, i) for spec, s in zip(self.specs,
                                                    self.strategies)
                    for i in range(spec.count)]
        n = len(expanded)
        out = []
        for j, (spec, s, i) in enumerate(expanded):
            stage = min(j * pp // max(1, n), pp - 1)
            out.append({
                "name": spec.name if spec.count == 1
                else f"{spec.name}.{i}",
                "stage": stage,
                "tp": s.tp,
                "dp": s.dp,
                "cp": s.cp,
                "fsdp": s.fsdp,
                # fsdp composes with tp: the non-tp weight dim shards over
                # 'dp' (Megatron+ZeRO layout), realizing the cost model's
                # param/optimizer-state division by BOTH axes
                "kernel_spec": (
                    P("dp" if s.fsdp else None, "tp") if s.tp > 1
                    else (P("dp") if s.fsdp else P())),
                "out_kernel_spec": (
                    P("tp", "dp" if s.fsdp else None) if s.tp > 1
                    else (P("dp") if s.fsdp else P())),
                "param_spec": (P("dp") if s.fsdp else P()),
            })
        return out

    def apply(self, layers, strict=True, zero=0):
        """Annotate model layers in place.

        ``layers``: sequence of objects exposing (any of) ``weight_var`` /
        ``in_kernels`` / ``out_kernels`` — e.g. our Linear / attention /
        FFN layers. Column-parallel specs go on ``in_kernels``,
        row-parallel on ``out_kernels``; fsdp directives shard every layer
        kernel over 'dp' (ZeRO-style param sharding — without this the
        MemoryCostModel's feasibility verdict would not hold at runtime).

        ``zero``: the executor's resolved ZeRO stage.  When it is on and
        :meth:`wants_zero` holds, the fsdp directives are realized by the
        slab machinery (``parallel/zero.py``) and the per-param 'dp'
        dispatch here is SKIPPED — an annotated param would make its
        optimizer ineligible for slab packing, so dispatching both would
        silently disable the very mechanism meant to realize the plan
        (the double-sharding trap ``Executor(plan=...)`` guards).

        Stage ('pp') directives cannot restructure an already-built model:
        they are realized by building with ``ht.pipeline_block``; with
        ``strict=True`` (default) a plan that needs pp raises here instead
        of silently executing un-pipelined.
        """
        import warnings
        from ..parallel.dispatch import apply_plan_directive
        directives = self.layer_specs()
        if len(layers) != len(directives):
            raise ValueError(
                f"plan has {len(directives)} layers, model has {len(layers)}")
        pp = max(s.pp for s in self.strategies)
        if pp > 1:
            msg = (f"plan assigns {pp} pipeline stages, which apply() "
                   "cannot retrofit onto a built model — construct the "
                   "model with ht.pipeline_block(n_stages=%d) and pass "
                   "the plan's stage assignment instead" % pp)
            if strict:
                raise ValueError(msg)
            warnings.warn(msg)
        cp = max(s.cp for s in self.strategies)
        if cp > 1:
            msg = (f"plan assigns cp={cp} context parallelism, which "
                   "apply() cannot retrofit onto built attention — "
                   "construct the model with context_parallel='ring' (or "
                   "'ulysses') and run on this plan's mesh")
            if strict:
                raise ValueError(msg)
            warnings.warn(msg)

        fsdp_via_zero = bool(zero) and self.wants_zero()
        for layer, d in zip(layers, directives):
            apply_plan_directive(layer, d, fsdp_via_zero=fsdp_via_zero)
        return directives

    def describe(self):
        lines = [f"devices={self.n_devices} mesh={self.mesh_axes()} "
                 f"est_step={self.est_time:.4f}s "
                 f"microbatches={self.microbatches}"]
        for spec, s in zip(self.specs, self.strategies):
            lines.append(f"  {spec.name} x{spec.count}: {s}")
        return "\n".join(lines)


__all__ = ["ParallelPlan", "Strategy"]
