"""ParallelPlan: the search result, emitted as mesh axes + shardings.

Where Galvatron emits per-layer NCCL process groups + Megatron module
wrappers (``tools/Galvatron/*/hybrid_parallel_model.py``), the TPU plan is
declarative: a mesh ``{'pp','dp','tp'}`` plus per-layer GSPMD sharding
directives that :func:`apply` attaches to a model's layers through the
existing ``ht.dispatch`` / ``pipeline_block`` machinery.
"""
from __future__ import annotations

from .cost_model import Strategy


class ParallelPlan:
    def __init__(self, specs, strategies, n_devices, est_time=None,
                 microbatches=1):
        self.specs = list(specs)
        self.strategies = list(strategies)
        self.n_devices = n_devices
        self.est_time = est_time
        self.microbatches = microbatches

    # -- mesh emission -------------------------------------------------------
    @property
    def uniform(self):
        return len(set(self.strategies)) == 1

    def mesh_axes(self):
        """Axis sizes for ``ht.make_mesh``. For non-uniform plans the mesh
        uses the max width per axis; narrower layers replicate over the
        leftover (GSPMD handles specs that omit an axis)."""
        pp = max(s.pp for s in self.strategies)
        tp = max(s.tp for s in self.strategies)
        cp = max(s.cp for s in self.strategies)
        if pp * tp * cp > self.n_devices:
            raise ValueError(
                f"mixed plan needs a pp{pp} x tp{tp} x cp{cp} mesh but "
                f"only {self.n_devices} devices exist; re-search with "
                "uniform=True (one strategy for all layers) or restrict "
                "candidates (allow_pp/max_tp/max_cp)")
        dp = self.n_devices // (pp * tp * cp)
        axes = {}
        if pp > 1:
            axes["pp"] = pp
        if dp > 1:
            axes["dp"] = dp
        if tp > 1:
            axes["tp"] = tp
        if cp > 1:
            axes["cp"] = cp
        return axes or {"dp": 1}

    def strategy(self):
        """An executor-ready distribution strategy for this plan."""
        from ..parallel.strategies import DataParallel, ModelParallel
        axes = self.mesh_axes()
        if set(axes) <= {"dp"}:
            return DataParallel()
        return ModelParallel(axes)

    # -- layer sharding directives ------------------------------------------
    def layer_specs(self):
        """Per-layer sharding directives:
        ``[{'stage': int, 'tp': int, 'fsdp': bool,
            'kernel_spec': P(None,'tp'), 'out_spec': P('tp',None)}, ...]``

        ``kernel_spec``/``out_spec`` are the canonical Megatron pair —
        column-parallel then row-parallel — to hand to ``ht.dispatch`` for a
        layer's two linear kernels.
        """
        from jax.sharding import PartitionSpec as P
        pp = max(s.pp for s in self.strategies)
        # expand by spec.count: one directive per ACTUAL model layer, so
        # apply() lines up with the model's layer list and the pp-stage
        # split weights repeated blocks correctly
        expanded = [(spec, s, i) for spec, s in zip(self.specs,
                                                    self.strategies)
                    for i in range(spec.count)]
        n = len(expanded)
        out = []
        for j, (spec, s, i) in enumerate(expanded):
            stage = min(j * pp // max(1, n), pp - 1)
            out.append({
                "name": spec.name if spec.count == 1
                else f"{spec.name}.{i}",
                "stage": stage,
                "tp": s.tp,
                "dp": s.dp,
                "cp": s.cp,
                "fsdp": s.fsdp,
                # fsdp composes with tp: the non-tp weight dim shards over
                # 'dp' (Megatron+ZeRO layout), realizing the cost model's
                # param/optimizer-state division by BOTH axes
                "kernel_spec": (
                    P("dp" if s.fsdp else None, "tp") if s.tp > 1
                    else (P("dp") if s.fsdp else P())),
                "out_kernel_spec": (
                    P("tp", "dp" if s.fsdp else None) if s.tp > 1
                    else (P("dp") if s.fsdp else P())),
                "param_spec": (P("dp") if s.fsdp else P()),
            })
        return out

    def apply(self, layers, strict=True):
        """Annotate model layers in place.

        ``layers``: sequence of objects exposing (any of) ``weight_var`` /
        ``in_kernels`` / ``out_kernels`` — e.g. our Linear / attention /
        FFN layers. Column-parallel specs go on ``in_kernels``,
        row-parallel on ``out_kernels``; fsdp directives shard every layer
        kernel over 'dp' (ZeRO-style param sharding — without this the
        MemoryCostModel's feasibility verdict would not hold at runtime).

        Stage ('pp') directives cannot restructure an already-built model:
        they are realized by building with ``ht.pipeline_block``; with
        ``strict=True`` (default) a plan that needs pp raises here instead
        of silently executing un-pipelined.
        """
        import warnings
        from ..parallel.dispatch import dispatch
        directives = self.layer_specs()
        if len(layers) != len(directives):
            raise ValueError(
                f"plan has {len(directives)} layers, model has {len(layers)}")
        pp = max(s.pp for s in self.strategies)
        if pp > 1:
            msg = (f"plan assigns {pp} pipeline stages, which apply() "
                   "cannot retrofit onto a built model — construct the "
                   "model with ht.pipeline_block(n_stages=%d) and pass "
                   "the plan's stage assignment instead" % pp)
            if strict:
                raise ValueError(msg)
            warnings.warn(msg)
        cp = max(s.cp for s in self.strategies)
        if cp > 1:
            msg = (f"plan assigns cp={cp} context parallelism, which "
                   "apply() cannot retrofit onto built attention — "
                   "construct the model with context_parallel='ring' (or "
                   "'ulysses') and run on this plan's mesh")
            if strict:
                raise ValueError(msg)
            warnings.warn(msg)

        def _kernels(layer):
            ks = list(getattr(layer, "in_kernels", []) or []) \
                + list(getattr(layer, "out_kernels", []) or [])
            w = getattr(layer, "weight_var", None)
            if w is not None and w not in ks:
                ks.append(w)
            return ks

        for layer, d in zip(layers, directives):
            if d["tp"] > 1:
                for v in getattr(layer, "in_kernels", []):
                    dispatch(v, d["kernel_spec"])
                for v in getattr(layer, "out_kernels", []):
                    dispatch(v, d["out_kernel_spec"])
                w = getattr(layer, "weight_var", None)
                if w is not None and not getattr(layer, "in_kernels", None):
                    dispatch(w, d["kernel_spec"])
            if d["fsdp"]:
                # ZeRO-style: params sharded over 'dp'; XLA inserts the
                # all-gather before use. tp-sharded kernels already carry
                # the combined (dp, tp) spec from the branch above; this
                # covers the remaining (tp-unsharded) kernels
                for v in _kernels(layer):
                    if getattr(v, "sharding", None) is None:
                        dispatch(v, d["param_spec"])
        return directives

    def describe(self):
        lines = [f"devices={self.n_devices} mesh={self.mesh_axes()} "
                 f"est_step={self.est_time:.4f}s "
                 f"microbatches={self.microbatches}"]
        for spec, s in zip(self.specs, self.strategies):
            lines.append(f"  {spec.name} x{spec.count}: {s}")
        return "\n".join(lines)


__all__ = ["ParallelPlan", "Strategy"]
