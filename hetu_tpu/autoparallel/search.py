"""Layerwise dynamic-programming strategy search (Galvatron DPAlg parity).

Reference: ``tools/Galvatron/utils/dp_utils.py:55`` — per-layer strategy
selection by DP under a per-device memory budget, with a resharding penalty
when consecutive layers change strategy. Emits TPU mesh axes + sharding
specs rather than NCCL groups.
"""
from __future__ import annotations

from .cost_model import (HardwareSpec, MemoryCostModel, Strategy,
                         TimeCostModel)


def candidate_strategies(n_devices, allow_pp=True, allow_fsdp=True,
                         max_tp=None, allow_cp=False, max_cp=None,
                         max_dp=None):
    """All (pp, tp, dp[, cp], fsdp) factorizations of n_devices (powers of
    2).  ``allow_cp`` adds the context-parallel axis (net-new vs Galvatron
    — the searcher can trade dp width for sequence sharding when
    activations dominate memory).  ``max_dp`` bounds data parallelism by
    the GLOBAL BATCH (dp cannot exceed the number of samples) — the
    long-context small-batch regime where only cp can spread one
    sequence's activations over devices."""
    cands = []
    pps = [1]
    p = 2
    while allow_pp and p <= n_devices:
        pps.append(p)
        p *= 2
    for pp in pps:
        rest = n_devices // pp
        if pp * rest != n_devices:
            continue
        tp = 1
        while tp <= rest:
            if max_tp and tp > max_tp:
                break
            inner = rest // tp
            if tp * inner == rest:
                cp = 1
                while cp <= inner:
                    if not allow_cp and cp > 1:
                        break
                    if max_cp and cp > max_cp:
                        break
                    dp = inner // cp
                    if cp * dp == inner and not (max_dp and dp > max_dp):
                        cands.append(Strategy(pp, tp, dp, False, cp))
                        if allow_fsdp and dp > 1:
                            cands.append(Strategy(pp, tp, dp, True, cp))
                    cp *= 2
            tp *= 2
    return cands


def _switch_cost(a: Strategy, b: Strategy, act_bytes, hw: HardwareSpec):
    """Resharding cost between consecutive layers with different layouts —
    an all-to-allish move of the activations (Galvatron models this as a
    fixed transfer coefficient)."""
    if (a.tp, a.dp, a.pp, a.cp) == (b.tp, b.dp, b.pp, b.cp):
        return 0.0
    return act_bytes / hw.coll_bw(max(a.world, b.world))


class DPAlg:
    """min-time DP over layers × strategies with a memory constraint.

    Memory is tracked as the running per-stage total; a strategy chain is
    feasible iff the projected stage bytes stay under ``hw.mem_bytes``.
    (Galvatron discretizes memory; layer counts here are small enough to
    track exact floats per DP state.)
    """

    def __init__(self, specs, n_devices, hw=None, microbatches=1,
                 remat=False, allow_pp=True, allow_fsdp=True, max_tp=None,
                 allow_cp=False, max_cp=None, max_dp=None, calibrate=False):
        self.specs = list(specs)
        # unspecified hardware: live calibration when asked for
        # (``calibrate=True`` — the profile leg of the Galvatron workflow
        # wired straight into construction; pass a mesh to also measure
        # collective bandwidth/overlap over it), else the committed
        # on-chip calibration artifact, else the built-in defaults
        if hw is None and calibrate:
            hw = HardwareSpec.measure(
                mesh=calibrate if calibrate is not True else None)
        self.hw = hw or HardwareSpec.from_artifact() or HardwareSpec()
        self.mem = MemoryCostModel(self.hw, microbatches, remat)
        self.time = TimeCostModel(self.hw, microbatches)
        self.cands = candidate_strategies(n_devices, allow_pp, allow_fsdp,
                                          max_tp, allow_cp, max_cp, max_dp)
        if not self.cands:
            raise ValueError(f"no strategy candidates for {n_devices} devices")

    #: cap on Pareto states kept per (layer, strategy) cell
    MAX_FRONTIER = 32

    @staticmethod
    def _pareto(entries, cap):
        """Prune (time, mem, chain) entries to the Pareto frontier over
        (time, mem); keep at most ``cap``, fastest first.

        A pure min-time DP is wrong here: the fastest chain so far may be
        memory-heavy and infeasible to extend, while a slower lean chain
        survives — (time, mem) trade off, so both must be kept.
        """
        entries.sort(key=lambda e: (e[0], e[1]))
        out = []
        best_mem = float("inf")
        for e in entries:
            if e[1] < best_mem:  # strictly less memory than any faster chain
                out.append(e)
                best_mem = e[1]
            if len(out) >= cap:
                break
        return out

    def fit(self):
        """Returns (best_time, [Strategy per spec]) or (inf, None)."""
        INF = float("inf")
        # state: strategy index -> Pareto list of (time, mem, chain)
        layer0 = self.specs[0]
        states = {}
        for i, s in enumerate(self.cands):
            t = self.time.layer_time(layer0, s) * layer0.count
            m = self.mem.layer_bytes(layer0, s) * layer0.count / s.pp
            if m <= self.hw.mem_bytes:
                states[i] = [(t, m, (i,))]
        if not states:
            return INF, None
        for li in range(1, len(self.specs)):
            spec = self.specs[li]
            new_states = {}
            for j, s in enumerate(self.cands):
                lt = self.time.layer_time(spec, s) * spec.count
                lm = self.mem.layer_bytes(spec, s) * spec.count / s.pp
                cands = []
                for i, frontier in states.items():
                    sw = _switch_cost(self.cands[i], s, spec.act_bytes,
                                      self.hw)
                    for (t, m, chain) in frontier:
                        cand_m = m + lm
                        if cand_m > self.hw.mem_bytes:
                            continue
                        cands.append((t + lt + sw, cand_m, chain + (j,)))
                if cands:
                    new_states[j] = self._pareto(cands, self.MAX_FRONTIER)
            if not new_states:
                return INF, None
            states = new_states
        best = min((f[0] for f in states.values()), key=lambda e: e[0])
        return best[0], [self.cands[i] for i in best[2]]


def search(specs, n_devices, hw=None, microbatches=1, remat=False,
           uniform=False, topk=1, calibrate=False, **kw):
    """Top-level search → :class:`ParallelPlan`.

    ``uniform=True`` restricts to one strategy for all layers (the common
    deployment case; also what the executor's single-mesh emission needs).
    ``calibrate=True`` (or a mesh) measures the HardwareSpec live instead
    of artifact/defaults when ``hw`` is not given.
    ``topk > 1`` additionally attaches the k best feasible UNIFORM
    alternates as ``plan.candidates`` (est_time-ordered, the returned
    plan first) — the measurement loop
    (``autoparallel.measure.measure_plans`` → ``plan.rerank``) runs these
    for real and re-orders them by measured step time.
    """
    from ..metrics import record_autoparallel
    from .plan import ParallelPlan
    alg = DPAlg(specs, n_devices, hw=hw, microbatches=microbatches,
                remat=remat, calibrate=calibrate, **kw)
    # feasible uniform chains, fastest first (the uniform answer AND the
    # alternate pool for topk — a DP primary's alternates are the uniform
    # plans the executor could equally compile)
    scored = []
    if uniform or topk > 1:          # only these paths consume the sweep
        for s in alg.cands:
            strategies = [s] * len(specs)
            if not alg.mem.fits(specs, strategies):
                continue
            scored.append((alg.time.total(specs, strategies), strategies))
        scored.sort(key=lambda e: e[0])
    if uniform:
        t, strategies = scored[0] if scored else (float("inf"), None)
    else:
        t, strategies = alg.fit()
    if strategies is None:
        raise ValueError(
            "no feasible strategy under the memory budget; raise mem_bytes, "
            "enable remat, or increase device count")
    plan = ParallelPlan(specs, strategies, n_devices, est_time=t,
                        microbatches=microbatches, hw=alg.hw)
    if topk > 1:
        cands = [plan]
        for tt, st in scored:
            if len(cands) >= topk:
                break
            if st == plan.strategies:
                continue
            alt = ParallelPlan(specs, st, n_devices, est_time=tt,
                               microbatches=microbatches, hw=alg.hw)
            cands.append(alt)
        cands.sort(key=lambda p: p.est_time)
        plan.candidates = cands
    record_autoparallel("autoparallel_plans_searched")
    return plan


def search_graph(fetches, n_devices, feeds=None, hw=None, calibrate=False,
                 split=None, dtype_bytes=4, name="graph", **kw):
    """Search a REAL fetch subgraph end-to-end: per-layer
    :class:`LayerSpec`s inferred from the graph that will actually
    compile (:func:`~hetu_tpu.autoparallel.cost_model.graph_layer_specs`
    — shape-inferred params/FLOPs/activations bucketed by the
    ``<prefix>.layer<N>`` naming convention, or a custom ``split``), then
    the standard layerwise DP.  Pass FORWARD fetches (the loss), not the
    optimizer op — the time model applies the fwd+bwd multiplier itself.

    ``feeds``: example values/shapes for placeholders declared without a
    static shape (same contract as ``ht.lint``)."""
    from .cost_model import graph_layer_specs
    specs = graph_layer_specs(fetches, feeds=feeds, split=split,
                              name=name, dtype_bytes=dtype_bytes)
    return search(specs, n_devices, hw=hw, calibrate=calibrate, **kw)


__all__ = ["DPAlg", "candidate_strategies", "search", "search_graph"]
