"""Chaos injection — deterministic, seedable fault schedules for the
fault-tolerance layer (ISSUE 2 tentpole stratum 1).

Real clusters lose PS servers, preempt hosts, and wedge sockets; the PS
lineage this repo reproduces (SSP bounds, P-Reduce dynamic groups) exists
*because* of those failures.  This module turns every failure mode into a
reproducible experiment instead of an anecdote: a :class:`ChaosInjector`
parsed from ``HETU_CHAOS=<seed>:<spec>[,<spec>...]`` drives

* **transport faults** — the dist-store client consults :func:`active`
  before every RPC frame and the injector answers drop / delay /
  duplicate / wedge with decisions drawn from a seeded RNG (same seed ⇒
  the exact same fault sequence for the same event order);
* **process-level kills** — ``kill:ps@rank<r>:step<s>`` stops a
  registered :class:`~hetu_tpu.ps.dist_store.StoreServer` when the
  executor reports training step ``s``; ``kill:proc@rank<r>:after<ms>``
  tells the supervising launcher to kill a child rank after a wall-clock
  delay (fired at most once per injector); ``kill:proc@rank<r>:step<n>``
  is the DETERMINISTIC form on the step clock — it stops a worker-rank
  handle registered via :meth:`ChaosInjector.register_proc` when the
  executor reports step ``n``, so the elastic tests
  (:mod:`hetu_tpu.parallel.elastic`) kill a rank at an exact step
  boundary instead of a wall-clock race;
* **replica-role kills** — with PS shard replication
  (``replication=2``), ``kill:primary@shard<s>:step<n>`` stops whichever
  registered server currently SERVES shard ``s`` at step ``n`` (resolved
  at fire time, so after a failover it targets the promoted ex-backup),
  and ``kill:backup@shard<s>:step<n>`` stops the server that HOLDS shard
  ``s`` without serving it — the two sides of the failover window the
  replication tests must straddle.  The ``:req<n>`` form
  (``kill:primary@shard<s>:req<n>``) schedules the same kill on the
  SERVING clock instead: it fires once ``n`` requests have been admitted
  by the online-serving router (:mod:`hetu_tpu.serving`), which reports
  its admission count through :meth:`ChaosInjector.on_request` — a
  serving process has no training steps, so "kill the primary mid-load"
  needs its own trigger;
* **fleet replica kills** (ISSUE 17) — ``kill:replica@<idx>:req<n>``
  fail-stops serving replica ``idx`` of a
  :class:`~hetu_tpu.serving.fleet.FrontDoor` once the front door has
  admitted ``n`` requests.  The clock is the DOOR's admission count
  (every admission calls :meth:`ChaosInjector.on_request` before
  dispatch), so the kill lands at a deterministic point in the request
  stream; targets volunteer via :meth:`ChaosInjector.register_replica`
  and die via their ``stop()`` (the router fail-stops at its next batch
  boundary, leaving its queue for the front door to rescue).  Like
  every kill, it consumes no RNG draw and fires at most once;
  ``kill:replica@<idx>:tok<n>`` (ISSUE 19) schedules the same kill on
  the DECODE ENGINE's own emitted-token clock instead — the victim's
  router loop reports cumulative tokens to
  :meth:`ChaosInjector.on_token` after every step, so the kill lands
  MID-GENERATION at an exact, replayable token count (the admission
  clock cannot reach inside a generation), exercising the in-flight
  stream recovery path (``detach_inflight`` → continuation adoption);
* **network partitions** —
  ``partition:rank<a>[+rank<b>...]|rank<c>[+rank<d>...]@step<n>[:heal<m>]``
  drops every frame BOTH directions between the two rank sets from the
  moment :meth:`ChaosInjector.on_step` reaches step ``n`` until it
  reaches ``m`` (omit ``:heal<m>`` for a partition that never heals).
  Unlike kills, a partition is LEVEL-triggered on the step clock — it
  is active for a window, never "fired once" — and it is fully
  deterministic (no RNG draw is consumed), so the same seed reproduces
  the same partition alongside the same probabilistic fault stream.
  Each dropped frame counts ``partition_frames_dropped``.  Senders
  identify themselves via ``on_send(..., src=rank)``; a frame whose
  sender is unknown (``src=None``) is never partition-dropped.

Spec grammar (everything after the first ``:`` is the comma-separated
fault list; probabilities in [0, 1], durations in milliseconds)::

    HETU_CHAOS="1234:drop=0.1,delay=0.2:50,dup=0.05,wedge=0.01:2000"
    HETU_CHAOS="7:kill:ps@rank1:step3"
    HETU_CHAOS="7:kill:proc@rank0:after250"
    HETU_CHAOS="7:kill:proc@rank2:step5"
    HETU_CHAOS="7:kill:primary@shard1:step3"
    HETU_CHAOS="7:kill:backup@shard1:step3"
    HETU_CHAOS="7:kill:primary@shard1:req200"
    HETU_CHAOS="7:kill:replica@1:req40"
    HETU_CHAOS="7:kill:replica@0:tok16"
    HETU_CHAOS="7:partition:rank0|rank1@step3:heal7"
    HETU_CHAOS="7:partition:rank0+rank1|rank2+rank3@step3"

Every injected fault increments a named counter in
:mod:`hetu_tpu.metrics` (``chaos_drop``, ``chaos_kill_ps``,
``partition_frames_dropped``, ...) so ``HetuProfiler.fault_counters()``
shows exactly what the schedule did.
"""
from __future__ import annotations

import os
import random

from .metrics import record_fault
from .obs.lock_witness import make_lock

#: transport fault kinds a schedule may inject on an outgoing RPC frame
_TRANSPORT_KINDS = ("drop", "delay", "dup", "wedge")


class ChaosSpecError(ValueError):
    """Malformed ``HETU_CHAOS`` spec (loud: a typo'd schedule silently
    injecting nothing would make a chaos run indistinguishable from a
    clean one)."""


_PARTITION_GRAMMAR = ("partition:rank<a>[+rank<b>...]|rank<c>[+rank<d>"
                      "...]@step<n>[:heal<m>]")


def _parse_rank_set(side, part):
    """``rank0+rank2`` -> frozenset({0, 2}); loud on anything else."""
    ranks = set()
    for tok in side.split("+"):
        tok = tok.strip()
        if not tok.startswith("rank"):
            raise ChaosSpecError(
                f"bad partition side {side!r} in {part!r}: expected "
                f"{_PARTITION_GRAMMAR}")
        try:
            ranks.add(int(tok[len("rank"):]))
        except ValueError:
            raise ChaosSpecError(
                f"bad rank {tok!r} in partition fault {part!r}: expected "
                f"{_PARTITION_GRAMMAR}") from None
    return frozenset(ranks)


def _parse_partition(part):
    """``partition:<side>|<side>@step<n>[:heal<m>]`` -> fault dict.

    Validated loudly: two non-empty DISJOINT rank sets (an overlapping
    cut is ill-defined), an integer start step, and — when given — a
    heal step strictly after the start (a zero-length window would make
    the chaos run indistinguishable from a clean one)."""
    body = part[len("partition:"):]
    try:
        sides, when = body.split("@", 1)
        a_s, b_s = sides.split("|", 1)
    except ValueError:
        raise ChaosSpecError(
            f"bad partition fault {part!r}: expected "
            f"{_PARTITION_GRAMMAR}") from None
    a, b = _parse_rank_set(a_s, part), _parse_rank_set(b_s, part)
    if not a or not b:
        raise ChaosSpecError(
            f"empty partition side in {part!r}: expected "
            f"{_PARTITION_GRAMMAR}")
    if a & b:
        raise ChaosSpecError(
            f"partition sides overlap on rank(s) {sorted(a & b)} in "
            f"{part!r} — a rank cannot sit on both sides of the cut")
    heal = None
    if ":" in when:
        when, heal_s = when.split(":", 1)
        if not heal_s.startswith("heal"):
            raise ChaosSpecError(
                f"bad partition clause {heal_s!r} in {part!r}: expected "
                f"{_PARTITION_GRAMMAR}")
        try:
            heal = int(heal_s[len("heal"):])
        except ValueError:
            raise ChaosSpecError(
                f"bad heal step in {part!r}: expected "
                f"{_PARTITION_GRAMMAR}") from None
    if not when.startswith("step"):
        raise ChaosSpecError(
            f"bad partition trigger {when!r} in {part!r}: expected "
            f"{_PARTITION_GRAMMAR}")
    try:
        step = int(when[len("step"):])
    except ValueError:
        raise ChaosSpecError(
            f"bad partition step in {part!r}: expected "
            f"{_PARTITION_GRAMMAR}") from None
    if heal is not None and heal <= step:
        raise ChaosSpecError(
            f"partition heal step {heal} must be after its start step "
            f"{step} in {part!r}")
    return {"kind": "partition", "a": a, "b": b, "step": step,
            "heal": heal}


def _parse_fault(part):
    part = part.strip()
    if not part:
        raise ChaosSpecError("empty fault entry")
    if part.startswith("partition:"):
        return _parse_partition(part)
    if part.startswith("kill:"):
        # kill:ps@rank<r>:step<s> | kill:proc@rank<r>:after<ms>
        # | kill:{primary,backup}@shard<s>:{step<n>|req<n>}  (replica-
        #   role kills, resolved against the live serving/holding sets at
        #   fire time; req<n> fires on the serving router's admission
        #   clock instead of the training step clock)
        # | kill:replica@<idx>:req<n>  (ISSUE 17: fleet serving-replica
        #   kill on the FRONT DOOR's admission clock, resolved against
        #   register_replica'd handles)
        # | kill:replica@<idx>:tok<n>  (ISSUE 19: MID-GENERATION decode
        #   replica kill on the victim engine's own deterministic
        #   emitted-token clock — fires once replica <idx> has emitted
        #   n tokens, landing inside a generation regardless of how the
        #   door spread the request stream)
        try:
            _, rest = part.split(":", 1)
            what, where = rest.split("@", 1)
            target, when = where.split(":", 1)
            if what == "replica":
                if when.startswith("req"):
                    return {"kind": "kill_replica", "idx": int(target),
                            "req": int(when[len("req"):])}
                if when.startswith("tok"):
                    return {"kind": "kill_replica", "idx": int(target),
                            "tok": int(when[len("tok"):])}
                raise ValueError(part)
            if what in ("primary", "backup"):
                if not target.startswith("shard"):
                    raise ValueError(part)
                shard = int(target[len("shard"):])
                if when.startswith("step"):
                    return {"kind": f"kill_{what}", "shard": shard,
                            "step": int(when[len("step"):])}
                if when.startswith("req"):
                    return {"kind": f"kill_{what}", "shard": shard,
                            "req": int(when[len("req"):])}
                raise ValueError(part)
            if not target.startswith("rank"):
                raise ValueError(part)
            rank = int(target[len("rank"):])
            if what == "ps" and when.startswith("step"):
                return {"kind": "kill_ps", "rank": rank,
                        "step": int(when[len("step"):])}
            if what == "proc" and when.startswith("after"):
                return {"kind": "kill_proc", "rank": rank,
                        "after_ms": float(when[len("after"):])}
            if what == "proc" and when.startswith("step"):
                # deterministic form: fires on the executor's step clock
                # against a register_proc'd handle — elastic tests kill a
                # rank at an EXACT step boundary instead of a wall-clock
                # delay (the after<ms> form stays the launcher's)
                return {"kind": "kill_proc", "rank": rank,
                        "step": int(when[len("step"):])}
            raise ValueError(part)
        except (ValueError, IndexError):
            raise ChaosSpecError(
                f"bad kill fault {part!r}: expected kill:ps@rank<r>:step<s>,"
                f" kill:proc@rank<r>:{{after<ms>|step<n>}}, "
                f"kill:{{primary,backup}}@shard<s>:{{step<n>|req<n>}}, or "
                f"kill:replica@<idx>:{{req<n>|tok<n>}}"
                ) from None
    if "=" not in part:
        raise ChaosSpecError(f"bad fault {part!r}: expected <kind>=<prob>"
                             f"[:<ms>] or kill:...")
    kind, val = part.split("=", 1)
    kind = kind.strip()
    if kind not in _TRANSPORT_KINDS:
        raise ChaosSpecError(
            f"unknown fault kind {kind!r} (known: {_TRANSPORT_KINDS})")
    ms = 0.0
    if ":" in val:
        val, ms_s = val.split(":", 1)
        ms = float(ms_s)
    try:
        prob = float(val)
    except ValueError:
        raise ChaosSpecError(f"bad probability in {part!r}") from None
    if not 0.0 <= prob <= 1.0:
        raise ChaosSpecError(f"probability {prob} out of [0,1] in {part!r}")
    if kind in ("delay", "wedge") and ms <= 0:
        raise ChaosSpecError(f"{kind} needs a duration: {kind}=<p>:<ms>")
    return {"kind": kind, "prob": prob, "ms": ms}


def parse_spec(spec):
    """``"<seed>:<fault>[,<fault>...]"`` → ``(seed, [fault dicts])``."""
    if ":" not in spec:
        raise ChaosSpecError(
            f"chaos spec {spec!r} missing the '<seed>:' prefix")
    seed_s, rest = spec.split(":", 1)
    try:
        seed = int(seed_s)
    except ValueError:
        raise ChaosSpecError(f"bad chaos seed {seed_s!r}") from None
    faults = [_parse_fault(p) for p in rest.split(",") if p.strip()]
    if not faults:
        raise ChaosSpecError(f"chaos spec {spec!r} declares no faults")
    return seed, faults


class ChaosInjector:
    """One parsed schedule + its RNG stream + its kill registry.

    Determinism contract: probabilistic decisions are drawn from ONE
    ``random.Random(seed)`` stream in event order — the same seed and the
    same sequence of :meth:`on_send` calls produce the same action
    sequence (the determinism test's exact claim).  Multi-threaded
    transports still get a *reproducible distribution* (the lock
    serializes draws), single-threaded tests get bitwise repeatability.
    """

    def __init__(self, seed, faults):
        self.seed = seed
        self.faults = list(faults)
        self._rng = random.Random(seed)
        self._lock = make_lock("ChaosInjector._lock")
        self._servers = {}          # rank -> StoreServer
        self._procs = {}            # rank -> proc handle (step-clock kills)
        self._replicas = {}         # idx -> fleet replica handle (ISSUE 17)
        self._fired = set()         # one-shot kill faults already fired
        #: the step clock partitions level-trigger on (fed by on_step);
        #: -1 = the executor never reported a step, so no partition is
        #: active yet.  Kills keep their own one-shot ``_fired`` set —
        #: the two clocks share on_step but nothing else (a heal must
        #: never consume or be consumed by a kill firing).
        self._now_step = -1
        #: per-event action log, kept for the determinism tests; bounded
        #: so a long chaos run doesn't grow it without limit
        self.decisions = []
        self.decisions_cap = 65536

    @classmethod
    def from_spec(cls, spec):
        seed, faults = parse_spec(spec)
        return cls(seed, faults)

    @classmethod
    def from_env(cls, env_var="HETU_CHAOS"):
        spec = os.environ.get(env_var, "").strip()
        return cls.from_spec(spec) if spec else None

    # -- transport faults --------------------------------------------------
    def _partitioned(self, src, peer):
        """True iff an ACTIVE partition separates ``src`` from ``peer``
        (caller holds the lock).  Level-triggered on the on_step clock:
        active from its start step until its heal step (or forever);
        symmetric (frames drop both directions); never consumes an RNG
        draw, so adding a partition to a schedule does not shift the
        probabilistic fault stream."""
        if src is None or peer is None:
            return False
        for f in self.faults:
            if f["kind"] != "partition" or self._now_step < f["step"]:
                continue
            if f["heal"] is not None and self._now_step >= f["heal"]:
                continue
            if (src in f["a"] and peer in f["b"]) \
                    or (src in f["b"] and peer in f["a"]):
                return True
        return False

    def on_send(self, peer=None, op=None, src=None):
        """Decide the fate of one outgoing RPC frame.

        Returns ``None`` (send normally) or ``(kind, ms)`` with kind in
        ``drop`` (never send; the client sees a timeout and retries),
        ``delay`` (sleep ``ms`` then send), ``dup`` (send the frame twice
        — the server's (client, seq) dedup must absorb it), ``wedge``
        (hold the socket ``ms``; the client's op deadline fires).

        ``src`` is the SENDING rank (transports pass their own rank so
        partition faults can tell which side of a cut the frame leaves
        from).  An active partition between ``src`` and ``peer`` drops
        the frame deterministically — it overrides any probabilistic
        fault, but the probabilistic draws still happen first so the
        RNG stream position stays a function of (schedule, event count)
        alone.
        """
        with self._lock:
            action = None
            for f in self.faults:
                if f["kind"] not in _TRANSPORT_KINDS:
                    continue
                # one draw per prob-fault per event: the stream position
                # depends only on (schedule, event count), never on which
                # earlier fault happened to trigger
                hit = self._rng.random() < f["prob"]
                if hit and action is None:
                    action = (f["kind"], f["ms"])
            if self._partitioned(src, peer):
                action = ("drop", 0.0)
                record_fault("partition_frames_dropped")
            elif action is not None:
                record_fault("chaos_" + action[0])
            if len(self.decisions) < self.decisions_cap:
                self.decisions.append(action)
            return action

    # -- step-scheduled PS-server kills ------------------------------------
    def register_server(self, rank, server):
        """A live PS server volunteers as a kill target for ``kill:ps``."""
        with self._lock:
            self._servers[rank] = server

    def register_proc(self, rank, handle):
        """A worker-rank handle volunteers as the kill target for the
        step-clock form ``kill:proc@rank<r>:step<n>`` — anything with a
        ``stop()`` (the elastic harness's
        :class:`~hetu_tpu.parallel.elastic.LogicalRank`; a real
        launcher-side wrapper could hold a Popen).  The wall-clock
        ``after<ms>`` form stays on :meth:`due_proc_kills` (the
        launcher's monitor loop has no step clock)."""
        with self._lock:
            self._procs[rank] = handle

    def register_replica(self, idx, handle):
        """A fleet serving replica volunteers as the kill target for
        ``kill:replica@<idx>:req<n>`` — anything with a ``stop()`` (the
        :class:`~hetu_tpu.serving.fleet.FrontDoor` registers its replica
        records, whose ``stop()`` fail-stops the replica's router at the
        next batch boundary).  The clock is the FRONT DOOR's admission
        count, so the kill lands at a deterministic point in the request
        stream regardless of how dispatch spread earlier requests."""
        with self._lock:
            self._replicas[int(idx)] = handle

    def _resolve_role_kill(self, fault):
        """The registered server currently filling the fault's replica
        role: ``kill_primary`` → the one SERVING the shard, ``kill_backup``
        → one HOLDING it without serving.  Resolved at fire time, so after
        an earlier failover ``kill:primary`` targets the promoted
        ex-backup — the double-failure schedules need exactly that."""
        shard = fault["shard"]
        for rank, srv in sorted(self._servers.items()):
            if getattr(srv, "_stop", False):
                continue
            serves = getattr(srv, "serves", None)
            holds = getattr(srv, "holds", None)
            if serves is None or holds is None:
                continue
            if fault["kind"] == "kill_primary" and serves(shard):
                return rank, srv
            if fault["kind"] == "kill_backup" and holds(shard) \
                    and not serves(shard):
                return rank, srv
        return None, None

    def on_step(self, step):
        """Executor hook: advances the step clock partitions level-
        trigger on (``partition:...@step<n>[:heal<m>]`` activates once
        the clock reaches ``n`` and heals once it reaches ``m``), then
        fires any step-scheduled server kill — ``kill:ps@rank<r>:
        step<s>`` and the replica-role forms ``kill:{primary,backup}@
        shard<s>:step<n>``.

        Returns the list of ranks whose server was stopped (empty almost
        always).  A fault whose target has no registered server is
        LOUD (warning + ``chaos_kill_target_missing`` counter) — a
        schedule that silently does nothing would make a chaos run
        indistinguishable from a clean one.  (Partitions are exempt from
        the one-shot ``_fired`` bookkeeping: they are windows, not
        events, so replaying a step can re-evaluate them without ever
        double-firing a kill.)"""
        killed, missing = [], []
        with self._lock:
            if step > self._now_step:
                self._now_step = step
            for i, f in enumerate(self.faults):
                if i in self._fired or f.get("step") != step \
                        or f["kind"] not in ("kill_ps", "kill_primary",
                                             "kill_backup", "kill_proc"):
                    continue
                self._fired.add(i)
                if f["kind"] == "kill_proc":
                    # step-clock worker-rank kill (elastic harness): the
                    # registered handle's stop() is the fail-stop death
                    handle = self._procs.get(f["rank"])
                    if handle is not None:
                        killed.append((f["rank"], handle,
                                       "chaos_kill_proc"))
                    elif not self._procs:
                        # same quiet/loud split as kill:ps — with OTHER
                        # handles registered the target presumably lives
                        # in a different process and fires there
                        missing.append(f"kill:proc@rank{f['rank']}"
                                       f":step{step}")
                elif f["kind"] == "kill_ps":
                    server = self._servers.get(f["rank"])
                    if server is not None:
                        killed.append((f["rank"], server, "chaos_kill_ps"))
                    elif not self._servers:
                        # no server registered in this process at all: the
                        # schedule cannot possibly fire here — loud.  When
                        # OTHER ranks' servers are registered, the target
                        # lives in a different process (each process hosts
                        # its own rank) and fires there: stay quiet.
                        missing.append(f"kill:ps@rank{f['rank']}"
                                       f":step{step}")
                else:
                    self._collect_role_kill(
                        f, f"kill:{f['kind'][len('kill_'):]}"
                           f"@shard{f['shard']}:step{step}",
                        killed, missing)
        return self._finish_kills(killed, missing)

    def _collect_role_kill(self, f, label, killed, missing):
        """Resolve one already-claimed replica-role fault (caller holds
        the lock): append its victim to ``killed``, or ``label`` to
        ``missing`` when NO server at all is registered in this process
        — the quiet/loud split: with OTHER servers registered the role
        is presumably filled in a different process and fires there."""
        rank, server = self._resolve_role_kill(f)
        if server is not None:
            killed.append((rank, server, "chaos_" + f["kind"]))
        elif not self._servers:
            missing.append(label)

    def _finish_kills(self, killed, missing):
        """Shared tail of every kill clock: loud counter + warning per
        unfillable kill (a chaos run that silently does nothing would be
        indistinguishable from a clean one), then stop each victim
        OUTSIDE the lock — ``stop()`` closes sockets and may block."""
        for what in missing:
            import warnings
            record_fault("chaos_kill_target_missing")
            warnings.warn(f"chaos {what} fired but no registered kill "
                          f"target fills that role (register_server for "
                          f"ps/primary/backup, register_proc for proc) — "
                          f"the kill did NOT happen", RuntimeWarning)
        for rank, server, counter in killed:
            record_fault(counter)
            server.stop()
        return [rank for rank, _, _ in killed]

    # -- request-count-scheduled kills (online serving) --------------------
    def on_request(self, admitted):
        """Serving-router hook: fires any replica-role kill scheduled on
        the ADMISSION clock (``kill:{primary,backup}@shard<s>:req<n>``)
        once ``admitted`` requests have entered the router — the serving
        analogue of :meth:`on_step` (a serving process has no training
        steps to schedule against) — and any fleet replica kill
        (``kill:replica@<idx>:req<n>``, ISSUE 17) once the FRONT DOOR's
        admission count reaches ``n``.  Each fault fires at most once;
        the same quiet/loud split as on_step applies when no registered
        target fills the role (for replica kills: against the
        ``register_replica`` registry).  Replica routers report their
        own smaller admission counts here too — harmless, since a
        replica's count can never exceed the door's, so a fleet-clock
        fault always fires first at the door."""
        killed, missing = [], []
        with self._lock:
            for i, f in enumerate(self.faults):
                if i in self._fired or f.get("req") is None \
                        or admitted < f["req"] \
                        or f["kind"] not in ("kill_primary", "kill_backup",
                                             "kill_replica"):
                    continue
                self._fired.add(i)
                if f["kind"] == "kill_replica":
                    handle = self._replicas.get(f["idx"])
                    if handle is not None:
                        killed.append((f["idx"], handle,
                                       "chaos_kill_replica"))
                    elif not self._replicas:
                        # same quiet/loud split as kill:ps — with OTHER
                        # replicas registered the target presumably
                        # lives behind a different front door
                        missing.append(f"kill:replica@{f['idx']}"
                                       f":req{f['req']}")
                    continue
                self._collect_role_kill(
                    f, f"kill:{f['kind'][len('kill_'):]}"
                       f"@shard{f['shard']}:req{f['req']}",
                    killed, missing)
        return self._finish_kills(killed, missing)

    # -- token-count-scheduled kills (decode serving, ISSUE 19) ------------
    def on_token(self, idx, total):
        """Decode-replica hook: fires ``kill:replica@<idx>:tok<n>`` once
        replica ``idx``'s OWN engine has emitted ``total`` >= n tokens —
        the engine's deterministic token clock, reported by the decode
        router loop after every step.  The door's admission clock cannot
        place a kill MID-GENERATION (dispatch spreads requests across
        replicas, and a request admits long before its tokens flow);
        this clock lands the kill inside a generation at an exact,
        replayable point.  Each fault fires at most once, with no RNG
        draw (transport fault decisions are unperturbed), and the same
        quiet/loud split as :meth:`on_request` applies against the
        ``register_replica`` registry."""
        killed, missing = [], []
        with self._lock:
            for i, f in enumerate(self.faults):
                if i in self._fired or f.get("tok") is None \
                        or f["kind"] != "kill_replica" \
                        or f["idx"] != idx or total < f["tok"]:
                    continue
                self._fired.add(i)
                handle = self._replicas.get(f["idx"])
                if handle is not None:
                    killed.append((f["idx"], handle, "chaos_kill_replica"))
                elif not self._replicas:
                    missing.append(
                        f"kill:replica@{f['idx']}:tok{f['tok']}")
        return self._finish_kills(killed, missing)

    # -- launcher-level child kills ----------------------------------------
    def due_proc_kills(self, elapsed_ms):
        """Ranks whose wall-clock ``kill:proc@rank<r>:after<ms>`` delay
        has elapsed; each fires once.  Step-clock ``:step<n>`` proc
        kills never fire here — they ride :meth:`on_step` against
        ``register_proc``'d handles."""
        due = []
        with self._lock:
            for i, f in enumerate(self.faults):
                if f["kind"] == "kill_proc" and "after_ms" in f \
                        and i not in self._fired \
                        and elapsed_ms >= f["after_ms"]:
                    self._fired.add(i)
                    due.append(f["rank"])
        for _ in due:
            record_fault("chaos_kill_proc")
        return due


# ------------------------------------------------------------- active chaos
_active = None
_active_lock = make_lock("chaos._active_lock")


def active():
    """The process-wide injector, or None (the hot-path check is one
    global read — a clean run pays nothing)."""
    return _active


def install(injector):
    """Make ``injector`` the process-wide schedule; returns the previous
    one so tests can restore it."""
    global _active
    with _active_lock:
        prev, _active = _active, injector
    return prev


def install_from_env(env_var="HETU_CHAOS"):
    """Install a schedule from the environment if one is set; returns the
    injector (or None).  Called by the dist-store and launcher entry
    points so ``HETU_CHAOS=...`` alone activates the harness."""
    inj = ChaosInjector.from_env(env_var)
    if inj is not None:
        install(inj)
    return inj


def uninstall():
    """Remove the process-wide schedule (test teardown)."""
    return install(None)


__all__ = ["ChaosInjector", "ChaosSpecError", "parse_spec", "active",
           "install", "install_from_env", "uninstall"]
