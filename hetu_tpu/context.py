"""Device placement DSL and cluster config.

TPU-native re-design of the reference ``python/hetu/context.py`` (DeviceGroup:19,
ContextStack/ht.context:153-181, DistConfig:284).  On TPU, placement is not
"which CUDA device runs this op's kernel" but "how is this op's data sharded
over a named mesh".  We keep the user-facing surface (``ht.context(...)``,
``DeviceGroup``, ``ht.gpu(i)``/``ht.cpu(i)``) and map it onto
``jax.sharding.Mesh`` + ``PartitionSpec``.

Standard mesh axes (SURVEY.md §7 design mapping):
    ``dp``  – data parallel        ``tp`` – tensor parallel
    ``pp``  – pipeline stages      ``ep`` – expert parallel
    ``cp``  – context/sequence parallel
"""
from __future__ import annotations

import contextlib

import numpy as np


class DLContext:
    """A single logical device. Parity shim for ``ht.gpu(i)`` / ``ht.cpu(i)``.

    On TPU we interpret device indices as positions in the flat device list;
    'cpu' marks host-resident placement (embedding tables, dataloaders).
    """

    def __init__(self, device_type: str, device_id: int = 0, hostname: str = "localhost"):
        self.device_type = device_type  # 'cpu' | 'gpu' | 'tpu'
        self.device_id = device_id
        self.hostname = hostname

    @property
    def is_host(self):
        return self.device_type == "cpu"

    def __eq__(self, other):
        return (isinstance(other, DLContext)
                and (self.device_type, self.device_id, self.hostname)
                == (other.device_type, other.device_id, other.hostname))

    def __hash__(self):
        return hash((self.device_type, self.device_id, self.hostname))

    def __repr__(self):
        return f"{self.hostname}:{self.device_type}:{self.device_id}"


def cpu(device_id: int = 0):
    return DLContext("cpu", device_id)


def gpu(device_id: int = 0):
    # On this framework "gpu" means "accelerator chip" — kept for API parity
    # with reference model scripts; maps to TPU device index.
    return DLContext("tpu", device_id)


def tpu(device_id: int = 0):
    return DLContext("tpu", device_id)


def rcpu(hostname, device_id=0):
    return DLContext("cpu", device_id, hostname)


def rgpu(hostname, device_id=0):
    return DLContext("tpu", device_id, hostname)


class DeviceGroup:
    """An ordered group of devices an op (or stage) is placed on.

    Reference: ``context.py:19``. Accepts contexts, strings like
    ``'gpu:0'``/``'cpu:0'``/``'node1:gpu:3'``, and tuples (a tuple = one
    model-parallel unit spanning several devices).
    """

    def __init__(self, ctxs):
        if not isinstance(ctxs, (list, tuple)):
            ctxs = [ctxs]
        self._contexts = [self._parse(c) for c in ctxs]

    @staticmethod
    def _parse(c):
        if isinstance(c, DLContext):
            return c
        if isinstance(c, tuple):
            return tuple(DeviceGroup._parse(x) for x in c)
        if isinstance(c, str):
            parts = c.split(":")
            if len(parts) == 2:
                dtype, idx = parts
                host = "localhost"
            elif len(parts) == 3:
                host, dtype, idx = parts
            else:
                raise ValueError(f"cannot parse device string {c!r}")
            dtype = "tpu" if dtype == "gpu" else dtype
            return DLContext(dtype, int(idx), host)
        raise TypeError(f"bad context spec: {c!r}")

    @property
    def contexts(self):
        return self._contexts

    @property
    def worker_num(self):
        return len(self._contexts)

    def flat_device_ids(self):
        out = []
        for c in self._contexts:
            if isinstance(c, tuple):
                out.extend(x.device_id for x in c)
            elif not c.is_host:
                out.append(c.device_id)
        return out

    def __len__(self):
        return len(self._contexts)

    def __iter__(self):
        return iter(self._contexts)

    def __getitem__(self, i):
        return self._contexts[i]

    def __eq__(self, other):
        return isinstance(other, DeviceGroup) and self._contexts == other._contexts

    def __hash__(self):
        return hash(tuple(self._contexts))

    def __repr__(self):
        return f"DeviceGroup({self._contexts})"


class _ContextStack:
    def __init__(self):
        self._stack = []

    def peek(self):
        return self._stack[-1] if self._stack else None

    def push(self, ctx):
        self._stack.append(ctx)

    def pop(self):
        self._stack.pop()


_ctx_stack = _ContextStack()


def current_context():
    return _ctx_stack.peek()


#: reference name (context.py:170) — same function
get_current_context = current_context


@contextlib.contextmanager
def context(ctx):
    """``with ht.context(ht.gpu(0)):`` placement scope (reference context.py:174)."""
    if not isinstance(ctx, DeviceGroup):
        ctx = DeviceGroup(ctx)
    _ctx_stack.push(ctx)
    try:
        yield ctx
    finally:
        _ctx_stack.pop()


# ---------------------------------------------------------------------------
# Mesh helpers
# ---------------------------------------------------------------------------

MESH_AXES = ("dp", "pp", "tp", "ep", "cp")


def make_mesh(axis_sizes=None, devices=None, dcn_axes=None):
    """Build a ``jax.sharding.Mesh`` with named axes.

    ``axis_sizes``: dict like {'dp': 4, 'tp': 2}; unmentioned axes get size 1
    and are dropped. If None, all devices go on 'dp'.

    ``dcn_axes``: DCN-aware hybrid placement for multi-slice topologies
    (SURVEY.md §5.8; reference analogue: the HAllToAll intra/inter-node
    split, ``mpi_nccl_communication.cu:396``).  A dict ``{axis: n_slices}``
    declaring how much of each axis spans the slow (DCN) interconnect; the
    remaining factor of that axis stays on ICI.  E.g. 16 devices over 2
    slices with ``{'dp': 4, 'tp': 4}, dcn_axes={'dp': 2}`` puts the tp
    groups and half of dp inside each slice and crosses DCN only along the
    outer dp dimension — gradient allreduce hierarchically decomposes so
    only 1/4 of its traffic rides DCN.  On real multi-slice TPU the device
    assignment comes from ``mesh_utils.create_hybrid_device_mesh``; on flat
    (single-slice / CPU-simulated) topologies contiguous device blocks act
    as virtual slices so the SAME program shape is testable anywhere.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = {"dp": n}
    names, sizes = [], []
    # canonical axes first (stable order), then any custom axes
    # (e.g. 'row' for the 1.5D GCN partition)
    ordered = [ax for ax in MESH_AXES if ax in axis_sizes] + \
        [ax for ax in axis_sizes if ax not in MESH_AXES]
    for ax in ordered:
        s = int(axis_sizes.get(ax, 1))
        if s >= 1:
            names.append(ax)
            sizes.append(s)
    total = int(np.prod(sizes)) if sizes else 1
    if total > n:
        raise ValueError(f"mesh axes {dict(zip(names, sizes))} need {total} "
                         f"devices, got {n}")
    if total < n:  # use a subset (reference DeviceGroup picks GPUs the same way)
        import warnings
        warnings.warn(
            f"mesh axes {dict(zip(names, sizes))} use {total} of {n} "
            f"devices; {n - total} devices are left idle")
        devices = list(devices)[:total]
    if dcn_axes:
        dev_array = _hybrid_device_array(names, sizes, dict(dcn_axes),
                                         list(devices))
        return Mesh(dev_array, tuple(names))
    dev_array = np.asarray(devices).reshape(sizes if sizes else (1,))
    return Mesh(dev_array, tuple(names) if names else ("dp",))


def _hybrid_device_array(names, sizes, dcn_axes, devices):
    """Device array for a 2-level (ICI x DCN) mesh — see ``make_mesh``."""
    unknown = set(dcn_axes) - set(names)
    if unknown:
        raise ValueError(f"dcn_axes {sorted(unknown)} not in mesh axes "
                         f"{names}")
    dcn_sizes = [int(dcn_axes.get(ax, 1)) for ax in names]
    for ax, sz, d in zip(names, sizes, dcn_sizes):
        if d < 1 or sz % d:
            raise ValueError(
                f"dcn factor {d} must divide axis {ax!r} size {sz}")
    ici_sizes = [sz // d for sz, d in zip(sizes, dcn_sizes)]
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if len(slice_ids) > 1 and None not in slice_ids:
        # real multi-slice topology: let jax match slices to DCN dims
        from jax.experimental import mesh_utils
        return mesh_utils.create_hybrid_device_mesh(
            ici_sizes, dcn_sizes, devices, allow_split_physical_axes=True)
    # flat topology (one slice, or the CPU-simulated mesh): contiguous
    # device blocks play the role of slices, so each ICI group is a
    # contiguous run — the layout multi-process CPU meshes give per host
    k = len(names)
    arr = np.asarray(devices).reshape(tuple(dcn_sizes) + tuple(ici_sizes))
    perm = [i for j in range(k) for i in (j, j + k)]   # d1,s1,d2,s2,...
    return arr.transpose(perm).reshape(sizes)


class DistConfig:
    """Cluster spec loaded from yaml (reference ``context.py:284``).

    On TPU pods the runtime discovers topology itself
    (``jax.distributed.initialize``); the yaml is kept for launcher parity and
    for multi-slice (DCN) descriptions.
    """

    def __init__(self, file=None, num_hosts=1, hosts=None):
        self.hosts = hosts or ["localhost"]
        self.num_hosts = num_hosts
        if file is not None:
            import yaml
            with open(file) as f:
                spec = yaml.safe_load(f)
            nodes = spec.get("nodes", [])
            self.hosts = [n.get("host", "localhost") for n in nodes] or self.hosts
            self.num_hosts = len(self.hosts)
        self.chief = self.hosts[0]

    def __repr__(self):
        return f"DistConfig(hosts={self.hosts})"
