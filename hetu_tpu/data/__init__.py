from .dataloader import Dataloader, DataloaderOp, GNNDataLoaderOp, dataloader_op
from .datasets import (mnist, cifar10, cifar100, normalize_cifar,
                       imagenet, ImageNetFolder, convert_to_one_hot)
from . import transforms
from .transforms import (Compose, Normalize, RandomHorizontalFlip,
                         RandomCrop, Resize, CenterCrop)
