"""Data pipeline (reference ``python/hetu/dataloader.py``: Dataloader:84 with
triple-buffer prefetch + dp sharding, DataloaderOp:259 multi-split).

TPU-native: the loader hands the executor one GLOBAL batch per step; under a
DataParallel mesh the executor ``device_put``s it with a 'dp' PartitionSpec so
each chip receives its shard via async host→device transfer (the reference
instead had each MPI rank slice by ``dp_rank``, dataloader.py:96-101).
Prefetch = simple lookahead queue; XLA's async dispatch overlaps the copy
with the previous step's compute.
"""
from __future__ import annotations

import numpy as np

from ..graph.node import PlaceholderOp


class Dataloader:
    """One split of data batched for one subgraph name.

    ``dp_rank``/``dp_nrank`` shard the dataset across data-parallel workers
    (reference dataloader.py:96-101); ``prefetch`` batches are prepared on a
    background thread (the reference's triple-buffer queue:103) so host-side
    augmentation overlaps device compute.
    """

    def __init__(self, raw_data, batch_size, name="default", func=None,
                 drop_last=True, shuffle=False, seed=0,
                 dp_rank=0, dp_nrank=1, prefetch=2):
        data = np.asarray(raw_data, np.float32)
        if dp_nrank > 1:  # contiguous shard per dp worker
            per = len(data) // dp_nrank
            data = data[dp_rank * per:(dp_rank + 1) * per]
        self.raw_data = data
        self.batch_size = int(batch_size)
        self.name = name
        self.func = func
        self.drop_last = drop_last
        self.shuffle = shuffle
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        self._order = np.arange(len(self.raw_data))
        self._cursor = 0
        if shuffle:
            self._rng.shuffle(self._order)
        self._queue = None
        self._prefetch = max(0, int(prefetch))
        self._consumed = 0        # batches handed to the consumer (resume pt)
        self._gen = 0             # bumped by load_state to retire producers
        from ..obs.lock_witness import make_lock
        self._plock = make_lock("Dataloader._plock")

    @property
    def batch_num(self):
        n = len(self.raw_data) // self.batch_size
        if not self.drop_last and len(self.raw_data) % self.batch_size:
            n += 1
        return n

    def _advance_unlocked(self):
        idx = self._order[self._cursor * self.batch_size:
                          (self._cursor + 1) * self.batch_size]
        batch = self.raw_data[idx]
        self._cursor += 1
        if self._cursor >= self.batch_num:
            self._cursor = 0
            if self.shuffle:
                self._rng.shuffle(self._order)
        return batch

    def _produce(self):
        with self._plock:
            batch = self._advance_unlocked()
        if self.func is not None:
            batch = self.func(batch)
        return batch

    def _ensure_thread(self):
        if self._queue is not None or self._prefetch == 0:
            return
        import queue
        import threading
        self._queue = queue.Queue(maxsize=self._prefetch)

        def worker(q=self._queue, gen=self._gen):
            import queue as _q
            while True:
                # generation check and cursor advance are ATOMIC: a retired
                # producer (load_state bumped _gen) must not touch the
                # restored cursor/order/rng
                with self._plock:
                    if self._gen != gen:
                        return
                    batch = self._advance_unlocked()
                if self.func is not None:
                    batch = self.func(batch)
                while self._gen == gen:
                    try:
                        q.put(batch, timeout=0.25)
                        break
                    except _q.Full:
                        continue
                if self._gen != gen:
                    return

        t = threading.Thread(target=worker, daemon=True)
        t.start()

    def _take(self):
        if self._prefetch:
            self._ensure_thread()
            return self._queue.get()
        return self._produce()

    def get_arr(self):
        self._consumed += 1
        if getattr(self, "_peeked", None) is not None:
            batch, self._peeked = self._peeked, None
            return batch
        return self._take()

    # -- checkpointable position (resume at the exact next batch) ----------
    def state_dict(self):
        """Resume point: how many batches the CONSUMER has taken.  Batches
        sitting prefetched in the queue/peek are not counted — they are
        regenerated after restore (``func`` reruns on them; a stateful
        func's side effects replay).  Batching geometry is recorded so a
        restore into a DIFFERENTLY-batched loader fails loudly instead of
        resuming at a silently wrong data position."""
        return {"consumed": int(self._consumed), "seed": self._seed,
                "shuffle": bool(self.shuffle),
                "batch_size": self.batch_size,
                "drop_last": bool(self.drop_last),
                "n_rows": int(len(self.raw_data))}

    def load_state(self, state):
        """Rewind to a saved position: re-derive order/rng from the SAVED
        seed/shuffle (the live seed may differ — exact resume must follow
        the checkpoint) and fast-forward ``consumed`` batches without
        materialising them (one shuffle per completed epoch)."""
        for field, live in (("batch_size", self.batch_size),
                            ("drop_last", bool(self.drop_last)),
                            ("n_rows", int(len(self.raw_data)))):
            saved = state.get(field)
            if saved is not None and saved != live:
                raise ValueError(
                    f"dataloader '{self.name}' cannot resume: checkpoint "
                    f"{field}={saved} != live {field}={live} (the saved "
                    f"position is meaningless under different batching)")
        with self._plock:
            self._gen += 1              # retires any live prefetch thread
            self._queue = None
            self._peeked = None
            self._seed = state.get("seed", self._seed)
            self.shuffle = bool(state.get("shuffle", self.shuffle))
            self._rng = np.random.RandomState(self._seed)
            self._order = np.arange(len(self.raw_data))
            if self.shuffle:
                self._rng.shuffle(self._order)
            n = int(state["consumed"])
            epochs, self._cursor = divmod(n, self.batch_num)
            if self.shuffle:            # replay completed epochs' shuffles
                for _ in range(epochs):
                    self._rng.shuffle(self._order)
            self._consumed = n

    def get_next_arr(self):
        """Peek the upcoming batch without consuming it (reference lookahead
        used for PS SparsePull prefetch, ParameterServerCommunicate.py:69-77)."""
        if getattr(self, "_peeked", None) is None:
            self._peeked = self._take()
        return self._peeked

    def get_cur_shape(self):
        return (self.batch_size,) + self.raw_data.shape[1:]


class DataloaderOp(PlaceholderOp):
    """Graph input fed from per-subgraph Dataloaders (reference :259)."""

    op_type = "DataloaderOp"

    def __init__(self, dataloaders, name=None):
        super().__init__(name or "dataloader")
        self.dataloaders = {dl.name: dl for dl in dataloaders}

    def get_batch_num(self, name):
        return self.dataloaders[name].batch_num

    def get_arr(self, name):
        return self.dataloaders[name].get_arr()

    def get_next_arr(self, name):
        return self.dataloaders[name].get_next_arr()

    def get_cur_shape(self, name):
        return self.dataloaders[name].get_cur_shape()


def dataloader_op(dataloaders, name=None):
    """``ht.dataloader_op([ht.Dataloader(x, bs, 'train'), ...])`` parity."""
    dls = []
    for d in dataloaders:
        if isinstance(d, Dataloader):
            dls.append(d)
        else:  # [raw_data, batch_size, name?, func?] list form
            dls.append(Dataloader(*d))
    return DataloaderOp(dls, name=name)


class GNNDataLoaderOp(PlaceholderOp):
    """Graph-minibatch loader (reference :220) — host-side graph sampling
    feeding dense blocks; ping-pong buffering is XLA-async here."""

    op_type = "GNNDataloaderOp"

    def __init__(self, handler, name=None):
        super().__init__(name or "gnn_dataloader")
        self.handler = handler
        self._next = None

    def step(self, graph):
        self._next = self.handler(graph)

    def get_arr(self, name):
        return self._next
