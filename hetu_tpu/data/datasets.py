"""Dataset loaders (reference ``python/hetu/data.py`` — MNIST/CIFAR/ImageNet).

Looks for on-disk datasets under ``$HETU_DATA_DIR`` (mnist.npz /
cifar10 npy files); when absent, generates a deterministic synthetic set with
the same shapes/dtypes so tests and benchmarks run hermetically (this repo
has no network egress).
"""
from __future__ import annotations

import os

import numpy as np

def _data_dir():
    """Resolved per call so tests/fixture generators can point
    ``HETU_DATA_DIR`` at a tmp dir after import."""
    return os.environ.get("HETU_DATA_DIR",
                          os.path.expanduser("~/.hetu/data"))


def _synthetic(n, shape, num_class, seed):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, *shape).astype(np.float32)
    labels = rng.randint(0, num_class, size=n)
    y = np.zeros((n, num_class), np.float32)
    y[np.arange(n), labels] = 1.0
    return x, y


def mnist(onehot=True):
    """Returns [(train_x, train_y), (valid_x, valid_y), (test_x, test_y)],
    x: (N, 784) float32 in [0,1], y: (N, 10) one-hot (reference layout)."""
    path = os.path.join(_data_dir(), "mnist.npz")
    if os.path.exists(path):
        with np.load(path) as d:
            xs = d["x_train"].reshape(-1, 784).astype(np.float32) / 255.0
            ys = np.eye(10, dtype=np.float32)[d["y_train"]]
            xt = d["x_test"].reshape(-1, 784).astype(np.float32) / 255.0
            yt = np.eye(10, dtype=np.float32)[d["y_test"]]
        # standard MNIST: 50k train / 10k valid; smaller real sets (e.g.
        # the UCI digits fixture) split 5/6 so the valid split is never empty
        n_tr = min(50000, len(xs) * 5 // 6)
        return [(xs[:n_tr], ys[:n_tr]), (xs[n_tr:], ys[n_tr:]), (xt, yt)]
    tx, ty = _synthetic(8192, (784,), 10, 0)
    vx, vy = _synthetic(1024, (784,), 10, 1)
    sx, sy = _synthetic(1024, (784,), 10, 2)
    return [(tx, ty), (vx, vy), (sx, sy)]


def normalize_cifar(num_class=10):
    """train_x (N,3,32,32) normalized, train_y one-hot; reference data.py."""
    path = os.path.join(_data_dir(), f"cifar{num_class}")
    if os.path.isdir(path):
        tx = np.load(os.path.join(path, "train_x.npy"))
        ty = np.load(os.path.join(path, "train_y.npy"))
        vx = np.load(os.path.join(path, "test_x.npy"))
        vy = np.load(os.path.join(path, "test_y.npy"))
        mean = tx.mean(axis=(0, 2, 3), keepdims=True)
        std = tx.std(axis=(0, 2, 3), keepdims=True)
        tx = (tx - mean) / std
        vx = (vx - mean) / std
        if ty.ndim == 1:
            ty = np.eye(num_class, dtype=np.float32)[ty]
            vy = np.eye(num_class, dtype=np.float32)[vy]
        return tx.astype(np.float32), ty, vx.astype(np.float32), vy
    tx, ty = _synthetic(8192, (3, 32, 32), num_class, 0)
    vx, vy = _synthetic(1024, (3, 32, 32), num_class, 1)
    return tx, ty, vx, vy


def cifar10():
    return normalize_cifar(10)


def cifar100():
    return normalize_cifar(100)


IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


class ImageNetFolder:
    """Streaming ImageNet-layout loader (reference ``data.py`` ImageNet
    path): a root with one subdirectory per class, JPEG/PNG files inside.

    Decodes lazily with PIL batch-by-batch (the full dataset never fits in
    RAM), resize-shorter-side→center-crop→normalize, NCHW float32.  When
    the directory is absent, yields a deterministic synthetic stream with
    identical shapes so examples run hermetically.
    """

    def __init__(self, root=None, split="train", image_size=224,
                 num_classes=1000, synthetic_batches=8, batch_size=32,
                 shuffle=True, seed=0):
        self.image_size = image_size
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0
        explicit_root = root is not None
        root = root or os.path.join(_data_dir(), "imagenet", split)
        self.samples = []      # (path, class_index)
        self.classes = []
        if os.path.isdir(root):
            self.classes = sorted(
                d for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d)))
            for ci, cname in enumerate(self.classes):
                cdir = os.path.join(root, cname)
                for f in sorted(os.listdir(cdir)):
                    if f.lower().endswith((".jpeg", ".jpg", ".png")):
                        self.samples.append((os.path.join(cdir, f), ci))
            if explicit_root and not self.samples:
                raise ValueError(
                    f"{root} exists but holds no class-dir/JPEG-or-PNG "
                    "layout images — refusing to silently substitute "
                    "synthetic data for an explicit root")
            if self.samples and len(self.samples) < batch_size:
                raise ValueError(
                    f"{len(self.samples)} images < batch_size {batch_size}:"
                    " the drop-remainder loader would yield zero batches")
        self.num_classes = len(self.classes) or num_classes
        self._synthetic_batches = synthetic_batches

    def __len__(self):
        if self.samples:
            return len(self.samples) // self.batch_size
        return self._synthetic_batches

    def _decode(self, path):
        from PIL import Image
        s = self.image_size
        img = Image.open(path).convert("RGB")
        w, h = img.size
        scale = s / min(w, h)
        img = img.resize((max(s, round(w * scale)),
                          max(s, round(h * scale))), Image.BILINEAR)
        w, h = img.size
        left, top = (w - s) // 2, (h - s) // 2
        img = img.crop((left, top, left + s, top + s))
        x = np.asarray(img, np.float32) / 255.0          # (H, W, C)
        x = (x - IMAGENET_MEAN) / IMAGENET_STD
        return x.transpose(2, 0, 1)                      # (C, H, W)

    def __iter__(self):
        """Yields (images (B, 3, S, S) float32, labels (B,) int32)."""
        s = self.image_size
        if not self.samples:
            rng = np.random.RandomState(self.seed)
            for _ in range(self._synthetic_batches):
                x = rng.rand(self.batch_size, 3, s, s).astype(np.float32)
                y = rng.randint(0, self.num_classes,
                                self.batch_size).astype(np.int32)
                yield x, y
            return
        order = np.arange(len(self.samples))
        if self.shuffle:
            # fold the epoch counter in so every pass reshuffles
            np.random.RandomState(self.seed + self._epoch).shuffle(order)
        self._epoch += 1
        for b in range(len(self)):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            xs = np.stack([self._decode(self.samples[i][0]) for i in idx])
            ys = np.asarray([self.samples[i][1] for i in idx], np.int32)
            yield xs, ys


def imagenet(root=None, image_size=224, batch_size=32, **kw):
    """(train_iter, val_iter) ImageNet loaders (see :class:`ImageNetFolder`).

    ``shuffle`` (if given) applies to the train split; val never shuffles.
    """
    kw.pop("split", None)
    train_shuffle = kw.pop("shuffle", True)
    # an explicit root is the dataset PARENT (containing train/ and val/)
    tr = os.path.join(root, "train") if root else None
    va = os.path.join(root, "val") if root else None
    return (ImageNetFolder(tr, "train", image_size,
                           batch_size=batch_size, shuffle=train_shuffle,
                           **kw),
            ImageNetFolder(va, "val", image_size, batch_size=batch_size,
                           shuffle=False, **kw))


def convert_to_one_hot(vals, max_val=0):
    """Label array → one-hot float32 (reference ``data.py:226`` — used
    across its example mains)."""
    vals = np.asarray(vals).astype(np.int64).reshape(-1)
    if max_val == 0:
        max_val = int(vals.max()) + 1
    out = np.zeros((vals.size, max_val), np.float32)
    out[np.arange(vals.size), vals] = 1.0
    return out
