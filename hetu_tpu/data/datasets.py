"""Dataset loaders (reference ``python/hetu/data.py`` — MNIST/CIFAR/ImageNet).

Looks for on-disk datasets under ``$HETU_DATA_DIR`` (mnist.npz /
cifar10 npy files); when absent, generates a deterministic synthetic set with
the same shapes/dtypes so tests and benchmarks run hermetically (this repo
has no network egress).
"""
from __future__ import annotations

import os

import numpy as np

DATA_DIR = os.environ.get("HETU_DATA_DIR", os.path.expanduser("~/.hetu/data"))


def _synthetic(n, shape, num_class, seed):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, *shape).astype(np.float32)
    labels = rng.randint(0, num_class, size=n)
    y = np.zeros((n, num_class), np.float32)
    y[np.arange(n), labels] = 1.0
    return x, y


def mnist(onehot=True):
    """Returns [(train_x, train_y), (valid_x, valid_y), (test_x, test_y)],
    x: (N, 784) float32 in [0,1], y: (N, 10) one-hot (reference layout)."""
    path = os.path.join(DATA_DIR, "mnist.npz")
    if os.path.exists(path):
        with np.load(path) as d:
            xs = d["x_train"].reshape(-1, 784).astype(np.float32) / 255.0
            ys = np.eye(10, dtype=np.float32)[d["y_train"]]
            xt = d["x_test"].reshape(-1, 784).astype(np.float32) / 255.0
            yt = np.eye(10, dtype=np.float32)[d["y_test"]]
        return [(xs[:50000], ys[:50000]), (xs[50000:], ys[50000:]), (xt, yt)]
    tx, ty = _synthetic(8192, (784,), 10, 0)
    vx, vy = _synthetic(1024, (784,), 10, 1)
    sx, sy = _synthetic(1024, (784,), 10, 2)
    return [(tx, ty), (vx, vy), (sx, sy)]


def normalize_cifar(num_class=10):
    """train_x (N,3,32,32) normalized, train_y one-hot; reference data.py."""
    path = os.path.join(DATA_DIR, f"cifar{num_class}")
    if os.path.isdir(path):
        tx = np.load(os.path.join(path, "train_x.npy"))
        ty = np.load(os.path.join(path, "train_y.npy"))
        vx = np.load(os.path.join(path, "test_x.npy"))
        vy = np.load(os.path.join(path, "test_y.npy"))
        mean = tx.mean(axis=(0, 2, 3), keepdims=True)
        std = tx.std(axis=(0, 2, 3), keepdims=True)
        tx = (tx - mean) / std
        vx = (vx - mean) / std
        if ty.ndim == 1:
            ty = np.eye(num_class, dtype=np.float32)[ty]
            vy = np.eye(num_class, dtype=np.float32)[vy]
        return tx.astype(np.float32), ty, vx.astype(np.float32), vy
    tx, ty = _synthetic(8192, (3, 32, 32), num_class, 0)
    vx, vy = _synthetic(1024, (3, 32, 32), num_class, 1)
    return tx, ty, vx, vy


def cifar10():
    return normalize_cifar(10)


def cifar100():
    return normalize_cifar(100)
