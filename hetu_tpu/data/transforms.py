"""Host-side data transforms (reference ``python/hetu/transforms.py``).

Numpy-batch functions composable via :class:`Compose` and passable as the
``func=`` of :class:`hetu_tpu.data.Dataloader` — they run on the prefetch
thread, overlapping device compute.
"""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, batch):
        for t in self.transforms:
            batch = t(batch)
        return batch


class Normalize:
    """(x - mean) / std per channel (NCHW or flat)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, batch):
        if batch.ndim == 4:  # NCHW
            m = self.mean.reshape(1, -1, 1, 1)
            s = self.std.reshape(1, -1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (batch - m) / s


class RandomHorizontalFlip:
    def __init__(self, p=0.5, seed=0):
        self.p = p
        self._rng = np.random.RandomState(seed)

    def __call__(self, batch):
        flip = self._rng.rand(len(batch)) < self.p
        out = batch.copy()
        out[flip] = out[flip, ..., ::-1]
        return out


class RandomCrop:
    """Pad-and-crop augmentation (NCHW)."""

    def __init__(self, size, padding=4, seed=0):
        self.size = size
        self.padding = padding
        self._rng = np.random.RandomState(seed)

    def __call__(self, batch):
        n, c, h, w = batch.shape
        p = self.padding
        padded = np.pad(batch, ((0, 0), (0, 0), (p, p), (p, p)))
        out = np.empty((n, c, self.size, self.size), batch.dtype)
        ys = self._rng.randint(0, h + 2 * p - self.size + 1, n)
        xs = self._rng.randint(0, w + 2 * p - self.size + 1, n)
        for i in range(n):
            out[i] = padded[i, :, ys[i]:ys[i] + self.size,
                            xs[i]:xs[i] + self.size]
        return out


class Cutout:
    def __init__(self, length=8, seed=0):
        self.length = length
        self._rng = np.random.RandomState(seed)

    def __call__(self, batch):
        n, _, h, w = batch.shape
        out = batch.copy()
        ys = self._rng.randint(0, h, n)
        xs = self._rng.randint(0, w, n)
        half = self.length // 2
        for i in range(n):
            y0, y1 = max(0, ys[i] - half), min(h, ys[i] + half)
            x0, x1 = max(0, xs[i] - half), min(w, xs[i] + half)
            out[i, :, y0:y1, x0:x1] = 0.0
        return out


__all__ = ["Compose", "Normalize", "RandomHorizontalFlip", "RandomCrop",
           "Cutout", "Resize", "CenterCrop"]


class Resize:
    """Resize an NCHW batch to ``size`` (int or (H, W)) — reference
    ``transforms.py:13`` (PIL bilinear), vectorised numpy (no per-image
    PIL round-trip).  PIL area-weights over the full source footprint on
    downscale (antialias); a plain 2-tap bilinear would alias past 2×
    reduction, so heavier downscales box-prefilter by 2× halvings (the
    mipmap construction) until within bilinear range."""

    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    @staticmethod
    def _halve(batch, axis):
        n = batch.shape[axis]
        if n % 2:   # drop the trailing odd row/col (size-preserving
            batch = np.take(batch, range(n - 1), axis=axis)  # enough here)
        sl0 = [slice(None)] * batch.ndim
        sl1 = [slice(None)] * batch.ndim
        sl0[axis] = slice(0, None, 2)
        sl1[axis] = slice(1, None, 2)
        return (batch[tuple(sl0)].astype(np.float32)
                + batch[tuple(sl1)]) * 0.5

    def __call__(self, batch):
        oh, ow = self.size
        if (oh, ow) == batch.shape[2:]:
            return np.array(batch, copy=True)   # uniform fresh-array
        dt = batch.dtype                        # contract (see CenterCrop)
        work = batch
        while work.shape[2] >= 2 * oh and work.shape[2] >= 4:
            work = self._halve(work, 2)
        while work.shape[3] >= 2 * ow and work.shape[3] >= 4:
            work = self._halve(work, 3)
        n, c, h, w = work.shape
        ys = (np.arange(oh) + 0.5) * h / oh - 0.5
        xs = (np.arange(ow) + 0.5) * w / ow - 0.5
        y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = np.clip(ys - y0, 0.0, 1.0).astype(np.float32)
        wx = np.clip(xs - x0, 0.0, 1.0).astype(np.float32)
        rows0 = work[:, :, y0]       # hoisted: one gather per source row
        rows1 = work[:, :, y1]
        top = rows0[..., x0] * (1 - wx) + rows0[..., x1] * wx
        bot = rows1[..., x0] * (1 - wx) + rows1[..., x1] * wx
        out = top * (1 - wy[:, None]) + bot * wy[:, None]
        if np.issubdtype(dt, np.integer):
            out = np.rint(out)       # PIL rounds; truncation would darken
        return out.astype(dt)


class CenterCrop:
    """Center-crop an NCHW batch to ``size`` (reference
    ``transforms.py:22``); pads with zeros when the target exceeds the
    input, matching the reference's behavior for small images."""

    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, batch):
        n, c, h, w = batch.shape
        th, tw = self.size
        if th > h or tw > w:
            out = np.zeros((n, c, max(th, h), max(tw, w)), batch.dtype)
            out[:, :, (out.shape[2] - h) // 2:(out.shape[2] - h) // 2 + h,
                (out.shape[3] - w) // 2:(out.shape[3] - w) // 2 + w] = batch
            batch = out
            n, c, h, w = batch.shape
        i = (h - th) // 2
        j = (w - tw) // 2
        # fresh contiguous array, not a view: transforms run on the
        # dataloader prefetch thread and a view would alias the cached
        # dataset (and pin the uncropped parent buffer)
        return np.ascontiguousarray(batch[:, :, i:i + th, j:j + tw])
