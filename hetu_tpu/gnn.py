"""GNN support: sparse matmul ops + 1.5D-distributed GCN.

Reference parity:
* CuSparse kernels ``src/ops/CuSparseCsrmv.cu`` / ``CuSparseCsrmm.cu`` →
  :func:`csrmv_op` / :func:`csrmm_op` (COO/segment-sum form — gather +
  ``segment_sum`` is the TPU-native SpMM: static shapes, MXU-friendly
  dense feature blocks, no dynamic CSR walks);
* ``python/hetu/gpu_ops/DistGCN_15d.py:73`` (1.5D-partitioned GCN with
  row-broadcast groups) → :class:`DistGCN15D` — node rows sharded over a
  mesh axis, features all-gathered within the row group (the reference's
  ``broad_func`` NCCL broadcast:19), local COO aggregation per shard.
"""
from __future__ import annotations

import numpy as np

from .graph.node import Op
from .ops.base import def_op


# -- sparse matmul (COO edge-list form) --------------------------------------

def _spmm(c, values, rows, cols, dense, num_rows=None):
    """out[r] = sum_e values[e] * dense[cols[e]]  for edges e with rows[e]=r."""
    import jax
    import jax.numpy as jnp
    if num_rows is None:
        raise ValueError("csrmm_op/csrmv_op need num_rows= (static output "
                         "row count; it cannot be inferred under jit)")
    gathered = dense[cols.astype(jnp.int32)] * values[:, None]
    return jax.ops.segment_sum(gathered, rows.astype(jnp.int32),
                               num_segments=num_rows)


csrmm_op = def_op("CuSparseCsrmm", _spmm)


def _spmv(c, values, rows, cols, vec, num_rows=None):
    import jax
    import jax.numpy as jnp
    if num_rows is None:
        raise ValueError("csrmv_op needs num_rows= (static output row "
                         "count; it cannot be inferred under jit)")
    gathered = vec[cols.astype(jnp.int32)] * values
    return jax.ops.segment_sum(gathered, rows.astype(jnp.int32),
                               num_segments=num_rows)


csrmv_op = def_op("CuSparseCsrmv", _spmv)


def normalized_adjacency(edges, num_nodes, add_self_loops=True):
    """Symmetric-normalized GCN adjacency as COO arrays (host-side prep).

    ``edges``: (E, 2) int array of (src, dst). Returns (values, rows, cols)
    with values = 1/sqrt(deg[dst]*deg[src]).
    """
    edges = np.asarray(edges, np.int64)
    if add_self_loops:
        loops = np.stack([np.arange(num_nodes)] * 2, axis=1)
        edges = np.concatenate([edges, loops], axis=0)
    src, dst = edges[:, 0], edges[:, 1]
    deg = np.bincount(dst, minlength=num_nodes).astype(np.float32)
    deg_src = np.bincount(src, minlength=num_nodes).astype(np.float32)
    vals = 1.0 / np.sqrt(np.maximum(deg[dst], 1) * np.maximum(deg_src[src], 1))
    return vals.astype(np.float32), dst.astype(np.int32), src.astype(np.int32)


# -- distributed 1.5D GCN ----------------------------------------------------

class GCNAggregateOp(Op):
    """Row-sharded neighbor aggregation over a mesh axis.

    SPMD program per device (via shard_map when a mesh axis is given):
    all-gather the feature rows within the row group (reference broadcast),
    then segment-sum the LOCAL edge block — edges are pre-partitioned by
    destination row so each device owns the edges that produce its rows.
    """

    op_type = "GCNAggregate"

    def __init__(self, values, rows, cols, x, num_nodes, axis=None,
                 name=None):
        super().__init__([values, rows, cols, x], name=name)
        self.num_nodes = int(num_nodes)
        self.axis = axis

    def infer_shape(self, shapes):
        return (self.num_nodes,) + tuple(shapes[3][1:])

    def lower(self, ctx, values, rows, cols, x):
        import jax
        import jax.numpy as jnp
        mesh = ctx.mesh if self.axis else None
        if mesh is None or self.axis not in getattr(mesh, "axis_names", ()):
            return _spmm(ctx, values, rows, cols, x,
                         num_rows=self.num_nodes)

        from jax.sharding import PartitionSpec as P
        n_shard = mesh.shape[self.axis]
        if self.num_nodes % n_shard:
            raise ValueError(
                f"num_nodes={self.num_nodes} must divide by the "
                f"'{self.axis}' mesh width {n_shard}; pad the node set")
        local_rows = self.num_nodes // n_shard

        def per_device(vals, rws, cls, xs):
            # gather the full feature matrix within the row group
            # (reference's row-broadcast, DistGCN_15d.py broad_func:19)
            full_x = jax.lax.all_gather(xs, self.axis, axis=0, tiled=True)
            rank = jax.lax.axis_index(self.axis)
            local_r = rws.astype(jnp.int32) - rank * local_rows
            gathered = full_x[cls.astype(jnp.int32)] * vals[:, None]
            # edges whose dst is outside this shard contribute nothing
            mask = ((local_r >= 0) & (local_r < local_rows))[:, None]
            return jax.ops.segment_sum(
                jnp.where(mask, gathered, 0.0),
                jnp.clip(local_r, 0, local_rows - 1),
                num_segments=local_rows)

        return jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(P(self.axis), P(self.axis), P(self.axis),
                      P(self.axis, None)),
            out_specs=P(self.axis, None))(values, rows, cols, x)


def gcn_aggregate_op(values, rows, cols, x, num_nodes, axis=None, name=None):
    return GCNAggregateOp(values, rows, cols, x, num_nodes, axis=axis,
                          name=name)


def partition_edges_by_row(vals, rows, cols, num_nodes, n_shards):
    """Host-side prep for the sharded aggregate: order edges by owning row
    shard and pad each shard's slice to equal length (static shapes)."""
    if num_nodes % n_shards:
        raise ValueError(
            f"num_nodes={num_nodes} must divide by n_shards={n_shards}; "
            "pad the node set (edges past the last full shard would be "
            "silently dropped otherwise)")
    rows = np.asarray(rows)
    shard_of = rows // (num_nodes // n_shards)
    order = np.argsort(shard_of, kind="stable")
    vals, rows, cols = (np.asarray(a)[order] for a in (vals, rows, cols))
    shard_of = shard_of[order]
    counts = np.bincount(shard_of, minlength=n_shards)
    cap = int(counts.max())
    E = cap * n_shards
    out_v = np.zeros(E, vals.dtype)
    out_r = np.zeros(E, rows.dtype)   # pad rows point at row 0 shard-local
    out_c = np.zeros(E, cols.dtype)
    for s in range(n_shards):
        seg = slice(s * cap, s * cap + counts[s])
        src = shard_of == s
        out_v[seg] = vals[src]
        out_r[seg] = rows[src]
        out_c[seg] = cols[src]
        # padding rows: first row of shard s with zero value (no-op adds)
        pad = slice(s * cap + counts[s], (s + 1) * cap)
        out_r[pad] = s * (num_nodes // n_shards)
    return out_v, out_r, out_c


class DistGCN15D:
    """Two-layer GCN with 1.5D row-partitioned aggregation
    (reference ``DistGCN_15d.py:73`` model shape: agg → dense → relu ×2)."""

    def __init__(self, in_dim, hidden, out_dim, num_nodes, axis=None,
                 name="gcn"):
        from . import initializers as init
        self.w1 = init.xavier_uniform((in_dim, hidden), name=f"{name}.w1")
        self.w2 = init.xavier_uniform((hidden, out_dim), name=f"{name}.w2")
        self.num_nodes = num_nodes
        self.axis = axis

    def __call__(self, vals, rows, cols, x):
        from .ops import matmul_op, relu_op
        h = gcn_aggregate_op(vals, rows, cols, matmul_op(x, self.w1),
                             self.num_nodes, axis=self.axis)
        h = relu_op(h)
        h = gcn_aggregate_op(vals, rows, cols, matmul_op(h, self.w2),
                             self.num_nodes, axis=self.axis)
        return h


__all__ = ["csrmm_op", "csrmv_op", "normalized_adjacency",
           "gcn_aggregate_op", "GCNAggregateOp", "partition_edges_by_row",
           "DistGCN15D"]
