from .node import Op, PlaceholderOp, Variable, placeholder_op, topo_sort, LowerCtx
from .gradients import gradients, GradientOp
from .executor import Executor, SubExecutor, worker_init, worker_finish, \
    server_init, server_finish, scheduler_init, scheduler_finish
