"""Executor: compiles fetch subgraphs into single jitted XLA programs.

TPU-native redesign of the reference execution engine
(``python/hetu/gpu_ops/executor.py``: HetuConfig:134, Executor:365,
SubExecutor:570).  The reference interprets the graph op-by-op around CUDA
streams/events with a hand-rolled memory-reuse plan (SURVEY.md §3.1); here a
SubExecutor lowers its whole topo into ONE pure function

    step(params, states, opt_states, feeds, key, lrs) -> (fetches, new_...)

and ``jax.jit``-compiles it with buffer donation, so XLA does fusion, buffer
assignment/reuse, and async scheduling — the roles of the reference's
5-stream overlap machinery, chunk allocator and memory planner.  Shape
changes retrace automatically (jit cache keyed on shapes, replacing
``SubExecutor.run``'s realloc path, executor.py:971-975).

Gradients (GradientOp markers) resolve to one ``jax.value_and_grad`` over the
lowered forward; optimizer updates apply inside the same jitted step, so
forward+backward+update is a single XLA computation per training step.

Distribution: with a ``dist_strategy`` (e.g. DataParallel) the executor holds
a ``jax.sharding.Mesh``; feeds are device_put with the strategy's
PartitionSpec and jit emits SPMD with XLA collectives over ICI — the TPU
equivalent of the reference's NCCL allreduce insertion
(``optimizer.py:145-164``).
"""
from __future__ import annotations

import pickle
import time as _time
import warnings

import numpy as np

from .node import Op, PlaceholderOp, LowerCtx, topo_sort
from .gradients import GradientOp
from ..ndarray import NDArray, wrap_device
from .. import metrics as _metrics
from ..obs.trace import TRACER as _TRACE


def _dev_roundtrip(h):
    """Feed-pipeline-thread unit of work for a device-cache step: the
    batched pending-push + miss-pull round trip (``_DevLookup.roundtrip``
    — store calls only, no cache state).  Traced as a ``ps.miss_pull``
    span on the feed-pipeline track, with a flow arrow opened here and
    closed inside the step span that consumes the rows."""
    if not _TRACE.on:
        return h.roundtrip()
    t0 = _time.perf_counter_ns()
    rows = h.roundtrip()
    _TRACE.complete("ps.miss_pull", t0, _time.perf_counter_ns(), cat="ps",
                    args={"miss_rows": 0 if rows is None
                          else int(rows.shape[0])})
    h.flow_id = _TRACE.flow_begin("emb.miss_fill", cat="ps")
    return rows


class _ZeroView:
    """``Executor.var_values`` stand-in for a stage-3 ZeRO parameter: the
    master bytes live dp-SHARDED inside a bucket slab
    (``Executor._zero_slabs``), so no full copy of the parameter exists
    between steps.  ``materialize()`` reconstructs the full host array
    (checkpointing, eval subgraphs, ``return_tensor_values``)."""

    __slots__ = ("ex", "node", "bucket")

    def __init__(self, ex, node, bucket):
        self.ex = ex
        self.node = node
        self.bucket = bucket

    @property
    def _index(self):
        return self.bucket.param_keys.index(self.ex._k(self.node))

    @property
    def shape(self):
        return self.bucket.shapes[self._index]

    @property
    def dtype(self):
        return np.dtype(self.bucket.dtype)

    def materialize(self):
        """Full host-side value (gathers the slab; multiprocess-safe).
        The slab fetch is memoized per step (``Executor._slab_host``):
        materializing k co-bucketed params costs ONE gather, not k."""
        from ..parallel.zero import host_unpack_slab
        slab = self.ex._slab_host(self.bucket)
        return host_unpack_slab(slab, self.bucket)[self.ex._k(self.node)]

    def __repr__(self):
        return (f"<ZeroView of '{self.node.name}' shape={self.shape} "
                f"in slab {self.bucket.key}>")


#: process-wide persistent-compilation-cache config (idempotent): jitting
#: with canonical input keys makes a rebuilt executor's HLO byte-identical,
#: so pointing jax's disk cache here turns the supervisor's post-restart
#: recompile into a cache read (``HETU_COMPILE_CACHE_DIR``)
_compile_cache_dir = None


def _configure_compile_cache(path):
    global _compile_cache_dir
    if not path or _compile_cache_dir == path:
        return
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        _compile_cache_dir = path
    except Exception:
        pass    # older jax without the knobs: in-process cache still works


def _filter_spec(mesh, spec):
    """Drop axes the mesh doesn't have (e.g. 'ep' under pure DP)."""
    from jax.sharding import PartitionSpec
    return PartitionSpec(*[a if a in mesh.axis_names else None for a in spec])


#: resolved once on first step (a per-step `from .. import chaos` plus
#: attribute walk is measurable at microsecond step rates); the injector
#: itself can still be (un)installed mid-run — only the module ref is
#: cached, active() is consulted every training step
_chaos_active_fn = None


def _chaos_active():
    global _chaos_active_fn
    if _chaos_active_fn is None:
        from .. import chaos
        _chaos_active_fn = chaos.active
    return _chaos_active_fn()


def _sync_outs(outs):
    """Force completion of step outputs via a host read — THE sync
    discipline (``HetuProfiler._sync`` and bench.py delegate here):
    remote-tunnel platforms do not honor ``block_until_ready``, and
    training steps chain through the params, so reading one element
    back syncs every dispatched step."""
    for o in outs or ():
        if o is None:
            continue
        arr = o.jax() if hasattr(o, "jax") else o
        if getattr(arr, "ndim", 0):
            if not getattr(arr, "size", 1):
                continue    # size-0 fetch: no element to read back
            arr = arr.ravel()[0]
        np.asarray(arr)


def _block_one(arr):
    """Bound the async in-flight window on one array.  Unlike
    ``_sync_outs`` this must be FREE on an already-complete array (it
    runs once per step at the window bound — a ``ravel()`` host-read
    would dispatch a fresh device op every step), so it uses
    ``block_until_ready`` and falls back to a host read only where
    that's unavailable.  On remote-tunnel platforms that do not honor
    block_until_ready the window is advisory, not a hard bound."""
    try:
        arr.block_until_ready()
    except Exception:
        _sync_outs([arr])


def lower_forward(topo, ctx, resolve_leaf, mesh=None, skip=(),
                  remat_segments=None, keep=()):
    """Lower every value-producing node of ``topo`` into one traced
    environment ``{node: value}``.

    The forward lowering loop, split out of the training SubExecutor's
    session/run machinery so the serving path
    (:class:`hetu_tpu.serving.InferenceExecutor`) shares ONE definition of
    "evaluate this graph" without carrying the train-side state threading:
    placeholders resolve through ``resolve_leaf(node)``, gradient markers
    and ``skip`` nodes (optimizer updates, anything train-only) are left
    out, and sharding annotations become ``with_sharding_constraint``
    under ``mesh``.  State written during forward (BN running stats)
    lands in ``ctx.state_updates`` — the training executor commits it,
    serving discards it (read-only replicas).

    ``remat_segments`` (ISSUE 13, the ``remat='full'|'auto'`` policies):
    node lists — contiguous runs in topo order, planned by
    ``parallel/remat.py`` — that each lower inside a NESTED
    ``jax.checkpoint``, so only their boundary values (consumed outside
    the segment, or in ``keep``) survive as backward residuals; the
    interiors recompute during the backward pass.  Interior values are
    NOT in the returned env — callers needing a value must name it in
    ``keep``."""
    import jax

    def constrain(node, v):
        if node.sharding is not None and mesh is not None \
                and not isinstance(node, PlaceholderOp):
            from jax.sharding import NamedSharding
            v = jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, _filter_spec(mesh, node.sharding)))
        return v

    env = {}
    if not remat_segments:
        for node in topo:
            if isinstance(node, GradientOp) or node in skip:
                continue
            if isinstance(node, PlaceholderOp):
                env[node] = resolve_leaf(node)
            else:
                env[node] = constrain(
                    node, node.lower(ctx, *[env[i] for i in node.inputs]))
        return env

    # segmented path: topo_sort guarantees inputs precede consumers, and
    # segments are contiguous runs of lowerable nodes, so every external
    # input of a segment is already in env when its first node arrives
    from ..parallel.remat import checkpoint_segment
    lowerable = [n for n in topo
                 if not (isinstance(n, GradientOp) or n in skip)]
    consumers = {}
    for n in lowerable:
        for i in n.inputs:
            consumers.setdefault(i, []).append(n)
    keep = set(keep)
    seg_of = {}
    for si, seg in enumerate(remat_segments):
        for n in seg:
            seg_of[n] = si
    done = set()
    for node in lowerable:
        if node in done:
            continue
        if isinstance(node, PlaceholderOp):
            env[node] = resolve_leaf(node)
            done.add(node)
            continue
        si = seg_of.get(node)
        if si is None:
            env[node] = constrain(
                node, node.lower(ctx, *[env[i] for i in node.inputs]))
            done.add(node)
            continue
        seg = remat_segments[si]
        segset = set(seg)
        ext = []
        for n in seg:
            for i in n.inputs:
                if isinstance(i, PlaceholderOp) and i not in env:
                    # a placeholder interleaved in topo order INSIDE the
                    # segment's span: leaf resolution is order-free
                    env[i] = resolve_leaf(i)
                    done.add(i)
                if i not in segset and i not in ext:
                    ext.append(i)
        outs = [n for n in seg
                if n in keep or not consumers.get(n)
                or any(c not in segset for c in consumers[n])]

        def seg_fn(ins, _seg=seg, _ext=ext, _outs=outs):
            e = dict(zip(_ext, ins))
            for n in _seg:
                e[n] = constrain(n, n.lower(ctx, *[e[i] for i in n.inputs]))
            return [e[o] for o in _outs]

        vals = checkpoint_segment(seg_fn)([env[i] for i in ext])
        for o, v in zip(outs, vals):
            env[o] = v
        done.update(seg)
    return env


class SubExecutor:
    """One fetch-list → one jitted step function."""

    def __init__(self, name, fetches, executor):
        self.name = name
        self.fetches = list(fetches)
        self.ex = executor
        self.topo = topo_sort([f for f in self.fetches if f is not None])

        from ..optim.optimizer import OptimizerOp
        self.opt_ops = [n for n in self.topo if isinstance(n, OptimizerOp)]
        self.grad_ops = [n for n in self.topo if isinstance(n, GradientOp)]
        # Training mode iff the subgraph differentiates (optimizer or raw
        # gradient fetches) or is literally the 'train' subgraph; substring
        # matching would misfire on names like 'pretrain_eval'.
        self.training = bool(self.opt_ops or self.grad_ops) or name == "train"

        # PS-backed embedding leaves: their per-step value is pulled from the
        # host store before the step; their gradient is pushed back after
        # (reference EmbeddingLookUp PS path, SURVEY.md §3.3)
        self.ps_nodes = [n for n in self.topo
                         if getattr(n, "is_ps", False)]
        # node -> (ids, Future[rows]): lookahead pulls in flight
        self._prefetched = {}
        self._prefetch_pool = None
        self.feed_nodes = [n for n in self.topo
                           if isinstance(n, PlaceholderOp) and not n.is_variable
                           and not getattr(n, "is_ps", False)]
        self.trainable_vars = sorted(
            {g.wrt for g in self.grad_ops}, key=lambda n: n.id)
        for v in self.trainable_vars:
            if not (isinstance(v, PlaceholderOp) and v.is_variable):
                raise ValueError(f"gradient w.r.t. non-variable {v} unsupported")
        self.state_vars = [n for n in self.topo
                           if isinstance(n, PlaceholderOp) and n.is_variable
                           and n not in self.trainable_vars]
        losses = {g.loss for g in self.grad_ops}
        if len(losses) > 1:
            raise ValueError("multiple distinct losses in one subgraph")
        self.loss_node = next(iter(losses)) if losses else None
        # graphs with a PipelineBlockOp pipeline via shard_map inside the
        # block; executor-level microbatching would double-split the batch
        self.has_pipeline_block = any(
            n.op_type == "PipelineBlock" for n in self.topo)
        if self.ex.pipeline and not self.has_pipeline_block and self.grad_ops:
            # loud, not silent: the schedule NAME promises stage overlap,
            # but without a PipelineBlock the executor can only run scanned
            # gradient accumulation (same numerics for mean-reduced losses;
            # 1F1B/hetpipe additionally remat each microbatch's forward).
            # The reference auto-partitions at recv/loss pivots
            # (pipeline_subexecutor.py:29-81); here stage functions must be
            # shape-homogeneous, so partitioning is the caller's call.
            import warnings
            warnings.warn(
                f"pipeline={self.ex.pipeline!r} on a graph with no "
                f"PipelineBlock: running scanned gradient accumulation "
                f"over {self.ex.num_microbatches} microbatches with NO "
                f"stage overlap — wrap the repeated layer chain in "
                f"ht.pipeline_block(...) to get the scheduled pipeline",
                UserWarning, stacklevel=4)
        # which fetches are batch-derived (transitively consume a fed
        # placeholder)? drives how microbatched aux outputs recombine
        feed_set = set(self.feed_nodes)
        deps = {}
        for node in self.topo:
            deps[node] = node in feed_set or any(
                deps.get(i, False) for i in node.inputs)
        self.fetch_depends_feed = [f is not None and deps.get(f, False)
                                   for f in self.fetches]
        self._jit = None
        # -- dispatch-path precomputation (graph/run_plan.py): everything
        # below depends only on graph structure + the executor's static
        # config, so it is resolved once here instead of per step --------
        self._plan_cache = None     # schema -> RunPlan (built lazily)
        self._feed_pool = None      # feed-pipeline device_put worker
        self._empty_lrs_dev = None  # committed (0,) lrs for all-traced
        ex = executor
        # traced lr: schedules that are pure functions of the step index
        # evaluate INSIDE the jitted step; only data-dependent ones stay
        # per-step host inputs (the `lrs` argument shrinks accordingly)
        self._opt_items = [(ex._k(op), op) for op in self.opt_ops]
        self._derive_lr_state()
        # state packing / writeback pairs: stage-3 ZeRO membership and
        # _zero_covered are fixed at Executor construction (before any
        # SubExecutor exists), so the per-step slab/view/plain split is
        # static
        self._zero3 = [
            (op, ex._zero_plans[op]) for op in self.opt_ops
            if ex._zero_plans.get(op) is not None
            and ex._zero_plans[op].stage >= 3]
        slab_nodes = set()
        self._slab_keys = []
        for op, plan in self._zero3:
            self._slab_keys += [b.key for b in plan.buckets]
            slab_nodes.update(op.params)
        covered = ex._zero_covered
        self._t_plain = [(ex._k(n), n) for n in self.trainable_vars
                         if n not in slab_nodes and n not in covered]
        self._t_view = [(ex._k(n), n) for n in self.trainable_vars
                        if n not in slab_nodes and n in covered]
        self._s_plain = [(ex._k(n), n) for n in self.state_vars
                         if n not in covered]
        self._s_view = [(ex._k(n), n) for n in self.state_vars
                        if n in covered]
        self._writeback_pairs = [(n, ex._k(n)) for n in self.trainable_vars
                                 if n not in covered]
        self._state_pairs = [(n, ex._k(n)) for n in self.state_vars]
        self._ps_items = [(n, ex._k(n), n.ids_node, ex._k(n.ids_node))
                          for n in self.ps_nodes]
        # device-resident HET tables (DistCacheTable(device=True)) take
        # the ISSUE 11 path: slot-plan host-side, batched miss pull on
        # the feed-pipeline thread (overlapping the dense forward),
        # slot-indexed on-device gather in the step, grads back through
        # the device scatter-add kernel.  Host-mode tables keep the
        # pull-rows-as-leaf path below unchanged.
        self._ps_dev_items = [t for t in self._ps_items
                              if getattr(t[0], "device_mode", False)]
        self._ps_host_items = [t for t in self._ps_items
                               if not getattr(t[0], "device_mode", False)]
        #: node -> in-flight _DevLookup handle (consumed by _ps_post_step
        #: for the summed-grad commit)
        self._dev_live = {}
        self._feed_node_set = frozenset(self.feed_nodes)
        self._dev_node_set = frozenset(t[0] for t in self._ps_dev_items)
        # PS rows are pulled full-batch; executor-level microbatching
        # splits feeds — statically incompatible (raised per run)
        self._ps_microbatch_clash = bool(
            self.ps_nodes and self.grad_ops and ex.pipeline
            and (ex.num_microbatches or 1) > 1
            and not self.has_pipeline_block)
        # ISSUE 13 selective remat: the segment plan for the
        # 'full'/'auto' policies, priced by the PR 5 cost model — built
        # at construction so Executor.remat_plan() answers before the
        # first run and the step-cache signature hashes the decisions
        from ..parallel import remat as _remat
        self._remat_plan = _remat.plan_for(self)
        self._remat_fingerprint = None if self._remat_plan is None \
            else self._remat_plan.fingerprint()
        if _TRACE.on and ex.remat != "off" and self.grad_ops:
            # build-time provenance in any exported trace: which policy
            # (and how many segments) this executor's measured steps ran
            # under — one instant at construction, zero hot-path cost
            _TRACE.instant("remat:plan", cat="executor", args={
                "sub": self.name, "policy": ex.remat,
                "segments_rematted": 0 if self._remat_plan is None
                else self._remat_plan.n_remat})

    # -- lowering ---------------------------------------------------------

    def _forward(self, tparams, sparams, feeds, key, remat_segments=None):
        """Evaluate every non-grad node; returns (env, state_updates).

        ``remat_segments`` (the ``remat='full'|'auto'`` training path):
        planned node lists that lower inside nested ``jax.checkpoint``
        scopes — see :func:`lower_forward`.  Only the gradient path
        passes them; eval subgraphs and the profiler's shape trace keep
        the flat lowering (and a complete env)."""
        ctx = LowerCtx(self.training, key, self.ex.mesh,
                       num_microbatches=self.ex.num_microbatches,
                       pipeline=self.ex.pipeline)

        def resolve(node):
            k = self.ex._k(node)
            if k in tparams:
                return tparams[k]
            if k in sparams:
                return sparams[k]
            return feeds[k]

        keep = ()
        if remat_segments:
            keep = [f for f in self.fetches
                    if f is not None and not isinstance(f, GradientOp)
                    and f not in self.opt_ops]
            if self.loss_node is not None:
                keep.append(self.loss_node)
        env = lower_forward(self.topo, ctx, resolve, mesh=self.ex.mesh,
                            skip=self.opt_ops,
                            remat_segments=remat_segments, keep=keep)
        updates = {self.ex._k(n): v for n, v in ctx.state_updates.items()}
        return env, updates

    def _zero3_plans(self):
        """[(opt_op, plan)] for this subgraph's stage-3 ZeRO optimizers —
        the ones whose params enter/leave the step as bucket slabs.
        Static after construction (precomputed in ``__init__``)."""
        return self._zero3

    def _pack_state(self, materialize=False):
        """Assemble the step's ``(tparams, sparams)`` inputs.

        Stage-3 ZeRO params ride as their bucket SLABS (keyed by bucket
        key) when their optimizer runs in this subgraph; a covered param
        used here *without* its optimizer (an eval subgraph sharing the
        weights) is materialized to a full replicated value instead.
        ``materialize=True`` forces full values everywhere (the
        profiler's forward-only shape evaluation).

        The slab/view/plain split is precomputed (``__init__``) — the
        per-step work is two dict builds over prebound (key, node)
        pairs, not a per-variable isinstance walk (the dispatch-gap
        discipline, graph/run_plan.py)."""
        ex = self.ex
        if materialize:
            tparams = {ex._k(n): ex._var_value(n)
                       for n in self.trainable_vars}
            sparams = {ex._k(n): ex._var_value(n) for n in self.state_vars}
            return tparams, sparams
        vv = ex.var_values
        tparams = {k: vv[n] for k, n in self._t_plain}
        for k, n in self._t_view:
            tparams[k] = ex._var_value(n)
        sparams = {k: vv[n] for k, n in self._s_plain}
        for k, n in self._s_view:
            sparams[k] = ex._var_value(n)
        for bk in self._slab_keys:
            tparams[bk] = ex._zero_slabs[bk]
        return tparams, sparams

    def _build_step(self):
        import jax

        fetch_nodes = self.fetches

        ps_keys = [self.ex._k(n) for n in self.ps_nodes]
        # device-resident tables: key -> Pallas dispatch knob (the grad
        # scatter-add runs inside the step with the table's own
        # interpret policy)
        dev_keys = {k: n.cache.device_interpret
                    for n, k, _i, _ik in self._ps_dev_items}

        from contextlib import nullcontext

        def _precision_scope():
            prec = self.ex.matmul_precision
            return jax.default_matmul_precision(prec) if prec \
                else nullcontext()

        import jax.numpy as jnp

        def _cast_tree(tree, dt, src=None):
            src_dt = jnp.dtype(src) if src else jnp.float32
            def cast(x):
                if hasattr(x, "dtype") and x.dtype == src_dt:
                    return x.astype(dt)
                return x
            return jax.tree.map(cast, tree)

        # lr resolution: traced schedules evaluate inside the step (a pure
        # function of step_idx — zero per-step host work, no retrace since
        # step_idx is a runtime input); data-dependent ones arrive through
        # the (shrunken) host `lrs` input.  _host_lrs builds that array.
        lr_traced = self._lr_traced
        host_slot = {}
        for i, t in enumerate(lr_traced):
            if t is None:
                host_slot[i] = len(host_slot)

        def _resolve_lrs(step_idx, lrs):
            return [lr_traced[i](step_idx) if lr_traced[i] is not None
                    else lrs[host_slot[i]]
                    for i in range(len(lr_traced))]

        def step(tparams, sparams, opt_states, feeds, key, step_idx, lrs):
            with _precision_scope():
                outs, ntp, upd, nos = _step_inner(
                    tparams, sparams, opt_states, feeds, key, step_idx,
                    lrs)
            # the step counter advances ON DEVICE (step_idx + 1 fed back
            # by the executor): converting a fresh np.int32 scalar at
            # every dispatch cost ~2-3us of host time; int32 wraps at
            # 2^31 steps (the x64-canonicalization note below)
            return outs, ntp, upd, nos, step_idx + 1

        def _step_inner(tparams, sparams, opt_states, feeds, key, step_idx,
                        lrs):
            # per-step RNG derivation lives INSIDE the jitted program: an
            # eager host-side fold_in cost ~280us/step of dispatch (30x a
            # raw jit call at small step sizes); here it fuses to nothing.
            # step_idx is a traced scalar, so no per-step retrace.
            key = jax.random.fold_in(key, step_idx)
            cd = self.ex.compute_dtype
            if cd:  # mixed precision: bf16 inside the step, fp32 masters out
                sparams = _cast_tree(sparams, cd)
                feeds = _cast_tree(feeds, cd)
            if self.grad_ops:
                # stage-3 ZeRO: params arrive as dp-sharded bucket slabs;
                # gather them to full shape HERE — at the top of the step,
                # where XLA's async scheduler overlaps the all-gather of
                # step N-1's updated params with step N's early compute
                # (the GC3 overlap discipline; parallel/zero.py docstring)
                model_params = tparams
                zero3 = self._zero3_plans()
                if zero3:
                    from ..parallel import zero as _zero
                    model_params = dict(tparams)
                    for _op, plan in zero3:
                        for b in plan.buckets:
                            slab = model_params.pop(b.key)
                            model_params.update(
                                _zero.gather_full(slab, b, self.ex.mesh))

                # ISSUE 13 policy-graded remat (parallel/remat.py): the
                # segmented policies ('full'/'auto') act INSIDE the
                # lowering — each planned segment lowers in a nested
                # jax.checkpoint so only boundary values survive as
                # backward residuals; the wrap policies ('dots' dots-
                # saveable, 'offload' host-offloaded dots with a counted
                # fallback) wrap the whole loss below
                seg_lists = None
                if self._remat_plan is not None:
                    seg_lists = self._remat_plan.remat_node_lists() or None

                def loss_fn(tp, fd, sp, k):
                    if cd:
                        tp = _cast_tree(tp, cd)
                    env, updates = self._forward(
                        tp, sp, fd, k, remat_segments=seg_lists)
                    aux_vals = [None if f is None or f in self.opt_ops
                                or isinstance(f, GradientOp)
                                else env[f] for f in fetch_nodes]
                    return env[self.loss_node], (aux_vals, updates)

                if self.ex.remat in ("dots", "offload"):
                    # rematerialize the forward in the backward pass:
                    # trades FLOPs (or, offloaded, host transfers) for
                    # activation memory — the TPU-native replacement for
                    # the reference's buffer-reuse memory plan
                    # (memory_pool.py:29)
                    from ..parallel import remat as _remat
                    loss_fn = _remat.wrap_loss(loss_fn, self.ex.remat)

                M = self.ex.num_microbatches or 1
                if self.ex.pipeline and M > 1 and not self.has_pipeline_block:
                    aux_vals, updates, grads = self._microbatched_grads(
                        loss_fn, model_params, sparams, feeds, key, M)
                else:
                    (loss_val, (aux_vals, updates)), grads = \
                        jax.value_and_grad(loss_fn, has_aux=True)(
                            model_params, feeds, sparams, key)
                    del loss_val
                # PS-embedding row-gradients ride the updates side-channel;
                # the executor pushes them into the host store post-step.
                # Device-resident tables segment-sum the per-occurrence
                # grads ON DEVICE first (sort + the Pallas segment-sum
                # kernel keyed by the batch's unique-inverse map) — the
                # host then commits U pre-summed rows instead of running
                # the scipy-CSR pass over the whole batch
                for k in ps_keys:
                    if k in grads:
                        g = grads[k]
                        if k in dev_keys:
                            from ..ops.pallas import emb_cache as _emb
                            g = _emb.emb_scatter_add(
                                g.reshape(-1, g.shape[-1]),
                                feeds["psdev:" + k + ":inv"],
                                interpret=dev_keys[k])
                        updates["psgrad:" + k] = g
                new_tparams = dict(tparams)
                new_opt_states = dict(opt_states)
                lr_vals = _resolve_lrs(step_idx, lrs)
                for i, opt_op in enumerate(self.opt_ops):
                    pk = [self.ex._k(v) for v in opt_op.params]
                    sub_g = {k: grads[k] for k in pk}
                    plan = self.ex._zero_plans.get(opt_op)
                    ok = self.ex._k(opt_op)
                    if plan is None:
                        sub_p = {k: new_tparams[k] for k in pk}
                        upd, new_opt_states[ok] = opt_op.optimizer.apply(
                            sub_p, sub_g, opt_states[ok], lr_vals[i])
                    else:
                        # ZeRO: reduce-scatter the grads, update only this
                        # replica's 1/dp slice of params+moments, gather
                        # the params back (stage 3: leave them sharded)
                        from ..parallel import zero as _zero
                        if plan.stage >= 3:
                            src = {b.key: tparams[b.key]
                                   for b in plan.buckets}
                        else:
                            src = {k: new_tparams[k] for k in pk}
                        upd, new_opt_states[ok] = _zero.apply_sharded(
                            opt_op.optimizer, plan, src, sub_g,
                            opt_states[ok], lr_vals[i], self.ex.mesh)
                    new_tparams.update(upd)
                outs = []
                for f, a in zip(fetch_nodes, aux_vals):
                    if isinstance(f, GradientOp):
                        outs.append(grads[self.ex._k(f.wrt)])
                    else:
                        outs.append(a)
                if cd:  # fetched values & state updates leave in fp32
                    outs = _cast_tree(outs, jnp.float32, src=cd)
                    updates = _cast_tree(updates, jnp.float32, src=cd)
                return outs, new_tparams, updates, new_opt_states
            env, updates = self._forward(
                _cast_tree(tparams, cd) if cd else tparams,
                sparams, feeds, key)
            outs = [None if f is None else env[f] for f in fetch_nodes]
            if cd:
                outs = _cast_tree(outs, jnp.float32, src=cd)
                updates = _cast_tree(updates, jnp.float32, src=cd)
            return outs, tparams, updates, opt_states

        # donate params & optimizer state: lets XLA update weights in place.
        # The jitted step is looked up in the process-wide compiled-step
        # cache first (graph/step_cache.py): a structurally identical
        # rebuild (bench re-run, supervisor restart in-process) reuses the
        # compiled executable instead of retracing.
        self._step_fn = step
        from . import step_cache
        self._jit = step_cache.lookup_or_build(self, step)

    def _microbatched_grads(self, loss_fn, tparams, sparams, feeds, key, M):
        """GPipe-semantics microbatch gradient accumulation.

        Replaces the reference's per-rank microbatch scheduler loops
        (``gpipe_subexecutor.py:79-89``, 1F1B ``pipedream_subexecutor.py``)
        with a ``lax.scan`` over microbatches inside the jitted step; stage-
        level overlap comes from ``pipeline_block``'s shard_map schedule.
        ``pipeline='pipedream'``/'hetpipe' additionally remat the per-
        microbatch forward (1F1B's activation footprint); grads are
        averaged, so the result equals the full-batch gradient for
        mean-reduced losses.  Stateful updates (BN stats) are threaded
        sequentially microbatch→microbatch, matching per-microbatch
        execution in the reference schedulers.
        """
        import jax
        import jax.numpy as jnp

        # Only feeds whose leading dim IS the batch get split; scalars and
        # constant side-inputs (masks, tables) broadcast to every microbatch.
        # Batch size: explicit via Executor(microbatch_feeds=[...]), else the
        # most common leading dim (ties → larger).
        explicit = self.ex._extra_config.get("microbatch_feeds")
        if explicit:
            names = {self.ex._k(n) if isinstance(n, Op) else n
                     for n in explicit}
            cand = [v.shape[0] for k, v in feeds.items()
                    if k in names and v.ndim]
        else:
            cand = [v.shape[0] for v in feeds.values() if v.ndim]
        from collections import Counter
        counts = Counter(cand)
        B = max(counts, key=lambda d: (counts[d], d)) if counts else 0
        if B % M:
            raise ValueError(
                f"batch {B} not divisible into {M} microbatches")
        split = {k: v for k, v in feeds.items()
                 if v.ndim and v.shape[0] == B
                 and (not explicit or k in names)}
        if not split:
            raise ValueError("pipeline microbatching needs at least one "
                             "batch-shaped feed")
        rest = {k: v for k, v in feeds.items() if k not in split}
        feeds_mb = {k: v.reshape((M, B // M) + v.shape[1:])
                    for k, v in split.items()}
        fn = loss_fn
        if self.ex.pipeline in ("pipedream", "hetpipe") \
                and self.ex.remat == "off":
            # 1F1B's per-microbatch activation footprint: full remat BY
            # DEFAULT, routed through the one policy resolver — an
            # explicit Executor(remat=...) policy already shaped loss_fn
            # (wrap or segmented lowering), so pipeline= + remat='dots'
            # COMPOSE instead of double-rematting (ISSUE 13 small fix)
            from ..parallel import remat as _remat
            fn = _remat.wrap_loss(loss_fn, "microbatch")

        grad_fn = jax.value_and_grad(fn, has_aux=True)

        def body(carry, xs):
            fd_mb, i = xs
            acc, sp = carry
            # per-microbatch key: independent dropout masks across the scan
            (_, (aux, updates)), g = grad_fn(
                tparams, {**fd_mb, **rest}, sp, jax.random.fold_in(key, i))
            acc = jax.tree.map(jnp.add, acc, g)
            sp = {**sp, **updates}
            return (acc, sp), aux

        zeros = jax.tree.map(jnp.zeros_like, tparams)
        (acc, sp_final), aux_stack = jax.lax.scan(
            body, (zeros, dict(sparams)), (feeds_mb, jnp.arange(M)))
        grads = jax.tree.map(lambda g: g / M, acc)
        # recombination by fetch kind: batch-derived fetches (transitively
        # consume a fed placeholder) re-concat along the microbatch dim
        # (token-flattened leading dims included); batch-aggregated ones
        # (e.g. per-feature stats) average; feed-independent fetches
        # (weights, constants) are identical per microbatch → last copy
        mb = B // M if M else 0

        def merge_aux(a, dep):
            if a is None:
                return None
            if a.ndim <= 1:
                return jnp.mean(a, 0)
            if dep:
                if mb and a.shape[1] % mb == 0:
                    return a.reshape((-1,) + a.shape[2:])
                return jnp.mean(a, 0)
            return a[-1]

        aux_vals = [merge_aux(a, d) for a, d in
                    zip(aux_stack, self.fetch_depends_feed)]
        # threaded state comes back committed wholesale (unchanged leaves
        # round-trip through the scan with their original values)
        return aux_vals, dict(sp_final), grads

    # -- run --------------------------------------------------------------

    def run(self, feed_dict, convert_to_numpy_ret_vals=False, sync=True):
        # the in-step guard defers a SIGTERM/SIGINT emergency save to the
        # step boundary: mid-step, var_values/opt_states are being swapped
        # and a signal-time save could capture a half-updated state
        ex = self.ex
        if self._lr_objs:
            self._check_lr_objs()
        # telemetry: the step span (HETU_TRACE=1) and the opt-in wall-
        # time histogram share one timed wrapper; both disabled costs
        # two module/attribute reads — the dispatch-gap gate
        # (tools/host_overhead_bench.py) holds that claim
        timed = _TRACE.on or _metrics.step_timing
        t0 = _time.perf_counter_ns() if timed else 0
        # captured BEFORE the step increments it: the span's step arg
        # must equal the StepTraceAnnotation step_num of the same run
        # (HetuProfiler.trace correlation), and eval subgraphs — which
        # never increment — use the same convention
        step0 = ex._step_counter if timed else 0
        ex._in_step = True
        try:
            out = self._run_impl(feed_dict, convert_to_numpy_ret_vals,
                                 sync, t0)
        finally:
            ex._in_step = False
        ex._post_step(self.training)
        if timed:
            t1 = _time.perf_counter_ns()
            if _metrics.step_timing:
                _metrics.record_step_time((t1 - t0) / 1e3, self.name)
            tr = _TRACE
            if tr.on:
                # the span covers _post_step too: chaos kills and the
                # re-replication tick fire inside the step that
                # scheduled them.  Inline ring store with the buffer
                # getattr open-coded (hot path: the <=25% tracing-tax
                # gate counts every frame here).
                b = getattr(tr._tl, "buf", None)
                if b is None or b.gen != tr._gen:
                    b = tr._buf()
                i = b.i
                # packed "S" record (see obs/trace.py): no args dict on
                # the hot path — the exporter rebuilds it
                b.items[i % b.cap] = ("S", self.name, t0, t1, step0)
                b.i = i + 1
        return out

    def _derive_lr_state(self):
        """Everything derived from each optimizer's CURRENT lr object:
        the traced-lr closures (constant floats and pure step-indexed
        schedulers evaluate inside the jitted step), the host ``lrs``
        input membership (data-dependent schedules), the baked-constant
        snapshot the per-run mutation check compares against, and the
        ops whose optimizer/scheduler actually OVERRIDES on_step (the
        built-ins are no-ops, not worth a per-step method call each).
        Called from ``__init__`` and again by ``_check_lr_objs`` when a
        reassignment is detected — ONE derivation, so a rebuilt lr
        cannot leave part of this state stale."""
        from ..optim.optimizer import Optimizer, traced_lr_fn
        from ..optim.lr_scheduler import LRScheduler
        self._lr_traced = [traced_lr_fn(op.optimizer)
                           for op in self.opt_ops]
        self._host_lr_ops = [op for op, t in
                             zip(self.opt_ops, self._lr_traced)
                             if t is None]
        # snapshot of every optimizer's lr OBJECT: a mid-training
        # `opt.lr = x` reassignment — new float, new scheduler,
        # scheduler↔float — is detected per run (identity compares on
        # the dispatch hot path) and honored by rebuilding whatever it
        # invalidates: a TRACED lr is baked into the compiled step (full
        # rebuild), and even on the host path a structural change can
        # move the op between the traced/host sets or bring a live
        # ``on_step`` (stale ``_sched_ops``).  Same-type host-path
        # reassignment (float→float under HETU_TRACED_LR=0 — the
        # mutate-every-step workflow) stays free: the host ``lrs`` input
        # re-reads the value anyway.  Mutating a live scheduler's ATTRS
        # in place stays undetected (the lr_scheduler docstring's
        # contract).
        self._lr_objs = [(op.optimizer, op.optimizer.lr)
                         for op in self.opt_ops]
        self._sched_ops = []
        for op in self.opt_ops:
            o = op.optimizer
            # class-level overrides AND instance-assigned hooks
            # (`opt.on_step = fn`) both count — the pre-plan executor
            # dispatched on_step unconditionally every step
            if type(o).on_step is not Optimizer.on_step \
                    or "on_step" in o.__dict__ \
                    or (isinstance(o.lr, LRScheduler)
                        and (type(o.lr).on_step is not LRScheduler.on_step
                             or "on_step" in o.lr.__dict__)):
                self._sched_ops.append(op)

    def _check_lr_objs(self):
        """Honor a mid-training ``optimizer.lr = x`` reassignment (see
        the ``_lr_objs`` note above): a traced lr lives inside
        the compiled step, so the step (and the plans bound to it) is
        rebuilt against the new value — the compiled-step cache hashes
        traced lrs, so a revisited value is a cache hit, a fresh one
        retraces once.  ALL lr state re-derives (``_sched_ops``
        included: the new lr may be a scheduler with a live
        ``on_step``).  Identity-first, then: traced + equal value (a
        re-assigned identical float) changes nothing; host-path + same
        TYPE (float→float, or same scheduler class — ``host_lr`` reads
        the live object every step) just refreshes the snapshot."""
        for i, (opt, old) in enumerate(self._lr_objs):
            lr = opt.lr
            if lr is old:
                continue
            if self._lr_traced[i] is not None:
                if lr != old:       # baked value/schedule changed
                    self._rebuild_lr_state()
                    return
            elif type(lr) is not type(old):     # host path: structural
                self._rebuild_lr_state()
                return
            self._lr_objs[i] = (opt, lr)    # benign: refresh snapshot

    def _rebuild_lr_state(self):
        self._derive_lr_state()
        self._jit = None            # rebuilt on the next _run_impl
        self._plan_cache = None     # plans captured the old jit

    def _host_lrs(self, step):
        """The step's host-side lr input: one float32 per optimizer whose
        schedule is DATA-dependent (everything else is traced inside the
        jitted step from ``step_idx`` — graph/run_plan.py).  The all-
        traced case returns one committed device constant: a fresh numpy
        array would pay an H2D conversion at every dispatch for an input
        the program never reads."""
        if not self._host_lr_ops:
            lrs = self._empty_lrs_dev
            if lrs is None:
                import jax
                lrs = self._empty_lrs_dev = jax.device_put(
                    np.zeros((0,), np.float32))
            return lrs
        return np.asarray([op.optimizer.host_lr(step)
                           for op in self._host_lr_ops], np.float32)

    def _run_impl(self, feed_dict, convert_to_numpy_ret_vals=False,
                  sync=True, t_run0=0):
        if self._jit is None:
            self._build_step()
        if not self._ps_dev_items:
            return self._run_general(feed_dict, convert_to_numpy_ret_vals,
                                     sync, t_run0, None)
        # device-resident PS tables: the batched miss pull is issued on
        # the feed-pipeline thread FIRST, so it overlaps everything the
        # host does before the dispatch (dense feed placement, state
        # packing) and — under async dispatch — the previous step's
        # in-flight device work (the GC3 overlap discipline).  Any
        # failure before the commit settles the in-flight handles so
        # the cache locks release and exactly-once holds.
        dev_pending = self._begin_dev_lookups(feed_dict)
        try:
            return self._run_general(feed_dict, convert_to_numpy_ret_vals,
                                     sync, t_run0, dev_pending)
        except BaseException:
            self._settle_dev_pending(dev_pending)
            raise

    def _run_general(self, feed_dict, convert_to_numpy_ret_vals, sync,
                     t_run0, dev_pending):
        ex = self.ex
        # the cached run plan resolves feed keys, placement closures and
        # the validation verdict ONCE per feed schema (run_plan.py); the
        # per-step residue is this flat replay
        cache = self._plan_cache
        if cache is None:
            from .run_plan import PlanCache
            cache = self._plan_cache = PlanCache(self)
        tr = _TRACE if _TRACE.on else None
        if tr is not None:
            # the lookup window starts at run()'s own stamp when it has
            # one (sub-us skew, one clock read saved on the hot path)
            t_pl = t_run0 or _time.perf_counter_ns()
        plan = cache.lookup(feed_dict)
        if not convert_to_numpy_ret_vals and plan._fast_eligible:
            fast = plan._fast
            if fast is None:
                fast = plan._fast = plan._make_fast()
            if tr is None:
                return fast(feed_dict, sync)
            # hand the lookup window to the fast lane: it batches ALL
            # three phase spans into one ring write (a separate emit
            # here would double the hot path's buffer walks)
            return fast(feed_dict, sync, t_pl, _time.perf_counter_ns())
        if tr is not None:
            # general path (PS / ZeRO-3 / convert): not the dispatch-gap
            # hot path — the method-call emit is fine here
            tr.complete("run_plan.lookup", t_pl, _time.perf_counter_ns(),
                        cat="executor")
            t_fd = _time.perf_counter_ns()
        feeds = plan.place_feeds(feed_dict)
        if tr is not None:
            tr.complete("feeds.place", t_fd, _time.perf_counter_ns(),
                        cat="executor")

        if self._ps_items:
            if tr is not None:
                t_ps = _time.perf_counter_ns()
            ps_vals = self._resolve_ps_rows(feed_dict, feeds)
            if tr is not None and self._ps_host_items:
                tr.complete("ps.pull_rows", t_ps,
                            _time.perf_counter_ns(), cat="ps")
            if dev_pending is not None:
                self._finish_dev_lookups(dev_pending, feeds, ps_vals)
            if self._ps_microbatch_clash:
                # only the executor-level microbatch path splits feeds;
                # PS rows are pulled full-batch — mutually exclusive
                raise NotImplementedError(
                    "PS embeddings + executor-level pipeline microbatching "
                    "are mutually exclusive (rows are pulled full-batch)")
        tparams, sparams = self._pack_state()
        if self._ps_items:
            (tparams if self.grad_ops else sparams).update(ps_vals)
        opt_states = {k: ex.opt_states[op] for k, op in self._opt_items}
        lrs = self._host_lrs(ex._step_counter)

        # step_idx rides as int32: without jax_enable_x64 an int64 input
        # is silently canonicalized to int32 anyway, and WITH x64 enabled
        # an int64 would change the traced dtype (and the jit cache key)
        # between configurations — fold_in only needs 32 bits.  It is
        # device-CHAINED: the step returns step_idx+1, fed back next run
        # (a fresh np scalar per dispatch cost ~2-3us; _step_input falls
        # back to host after construction/restore).
        if tr is not None:
            t_jit = _time.perf_counter_ns()
        outs, new_tparams, updates, new_opt_states, new_step = self._jit(
            tparams, sparams, opt_states, feeds, ex.master_key,
            ex._step_input(), lrs)
        if tr is not None:
            tr.complete("jit.dispatch", t_jit, _time.perf_counter_ns(),
                        cat="executor")

        # step N+1's host→device feed copies start NOW, overlapping the
        # in-flight device work (the double-buffered feed pipeline)
        plan.start_feed_prefetch()

        if self._ps_items:
            if tr is not None:
                t_push = _time.perf_counter_ns()
            self._ps_post_step(updates, sync)
            if tr is not None:
                tr.complete("ps.push_boundary", t_push,
                            _time.perf_counter_ns(), cat="ps")
        # stage-3 ZeRO: updated params come back as dp-sharded slabs —
        # they replace the slab store, never a full per-param array
        for opt_op, zplan in self._zero3:
            for b in zplan.buckets:
                ex._zero_slabs[b.key] = new_tparams[b.key]
                ex._slab_fetch_cache.pop(b.key, None)
        # covered params whose optimizer did NOT run here (eval /
        # grad-only subgraphs sharing stage-3 weights) entered as
        # transient materializations; writing those back would DETACH
        # the param from its slab — _writeback_pairs excludes them
        vv = ex.var_values
        for n, k in self._writeback_pairs:
            vv[n] = new_tparams[k]
        if updates:
            for n, k in self._state_pairs:
                if k in updates:
                    vv[n] = updates[k]
        for k, op in self._opt_items:
            ex.opt_states[op] = new_opt_states[k]
        if self.training:
            # host and device counters advance together; eval subgraphs
            # leave both untouched (their new_step is discarded)
            ex._step_counter += 1
            ex._step_dev = new_step
            for op in self._sched_ops:
                op.optimizer.on_step(ex._step_counter)

        if convert_to_numpy_ret_vals:
            if not sync:
                # the numpy conversion IS a sync point: materializing a
                # fetch waits for its step (per-run, not per-fetch)
                from ..metrics import record_run_plan
                record_run_plan("async_sync_points")
            results = [None if v is None else np.asarray(v) for v in outs]
        else:
            results = [None if v is None else wrap_device(v)
                       for v in outs]
            if not sync:
                ex._note_async(outs, new_opt_states)
        return results

    def _resolve_ps_rows(self, feed_dict, feeds):
        """PS pulls: resolve the ids batch host-side, pull rows (through
        the HET cache if configured), feed them as leaf params so jax
        computes their gradient alongside the model's.  A lookahead
        prefetch issued at the end of the PREVIOUS run (reference
        dataloader-lookahead overlap, ParameterServerCommunicate.py:69-77)
        is consumed here when its ids match — the pull then overlapped
        the prior step."""
        from ..data.dataloader import DataloaderOp
        ex = self.ex
        ps_vals = {}
        for node, key, idn, idk in self._ps_host_items:
            if idk in feeds:
                ids = np.asarray(feeds[idk])
            elif idn in feed_dict:
                ids = np.asarray(feed_dict[idn])
            elif isinstance(idn, DataloaderOp):
                ids = np.asarray(idn.get_arr(self.name))
            else:
                raise ValueError(f"cannot resolve ids for PS embedding {node}")
            rows = None
            pre = self._prefetched.pop(node, None)
            if pre is not None:
                pre_ids, fut = pre
                # compare ids BEFORE joining: a mismatched prefetch would
                # otherwise cost a full pull wait just to be discarded
                if np.array_equal(pre_ids, np.asarray(ids, np.int64)):
                    rows = fut.result()
                    node._last_ids = pre_ids
            if rows is None:
                rows = node.pull(ids)
            ps_vals[key] = ex._place_feed(node, rows)
        return ps_vals

    def _ensure_feed_pool(self):
        """The single feed-pipeline worker, shared by the dataloader
        H2D double-buffer (run_plan.start_feed_prefetch) and the
        device-cache miss pull — ONE bootstrap so the two paths can
        never build differently-configured pools."""
        pool = self._feed_pool
        if pool is None:
            import concurrent.futures
            pool = self._feed_pool = \
                concurrent.futures.ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=f"feed-pipeline-{self.name}")
        return pool

    # -- device-resident PS tables (ISSUE 11) -----------------------------
    def _begin_dev_lookups(self, feed_dict):
        """Phase 1 of the device-cache step: resolve each table's ids
        batch, take the cache plan (``begin_lookup`` — slot plan +
        push-payload copies under the cache lock), and issue the one
        fallible store round trip on the feed-pipeline thread.  The
        pull overlaps the dense feed placement / state packing on this
        thread and, under async dispatch, the previous step's device
        work; ``_finish_dev_lookups`` lands the rows in the slab before
        the gather consumes them."""
        from ..data.dataloader import DataloaderOp
        ex = self.ex
        if ex._multiprocess or ex.bsp != 0:
            raise NotImplementedError(
                "device-resident embedding caches support single-process "
                "BSP training (bsp=0) — ASP/SSP and multi-process meshes "
                "need the host-mode cache (DistCacheTable(device=False))")
        pool = self._ensure_feed_pool()
        pending = []
        try:
            for node, key, idn, idk in self._ps_dev_items:
                if idn in feed_dict:
                    ids = np.asarray(feed_dict[idn], np.int64)
                elif isinstance(idn, DataloaderOp):
                    if idn in self._feed_node_set:
                        # the run plan will CONSUME this loader when it
                        # places the graph's own ids feed later in the
                        # step — PEEK here (get_arr pops the same peeked
                        # batch), or the loader would advance twice per
                        # step and desync ids from rows
                        ids = np.asarray(idn.get_next_arr(self.name),
                                         np.int64)
                    else:
                        # ids feed nothing but this lookup: nobody else
                        # consumes, so consume here (host-path parity)
                        ids = np.asarray(idn.get_arr(self.name), np.int64)
                else:
                    raise ValueError(
                        f"cannot resolve ids for PS embedding {node}")
                h = node.cache.begin_lookup(ids)
                pending.append((node, key, ids, h,
                                pool.submit(_dev_roundtrip, h)))
        except BaseException:
            self._settle_dev_pending(pending)
            raise
        return pending

    def _finish_dev_lookups(self, pending, feeds, ps_vals):
        """Phase 3: join the miss pull, COMMIT the cache plan — host
        bookkeeping plus the EAGER in-place slab fill (a tiny donated
        per-bucket fill program) — then gather the batch's rows from the
        resident slab ON DEVICE and feed them as the node's ordinary
        leaf value: the jitted step is byte-identical to host mode
        except for the grad scatter-add, and hit rows never cross the
        host boundary (host mode materialized + H2D-copied every row,
        every step).  The unique-inverse map rides along for the in-step
        grad segment-sum."""
        import jax
        from ..ops.pallas import emb_cache as _emb
        tr = _TRACE if _TRACE.on else None
        for node, key, ids, h, fut in pending:
            try:
                rows = fut.result()
            except BaseException:
                node.cache.abort_lookup(h)
                raise
            # span stamped AFTER the join: any blocked wait for the
            # overlapped pull belongs to the ps.miss_pull span on the
            # feed-pipeline track, not to the gather
            t0 = _time.perf_counter_ns() if tr is not None else 0
            cache = node.cache
            # RLock depth 2 across commit+gather (finish_lookup's
            # release drops to 1): a concurrent lookup/update on the
            # same table must not evict a just-committed slot and fill
            # another key's row into it before the gather DISPATCH has
            # captured this slab/positions pairing (the same atomicity
            # _lookup_device keeps for standalone callers)
            cache._lock.acquire()
            try:
                cache.finish_lookup(h, rows)
                m = 0 if rows is None else int(rows.shape[0])
                if tr is not None and h.flow_id is not None:
                    # the overlapped pull, as an arrow from the feed-
                    # pipeline track into the step span that consumes it
                    tr.flow_end("emb.miss_fill", h.flow_id, cat="ps")
                w = cache.width
                if h.flat.size:
                    slots_occ = h.positions[h.inv].astype(np.int32)
                    inv = h.inv.astype(np.int32)
                else:
                    slots_occ = np.zeros(0, np.int32)
                    inv = np.zeros(0, np.int32)
                g = _emb.gather_for_step(cache._ensure_dev_slab(),
                                         jax.device_put(slots_occ),
                                         interpret=cache.device_interpret)
            finally:
                cache._lock.release()
            ps_vals[key] = g.reshape(tuple(ids.shape) + (w,))
            feeds["psdev:" + key + ":inv"] = jax.device_put(inv)
            self._dev_live[node] = h
            if tr is not None:
                tr.complete("emb.gather", t0, _time.perf_counter_ns(),
                            cat="ps",
                            args={"unique": 0 if h.uk is None
                                  else int(h.uk.size), "miss_rows": m})

    def _settle_dev_pending(self, pending):
        """Failure path: every not-yet-committed handle must release its
        cache lock.  A round trip that already SUCCEEDED is committed
        (its pushes reached the server — dropping the plan would leave
        the pending grads marked unsent and a retry would double-apply);
        a failed or unread one is aborted with the cache untouched."""
        for node, key, ids, h, fut in pending:
            if h.done:
                continue
            try:
                rows = fut.result()
            except BaseException:
                node.cache.abort_lookup(h)
                continue
            try:
                node.cache.finish_lookup(h, rows)   # eager slab fill
            except BaseException:
                node.cache.abort_lookup(h)

    def _ps_post_step(self, updates, sync=True):
        """Post-dispatch PS plane: grad push (sync/async by ``bsp``),
        cross-rank barriers, SSP clock, next-batch row prefetch — the
        push boundary is where non-blocking stepping is FORCED to sync
        (the row gradient must be materialized to host to be pushed)."""
        import jax
        ex = self.ex
        if ex.bsp == -1 and ex.prefetch:
            # ASP: next-batch pull may overlap the in-flight step AND the
            # async push (bounded-staleness semantics already allow it)
            self._start_ps_prefetch()
        pushed = False
        dev_nodes = self._dev_node_set
        tr = _TRACE if _TRACE.on else None
        if dev_nodes:
            from ..ops.pallas.emb_cache import fill_bucket
        for node in self.ps_nodes:
            if node in dev_nodes:
                # device-resident table: commit the device-summed grads
                # — the host applies U pre-summed rows (bounded-
                # staleness bookkeeping + batched push) instead of
                # segment-summing the whole batch
                k = ex._k(node)
                h = self._dev_live.pop(node, None)
                g = updates.pop("psgrad:" + k, None)
                if g is not None and h is not None and h.uk is not None:
                    pushed = True
                    t0 = _time.perf_counter_ns() if tr is not None else 0
                    # only rows [0, U) of the padded scatter-add output
                    # are real — slice to a pow2 bucket on device first
                    # so the D2H copy (the sync point) moves ~U rows,
                    # not the whole padded batch
                    U = int(h.uk.size)
                    ub = min(g.shape[0], fill_bucket(U))
                    gv = np.asarray(g[:ub])[:U]
                    node.cache.apply_update_summed(h.uk, gv, h.cnt)
                    if tr is not None:
                        tr.complete("emb.scatter_add", t0,
                                    _time.perf_counter_ns(), cat="ps",
                                    args={"unique": int(h.uk.size)})
                continue
            g = updates.pop("psgrad:" + ex._k(node), None)
            if g is not None:
                pushed = True
                # multiprocess: the host fetch may be a cross-process
                # COLLECTIVE, so every rank runs it BEFORE the one-pusher
                # gate below.  Single-process keeps the device array —
                # ASP's worker thread does the D2H copy off the main
                # thread
                gv = self._host_fetch(g) if ex._multiprocess else g
                # multi-process: the dp-psum'd row grad is REPLICATED
                # across ranks — exactly one rank applies it (the others
                # would double-count); routing to key owners is the
                # store's job
                if ex._multiprocess and jax.process_index() != 0:
                    continue
                if ex.bsp == -1:
                    # ASP (reference bsp=-1, ParameterServerCommunicate
                    # _compute_asp_prefetch:38): push on a background
                    # thread with a bounded in-flight window; the device→
                    # host copy happens on the worker too so the main
                    # thread never blocks on the grad transfer
                    ex._ps_async_push(node, gv)
                else:
                    node.push(np.asarray(gv))
        if pushed and not sync:
            # the push boundary forces the sync point: the row gradient
            # is materialized host-side exactly here (BSP inline; ASP on
            # the worker), which is where async-vs-sync parity is pinned
            from ..metrics import record_run_plan
            record_run_plan("async_sync_points")
        if ex._multiprocess and self.ps_nodes and self.training:
            # every rank's NEXT pull must observe this step's push (the
            # reference's _compute_bsp_prefetch barrier) — ranks must
            # never assemble "replicated" global arrays from DIVERGENT
            # row values.  This also bounds ASP: pushes stay async within
            # the step (overlapping the device work) but are flushed at
            # the step boundary — cross-rank row divergence would be
            # silent corruption, not bounded staleness
            if ex.bsp == -1:
                ex.ps_flush()
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                f"hetu-ps-step-{ex.step_counter}")
        if ex.bsp > 0 and self.training and self.ps_nodes:
            # SSP (reference bsp>0, _compute_ssp_prefetch:42 ssp_sync):
            # tick this worker's clock after its push and block while more
            # than `bsp` steps ahead of the slowest worker.  Stores whose
            # ssp_sync really blocks (native condvar; dist server-side
            # condition) get ONE wait for the whole budget — no per-step
            # host polling at real step rates (round-4 verdict weak 5).
            # The numpy fallback reports the condition without blocking
            # and keeps the poll loop.  Either way a finite watchdog
            # raises rather than wedging every healthy worker behind one
            # dead straggler with no diagnostic.
            seen = set()
            for node in self.ps_nodes:
                store = node.store
                if id(store) in seen or not hasattr(store, "ssp_sync") \
                        or not getattr(store, "ssp_ready", True):
                    continue   # local store without ssp_init: vacuous
                seen.add(id(store))
                rank = getattr(store, "rank", 0)
                try:
                    store.clock(rank)
                except RuntimeError as e:
                    if "not initialised" in str(e):
                        # distributed store whose rank-0 clocks were never
                        # ssp_init'd: bounded staleness is vacuous
                        continue
                    raise       # real store failures must surface
                deadline = _time.monotonic() + ex.ssp_timeout_ms / 1e3
                # every house store BLOCKS in ssp_sync now (native
                # condvar, dist server-side condition, and the numpy
                # fallback's threading.Condition — all declare
                # ssp_blocking=True) — one wait over the remaining
                # budget, no 5 ms host polling.  The default stays False
                # so an unknown store with a report-only ssp_sync gets
                # the polled path instead of a hot spin
                blocking = getattr(store, "ssp_blocking", False)
                while True:
                    left_ms = (deadline - _time.monotonic()) * 1e3
                    if blocking:
                        # looped only if the store caps a single wait
                        # below the requested timeout.  Never pass 0:
                        # blocking stores read timeout_ms<=0 as
                        # wait-FOREVER (ps_store.cc clk_cv.wait; dist
                        # lr=-1.0), which would defeat the watchdog
                        ok = left_ms > 0 and store.ssp_sync(
                            rank, ex.bsp, timeout_ms=max(1, int(left_ms)))
                    else:
                        ok = store.ssp_sync(rank, ex.bsp, timeout_ms=200)
                    if ok:
                        break
                    if _time.monotonic() >= deadline:
                        raise RuntimeError(
                            f"SSP bound {ex.bsp} not satisfied within "
                            f"{ex.ssp_timeout_ms}ms — a peer worker "
                            f"is stalled or dead")
                    if not blocking:
                        _time.sleep(0.005)
        if ex.bsp != -1 and ex.prefetch:
            # BSP: the prefetch pull must observe this step's push (the
            # reference's _compute_bsp_prefetch barriers for the same
            # reason), so it starts after it — overlapping the pull with
            # the step's remaining device work (dense param updates are
            # still in flight: np.asarray above only synced the grad) and
            # host-side inter-step time
            self._start_ps_prefetch()

    def _host_fetch(self, g):
        """Bring a step output to host memory across process boundaries.

        Single-process: plain asarray.  Multi-process: value-replicated
        outputs whose sharding metadata still spans remote devices cannot
        be fetched directly — read the local replica when metadata says
        fully-replicated, else allgather (a collective: EVERY rank must
        call this for such outputs)."""
        if not self.ex._multiprocess or getattr(
                g, "is_fully_addressable", True):
            return np.asarray(g)
        if getattr(g, "is_fully_replicated", False):
            return np.asarray(g.addressable_data(0))
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(g, tiled=True))

    def _start_ps_prefetch(self):
        """Issue next-batch row pulls on a background thread for every PS
        embedding whose ids come from a Dataloader (the only source whose
        next batch is knowable — reference lookahead, ``dl_node.
        get_next_arr``).  Consumed by the next ``run`` when ids match."""
        from ..data.dataloader import DataloaderOp
        from ..ps.dist_store import DistributedStore
        for node in self.ps_nodes:
            if node in self._prefetched:
                continue
            if getattr(node, "device_mode", False):
                # device-resident tables overlap their miss pull on the
                # feed-pipeline thread instead (_begin_dev_lookups)
                continue
            if isinstance(node.store, DistributedStore) \
                    and (self.ex.bsp != -1 or self.ex._multiprocess):
                # synchronous (BSP/SSP) multi-worker training: a lookahead
                # pull issued after only the LOCAL push would miss other
                # workers' same-step gradients — one step of hidden
                # staleness. ASP tolerates that — but NOT on a cross-
                # process mesh, where a pre-barrier prefetch could hand
                # different ranks different rows for the same "replicated"
                # global array (silent corruption, not staleness).
                continue
            idn = node.ids_node
            if not isinstance(idn, DataloaderOp):
                continue
            try:
                next_ids = np.asarray(idn.get_next_arr(self.name), np.int64)
            except KeyError:       # no dataloader registered for this split
                continue
            if self._prefetch_pool is None:
                import concurrent.futures
                self._prefetch_pool = \
                    concurrent.futures.ThreadPoolExecutor(
                        max_workers=1,
                        thread_name_prefix=f"ps-prefetch-{self.name}")
            fut = self._prefetch_pool.submit(node.pull_rows, next_ids)
            self._prefetched[node] = (next_ids, fut)

    def profile(self, feed_dict, log_file=None):
        """Per-step timing via real execution (reference SubExecutor.profile:686).

        Delegates to :class:`hetu_tpu.profiler.HetuProfiler` — one timer,
        one sync discipline (remote platforms need a host read to sync).
        """
        from ..profiler import HetuProfiler
        prof = HetuProfiler(self.ex, self.name, repeats=3, warmup=1)
        dt = prof.profile_step(feed_dict) / 1e3
        if log_file:
            with open(log_file, "a") as f:
                f.write(f"{self.name}: {dt * 1e3:.3f} ms/step\n")
        return dt


class Executor:
    """Multi-subgraph executor (parity: reference Executor:365).

    ``eval_node_dict``: list of fetches (single subgraph "default") or
    ``{name: fetch_list}`` (e.g. {'train': [...], 'validate': [...]}).
    """

    def __init__(self, eval_node_dict, ctx=None, seed=None, dist_strategy=None,
                 mesh=None, comm_mode=None, pipeline=None, num_microbatches=None,
                 matmul_precision=None, **kwargs):
        import jax
        import os as _os
        _configure_compile_cache(_os.environ.get("HETU_COMPILE_CACHE_DIR"))
        if isinstance(eval_node_dict, dict):
            self.eval_node_dict = dict(eval_node_dict)
        else:
            self.eval_node_dict = {"default": list(eval_node_dict)}
        # ZeRO-style weight-update sharding (parallel/zero.py): kwarg wins,
        # then HETU_ZERO, then the strategy's own zero= setting, then the
        # plan's fsdp default — resolved to a stage AFTER dist_strategy
        # lands (below)
        zero_arg = kwargs.pop("zero", None)
        # plan=: a searched ParallelPlan (hetu_tpu.autoparallel) drives
        # the whole distribution setup — its mesh axes become the
        # executor mesh, its strategy the dist_strategy, its fsdp axis
        # routes through the ZeRO slab machinery (ONE sharding mechanism,
        # never two), and its fingerprint keys the compiled-step cache so
        # candidate plans measured back-to-back each get (exactly) one
        # compile.  The plan is validated against the graph by the
        # mesh-axis / pipeline-stage / plan-coverage lints BEFORE any
        # compile — an illegal plan fails at construction with the
        # offending layer + creation site, not minutes into XLA.
        self.plan = kwargs.pop("plan", None)
        self._plan_fingerprint = None
        if self.plan is not None:
            self._plan_fingerprint = self.plan.fingerprint()
            if dist_strategy is None:
                dist_strategy = self.plan.strategy()
            if mesh is None:
                mesh = self.plan.make_mesh()
            if pipeline is not None and num_microbatches is None \
                    and self.plan.microbatches > 1:
                num_microbatches = self.plan.microbatches
        # 'bfloat16' runs fp32 matmuls as single-pass bf16 on the MXU (the
        # TPU mixed-precision fast path); None keeps jax's default
        self.matmul_precision = matmul_precision
        # compute_dtype='bfloat16': cast float params/feeds to bf16 inside
        # the step (fp32 master weights + optimizer state stay outside) —
        # halves HBM traffic for the bandwidth-bound elementwise ops
        self.compute_dtype = kwargs.pop("compute_dtype", None)
        # reference Executor(timing=...) — per-run wall timers + logOut API
        self.timing = bool(kwargs.pop("timing", False))
        self.timer_logs = {}
        self.seed = 0 if seed is None else int(seed)
        self.master_key = jax.random.key(self.seed)
        self._step_counter = 0
        self._step_dev = None   # device-chained int32 step (see run loop)
        self.comm_mode = comm_mode
        # bsp: 0 = synchronous push (BSP, default); -1 = ASP async push;
        # >0 = SSP staleness bound (enforced via ps store ssp_sync by the
        # launcher/worker loop). Reference flag semantics (README ctr:33).
        self.bsp = int(kwargs.pop("bsp", 0))
        # prefetch: overlap next-batch PS row pulls with the in-flight step
        # (reference HetuConfig(prefetch=True) default); pulls start after
        # the push under BSP (read-after-write preserved) and immediately
        # under ASP
        self.prefetch = bool(kwargs.pop("prefetch", True))
        # straggler watchdog for SSP waits (bsp>0)
        self.ssp_timeout_ms = int(kwargs.pop("ssp_timeout_ms", 600000))
        # remat: recompute activations in backward — a POLICY LADDER
        # (parallel/remat.py, ISSUE 13), not a boolean:
        #   'off'     save every activation (default)
        #   'dots'    jax.checkpoint, matmul outputs saved (== the old
        #             remat=True; True still maps here)
        #   'full'    segmented remat: the forward lowers in anchored
        #             segments, each inside a nested jax.checkpoint —
        #             only segment boundaries survive to backward
        #   'offload' dot outputs saved to HOST memory on TPU; counted
        #             fallback to 'dots' elsewhere
        #             (remat_offload_fallback)
        #   'auto'    per-segment decisions from the PR 5 shape-inferred
        #             cost model against an HBM budget
        #             (HETU_HBM_BUDGET_MB / backend-reported), cheapest
        #             recompute-per-byte rematted first; plan reported
        #             by Executor.remat_plan() and hashed into the
        #             compiled-step-cache signature
        # Every policy is BITWISE loss-equal to 'off' (remat replays the
        # same ops).  Capability analogue of the reference's memory
        # reuse plan (memory_pool.py).
        from ..parallel import remat as _remat_mod
        self.remat = _remat_mod.resolve_policy(kwargs.pop("remat", False))
        # validate: static graph verification (hetu_tpu.analysis) at
        # construction + fed-shape checks on every run().  'warn' (default)
        # reports diagnostics as warnings; 'error' fails fast with the
        # offending node and its creation site; 'off' skips analysis.
        self.validate = kwargs.pop("validate", "warn")
        if self.validate not in ("warn", "error", "off"):
            raise ValueError(f"validate={self.validate!r}: expected "
                             "'warn', 'error', or 'off'")
        self._feed_warned = set()
        # preemption-safe auto-checkpointing: every `auto_save_every`
        # training steps an atomic checkpoint lands under `auto_save_dir`
        # (keep-last-`auto_save_keep` retention); SIGTERM/SIGINT triggers
        # one final emergency save.  Env knobs HETU_AUTO_SAVE_{DIR,EVERY,
        # KEEP} let a launcher turn this on without touching user code.
        import os as _os
        self.auto_save_dir = kwargs.pop(
            "auto_save_dir", _os.environ.get("HETU_AUTO_SAVE_DIR") or None)
        self.auto_save_every = int(kwargs.pop(
            "auto_save_every", _os.environ.get("HETU_AUTO_SAVE_EVERY", "0")))
        self.auto_save_keep = int(kwargs.pop(
            "auto_save_keep", _os.environ.get("HETU_AUTO_SAVE_KEEP", "3")))
        # HETU_AUTO_RESUME=1 (set by `heturun --supervise --ckpt-dir`):
        # restore the newest complete checkpoint at construction, so a
        # training script that never calls resume() still continues
        # instead of silently restarting from step 0 on every relaunch
        self._auto_resume = bool(kwargs.pop(
            "auto_resume", _os.environ.get("HETU_AUTO_RESUME", "") == "1"))
        self._in_step = False
        self._preempt_signum = None
        self._prev_handlers = {}
        self._installed_handlers = {}
        install_handlers = kwargs.pop("install_signal_handlers", None)
        if install_handlers is None:
            install_handlers = bool(self.auto_save_dir)
        if install_handlers and self.auto_save_dir:
            self._install_signal_handlers()
        self._ps_futures = []
        self._ps_pool = None
        if pipeline is None and getattr(dist_strategy, "schedule", None):
            pipeline = dist_strategy.schedule  # PipelineParallel(schedule=..)
        if pipeline is not None and pipeline not in (
                "gpipe", "pipedream", "hetpipe"):
            raise ValueError(f"unknown pipeline schedule {pipeline!r}")
        self.pipeline = pipeline
        self.num_microbatches = num_microbatches
        if pipeline and not num_microbatches:
            self.num_microbatches = 4  # reference default microbatch count
        self._extra_config = kwargs

        # distribution
        self.dist_strategy = dist_strategy
        self.mesh = mesh
        if dist_strategy is not None and mesh is None:
            self.mesh = dist_strategy.make_mesh()
        self._replicated_sharding = None
        self._multiprocess = False
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._replicated_sharding = NamedSharding(self.mesh, PartitionSpec())
            # a mesh spanning processes (real multi-host, or launcher-
            # spawned local ranks) needs global-array construction: every
            # process holds the FULL host value and contributes its
            # addressable shards (single-controller API over SPMD ranks)
            self._multiprocess = any(
                d.process_index != jax.process_index()
                for d in self.mesh.devices.flat)

        from ..parallel import zero as _zero
        if zero_arg is None:
            zero_arg = _os.environ.get("HETU_ZERO") or None
        if zero_arg is None:
            zero_arg = getattr(dist_strategy, "zero", None) or None
        if zero_arg is None and self.plan is not None \
                and self.plan.wants_zero():
            # the plan's fsdp directives carry ZeRO-3 semantics in the
            # memory model (params+states+grads / dp); realize them
            # through the PR 6 slab machinery rather than a second
            # (per-param GSPMD) mechanism
            zero_arg = 3
        self.zero = _zero.resolve_stage(zero_arg)
        if self.plan is not None:
            # annotate bound layers now — BEFORE variables materialize
            # (placement honors node.sharding at init) — with the
            # resolved ZeRO stage, so fsdp is realized exactly once
            self.plan.realize(zero=self.zero)

        # materialize variables once, shared across subgraphs
        all_fetches = [n for fl in self.eval_node_dict.values() for n in fl
                       if n is not None]
        self.global_topo = topo_sort(all_fetches)
        # canonical step-input keys: topo ORDINALS, not process-local node
        # ids — two structurally identical graphs built in one process get
        # byte-identical input pytrees, which is what lets the compiled-
        # step cache (graph/step_cache.py) and jax's persistent compile
        # cache (HETU_COMPILE_CACHE_DIR) hit across Executor rebuilds
        self._node_keys = {n: f"t{i}" for i, n in enumerate(self.global_topo)}
        self.var_values = {}
        self._init_variables()

        # ZeRO sharding plans per OptimizerOp (requires a 'dp' mesh axis of
        # size >= 2; anything else degrades to replicated + a lint warning)
        self._zero_plans = {}
        self._zero_slabs = {}     # bucket key -> (dp, width) device slab
        self._zero_covered = {}   # stage-3 param node -> its ZeroBucket
        self._slab_fetch_cache = {}   # bucket key -> (device slab, host copy)
        self._build_zero_plans()

        from ..optim.optimizer import OptimizerOp
        self.opt_states = {}
        for node in self.global_topo:
            if isinstance(node, OptimizerOp):
                plan = self._zero_plans.get(node)
                if plan is None:
                    tp = {self._k(v): self.var_values[v]
                          for v in node.params}
                else:
                    # slab-layout state: moments are born dp-sharded
                    tp = self._init_zero_slabs(node, plan)
                self.opt_states[node] = node.optimizer.init_state(tp)

        # subgraphs whose ops carry ht.context placement run on the
        # inter-op model-parallel path (per-device segment chain)
        from .interop import detect_interop, InterOpSubExecutor
        self.subexecutors = {}
        for name, fetches in self.eval_node_dict.items():
            topo = topo_sort([f for f in fetches if f is not None])
            if self.mesh is None and detect_interop(topo):
                self.subexecutors[name] = InterOpSubExecutor(
                    name, fetches, self)
            else:
                self.subexecutors[name] = SubExecutor(name, fetches, self)

        # dispatch-path statics: PS presence gates the per-step PS hooks
        # (re-replication env polling etc.) off the dense hot path, and
        # the async in-flight window bounds run(sync=False) stepping
        self._has_ps = any(getattr(se, "ps_nodes", None)
                           for se in self.subexecutors.values())
        from collections import deque
        self._async_pending = deque()
        # flow-arrow ids paired with _async_pending entries (traced runs
        # only; empty otherwise) — ties each non-blocking dispatch to
        # the sync point that materialized it in the exported trace
        self._async_fids = deque()
        try:
            self._async_window = max(
                1, int(_os.environ.get("HETU_ASYNC_WINDOW", "4")))
        except ValueError:
            self._async_window = 4

        self._validate_graphs()

        if self._auto_resume and self.auto_save_dir:
            self.resume(self.auto_save_dir)

    # -- step counter ------------------------------------------------------

    @property
    def step_counter(self):
        return self._step_counter

    @step_counter.setter
    def step_counter(self, v):
        """External assignment (load/resume/user code): the device-
        chained step scalar is stale now — the next run re-places it
        from the host value.  The run loops bump ``_step_counter``
        directly (their device copy advances inside the jitted step)."""
        self._step_counter = int(v)
        self._step_dev = None

    def _step_input(self):
        """The jitted step's ``step_idx`` input: the device scalar the
        previous step returned (zero host work), or a fresh host int32
        right after construction / checkpoint restore / external
        assignment."""
        sd = self._step_dev
        return np.int32(self._step_counter) if sd is None else sd

    # -- canonical step-input keys ----------------------------------------

    def _k(self, node):
        """Canonical (topo-ordinal) step-input key of a graph node."""
        k = self._node_keys.get(node)
        return k if k is not None else f"n{node.id}"

    # -- ZeRO weight-update sharding (parallel/zero.py) --------------------

    def _build_zero_plans(self):
        """One :class:`ZeroPlan` per OptimizerOp when ZeRO is on and the
        mesh has a 'dp' axis of size >= 2.  An optimizer whose params are
        not all float arrays (e.g. a PS-backed table riding in the same
        op), or that owns a param with an EXPLICIT sharding annotation
        (``ht.dispatch``: model-parallel layouts the dp slab packing —
        and stage <3's replicated gather — would silently destroy), is
        left on the replicated update path — a partial plan would
        silently skip the uncovered params' update."""
        from ..parallel import zero as _zero
        if not self.zero or self.mesh is None \
                or _zero.ZERO_AXIS not in self.mesh.axis_names:
            return
        dp = int(self.mesh.shape[_zero.ZERO_AXIS])
        if dp < 2:
            return
        from ..optim.optimizer import OptimizerOp
        for node in self.global_topo:
            if not isinstance(node, OptimizerOp) or not node.params:
                continue
            items, eligible = [], True
            for p in node.params:
                v = self.var_values.get(p)
                if v is None or isinstance(v, _ZeroView) \
                        or _zero.ineligible_reason(p, v.dtype) is not None:
                    eligible = False
                    break
                items.append((self._k(p), tuple(v.shape),
                              np.dtype(v.dtype).name))
            if not eligible:
                continue
            # LAMB's trust ratio needs per-PARAMETER norms: a multi-param
            # slab would compute one norm for the whole bucket
            per_param = bool(getattr(node.optimizer, "lamb", False))
            self._zero_plans[node] = _zero.build_plan(
                items, dp, self.zero, per_param=per_param,
                prefix=self._k(node) + ".")

    def _init_zero_slabs(self, op, plan):
        """Pack ``op``'s params into dp-sharded bucket slabs; at stage 3
        the slabs BECOME the master copy (var_values swaps to
        :class:`_ZeroView` stand-ins) — no full param copy persists
        between steps."""
        from ..parallel import zero as _zero
        sh = _zero.slab_sharding(self.mesh)
        by_key = {self._k(p): p for p in op.params}
        slabs = {}
        for b in plan.buckets:
            host = {k: self._fetch_host(self.var_values[by_key[k]])
                    for k in b.param_keys}
            slabs[b.key] = self._global_put(
                _zero.host_pack_slab(host, b), sh)
        if plan.stage >= 3:
            for b in plan.buckets:
                self._zero_slabs[b.key] = slabs[b.key]
                for k in b.param_keys:
                    p = by_key[k]
                    self._zero_covered[p] = b
                    self.var_values[p] = _ZeroView(self, p, b)
        return slabs

    def _var_value(self, node):
        """Device value of a variable for a step input; a stage-3
        :class:`_ZeroView` is materialized to a full replicated array
        (eval subgraphs sharing sharded training weights)."""
        v = self.var_values[node]
        if isinstance(v, _ZeroView):
            return self._place_param(v.materialize(), node)
        return v

    def _set_vars_host(self, items):
        """Install full host values for variables (``{node: array}``) —
        writing THROUGH to the bucket slabs when params' master bytes
        live sharded (stage-3 ZeRO), so load/load_dict keep the sharded
        layout.  Batched: each touched slab is fetched and re-placed ONCE
        no matter how many of its params are set (a per-param round trip
        would make restoring a 50-param bucket pay 50 full slab
        gather+scatter trips — and on a multi-process mesh every fetch is
        a collective)."""
        from ..parallel import zero as _zero
        by_bucket = {}
        for node, val in items.items():
            b = self._zero_covered.get(node)
            if b is None:
                self.var_values[node] = self._place_param(
                    np.asarray(val), node)
            else:
                by_bucket.setdefault(b.key, (b, {}))[1][node] = val
        for key, (b, vals) in by_bucket.items():
            slab = np.array(self._fetch_host(self._zero_slabs[key]))
            flat = slab.reshape(-1)
            for node, val in vals.items():
                i = b.param_keys.index(self._k(node))
                shape = b.shapes[i]
                size = int(np.prod(shape, dtype=np.int64)) if shape else 1
                flat[b.offsets[i]:b.offsets[i] + size] = \
                    np.asarray(val, slab.dtype).reshape(-1)
            self._zero_slabs[key] = self._global_put(
                slab, _zero.slab_sharding(self.mesh))
            self._slab_fetch_cache.pop(key, None)

    def _set_var_host(self, node, val):
        self._set_vars_host({node: val})

    def _slab_host(self, bucket):
        """Host copy of one stage-3 bucket slab, memoized against the
        CURRENT device slab: save()/eval packing/return_tensor_values
        materialize every member of a bucket, and k params in one 4 MB
        bucket must pay ONE full-slab gather (a cross-process collective
        on a multiprocess mesh), not k.  The cache invalidates by slab
        identity — every step and every restore installs a new slab
        object — and is dropped eagerly on replacement, so at most the
        current step's materialized buckets live host-side."""
        cur = self._zero_slabs[bucket.key]
        slab, host = self._slab_fetch_cache.get(bucket.key, (None, None))
        if slab is cur:
            return host
        host = self._fetch_host(cur)
        self._slab_fetch_cache[bucket.key] = (cur, host)
        return host

    # -- elastic world resize (parallel/elastic.py, ISSUE 12) --------------

    @staticmethod
    def _transcode_opt_state(tree, old_plan, new_plan):
        """Re-layout one optimizer's HOST state between ZeRO bucket
        plans: slab-keyed moment dicts of ``old_plan`` unpack to
        per-param arrays, which ``new_plan`` re-packs into its own
        ``(dp, width)`` slabs — pure data movement (flatten/concat/pad),
        so the moments survive a resize bitwise.  Either plan may be
        None (replicated layout on that side).  Scalars (Adam's ``t``)
        and non-matching subtrees pass through untouched."""
        from ..parallel import zero as _zero
        old_keys = frozenset(b.key for b in old_plan.buckets) \
            if old_plan is not None else frozenset()
        new_keys = frozenset(new_plan.param_keys) \
            if new_plan is not None else frozenset()

        def walk(t):
            if not isinstance(t, dict):
                return t
            keys = frozenset(t)
            if old_keys and keys == old_keys:
                flat = {}
                for b in old_plan.buckets:
                    flat.update(_zero.host_unpack_slab(
                        np.asarray(t[b.key]), b))
                t = flat
                keys = frozenset(t)
            if new_keys and keys == new_keys:
                return {b.key: _zero.host_pack_slab(t, b)
                        for b in new_plan.buckets}
            return {k: walk(v) for k, v in t.items()}

        return walk(tree)

    def _maybe_transcode_loaded_opt(self, op, host_tree):
        """Cross-dp checkpoint portability: a directory checkpoint
        written under a different world size carries ``op``'s ZeRO
        moment slabs in the WRITER's ``(dp, width)`` layout.  Bucket
        boundaries are dp-independent (packing is by bytes and dtype),
        so the writer's plan is reconstructible from the slab's leading
        dim — reconstruct it and transcode the moments into this
        world's layout (bitwise, pure data movement).  Anything that
        does not look like a clean cross-dp slab set (different bucket
        partition, stage mismatch) passes through untouched and the
        existing shape handling decides.  This is what lets a
        supervisor restart — or a fresh executor — resume a checkpoint
        that an elastic resize (``resize_world``) wrote at a different
        dp."""
        plan = self._zero_plans.get(op)
        if plan is None:
            return host_tree
        from ..parallel import zero as _zero
        new_shapes = {(b.dp, b.width) for b in plan.buckets}
        bucket_keys = frozenset(b.key for b in plan.buckets)
        slab_shape = []

        def scan(t):
            if not isinstance(t, dict) or slab_shape:
                return
            if frozenset(t) == bucket_keys:
                for bi, b in enumerate(plan.buckets):
                    v = t.get(b.key)
                    if getattr(v, "ndim", 0) == 2:
                        slab_shape.append((bi, tuple(v.shape)))
                        return
            for v in t.values():
                scan(v)

        scan(host_tree)
        if not slab_shape or slab_shape[0][1] in new_shapes:
            return host_tree        # same world (or nothing slab-like)
        bi, shape = slab_shape[0]
        dp_old = int(shape[0])
        items = [(k, s, b.dtype) for b in plan.buckets
                 for k, s in zip(b.param_keys, b.shapes)]
        old_plan = _zero.build_plan(
            items, dp_old, plan.stage,
            per_param=bool(getattr(op.optimizer, "lamb", False)),
            prefix=self._k(op) + ".")
        if frozenset(b.key for b in old_plan.buckets) != bucket_keys \
                or shape != (old_plan.buckets[bi].dp,
                             old_plan.buckets[bi].width):
            return host_tree        # not a clean cross-dp layout
        warnings.warn(
            f"checkpoint optimizer state for '{op.name}' was written at "
            f"dp={dp_old}; transcoding its moment slabs to this world's "
            f"dp={plan.dp} layout (elastic-resize checkpoint "
            f"portability)")
        return self._transcode_opt_state(host_tree, old_plan, plan)

    def resize_world(self, ranks):
        """Resize the data-parallel world IN PLACE — the elastic
        shrink/grow primitive (:mod:`hetu_tpu.parallel.elastic`).

        ``ranks``: the active rank indices into the BASE world (the
        device order of the mesh this executor was constructed with —
        rank r is base device r).  Everything that makes training
        continuous is preserved bitwise: params and optimizer moments
        (ZeRO slab layouts transcoded through
        :meth:`_transcode_opt_state`), the RNG key, the step counter,
        and dataloader positions (never touched).  In-flight async
        steps are drained first; the jitted step rebuilds THROUGH the
        compiled-step cache, so revisiting a world size (the grow-back)
        is a ``step_cache_hit`` — no recompile.  The transient cost is
        one full host materialization of params + moments (the same
        bytes a checkpoint restore moves) plus one compile per
        first-visited world size.

        Single-controller only: a multiprocess mesh is refused (every
        process would have to agree on the new world — that is the
        jax.distributed coordination problem, out of scope per the
        fail-stop model note in ``parallel/elastic.py``), as is any
        mesh with model-parallel axes (re-planning 'tp'/'pp' layouts is
        a different problem than re-packing dp slabs).  Returns True if
        the world actually changed, False for a no-op."""
        from .. import race as _race
        if _race.ACTIVE is not None:   # ISSUE 14 preemption point
            _race.point("exec.resize_world")
        import jax
        from ..parallel import zero as _zero
        from ..context import make_mesh
        if self.mesh is None:
            raise ValueError(
                "resize_world needs a mesh (dist_strategy=DataParallel)")
        if self._multiprocess:
            raise NotImplementedError(
                "elastic resize is single-controller: a multiprocess "
                "mesh needs coordinated re-initialization (future work; "
                "use the supervisor's restart path)")
        if tuple(self.mesh.axis_names) != (_zero.ZERO_AXIS,):
            raise NotImplementedError(
                f"elastic resize supports pure data-parallel meshes "
                f"(axes ('dp',)), got {tuple(self.mesh.axis_names)}")
        base = getattr(self, "_elastic_base_devices", None)
        if base is None:
            base = self._elastic_base_devices = list(self.mesh.devices.flat)
        ranks = sorted({int(r) for r in ranks})
        if not ranks:
            raise ValueError("resize_world: empty rank set")
        if ranks[-1] >= len(base) or ranks[0] < 0:
            raise ValueError(
                f"resize_world: rank {ranks[-1] if ranks[0] >= 0 else ranks[0]}"
                f" outside the base world of {len(base)} "
                f"(ranks index the construction-time mesh)")
        new_devices = [base[r] for r in ranks]
        if new_devices == list(self.mesh.devices.flat):
            return False

        # 1. quiesce: no dispatched step may still reference the old
        # world's buffers, and no async PS push may land mid-swap
        self._drain_async()
        self.ps_flush()

        # 2. snapshot training state host-side (ZeRO views materialize
        # one gather per bucket via the _slab_host memo; optimizer slab
        # state transcodes to per-param layout below)
        var_host = {}
        for node in self.global_topo:
            if isinstance(node, PlaceholderOp) and node.is_variable:
                var_host[node] = self._fetch_host(self.var_values[node])
        old_plans = dict(self._zero_plans)
        opt_host = {
            op: jax.tree.map(self._fetch_host, st)
            for op, st in self.opt_states.items()}

        # 3. the new world: same axis name, the surviving base devices
        # in rank order — revisiting a rank set reproduces the exact
        # mesh fingerprint, which is what turns the grow-back rebuild
        # into a compiled-step cache HIT
        self.mesh = make_mesh({_zero.ZERO_AXIS: len(new_devices)},
                              new_devices)
        from jax.sharding import NamedSharding, PartitionSpec
        self._replicated_sharding = NamedSharding(self.mesh,
                                                  PartitionSpec())
        # the caller-owned strategy object is NOT touched: it may be
        # shared by other executors (its make_mesh only runs at
        # construction; this executor's live world is self.mesh)

        # 4. redistribute: re-place every variable, re-plan the ZeRO
        # buckets for the new dp, re-pack slabs and moments
        self._zero_plans = {}
        self._zero_slabs = {}
        self._zero_covered = {}
        self._slab_fetch_cache = {}
        for node, val in var_host.items():
            self.var_values[node] = self._place_param(val, node)
        self._build_zero_plans()
        for op in list(self.opt_states):
            plan = self._zero_plans.get(op)
            if plan is not None and plan.stage >= 3:
                # re-establish the slab-resident master params (and the
                # _ZeroView stand-ins) under the new bucket widths
                self._init_zero_slabs(op, plan)
            st = self._transcode_opt_state(opt_host[op],
                                           old_plans.get(op), plan)
            self.opt_states[op] = jax.tree.map(
                lambda leaf, _op=op: self._place_opt_leaf(_op, leaf), st)

        # 5. rebuild the subexecutors against the new mesh.  The old
        # ones' background pools are shut down here (their caches stay
        # open — they belong to the graph nodes, which the new
        # subexecutors share); the new jitted steps resolve through the
        # compiled-step cache.
        for se in self.subexecutors.values():
            for attr in ("_prefetch_pool", "_feed_pool"):
                pool = getattr(se, attr, None)
                if pool is not None:
                    pool.shutdown(wait=False)
        self.subexecutors = {
            name: SubExecutor(name, [f for f in fetches], self)
            for name, fetches in self.eval_node_dict.items()}
        self._has_ps = any(getattr(se, "ps_nodes", None)
                           for se in self.subexecutors.values())
        # the device-chained step scalar lives on the old mesh — force
        # the next run to re-place it from the host counter
        self.step_counter = self._step_counter
        return True

    # -- static validation (hetu_tpu.analysis) -----------------------------

    def _validate_graphs(self):
        """Construction-time graph lint (``validate='warn'|'error'``).

        Rules that need no feed shapes (grad-onto-non-trainable, duplicate
        checkpoint names, PS table width, mesh-axis validity, pipeline
        contiguity, static flash-fallback prediction, hand-shape-rule
        cross-checks) run here, so a broken graph fails at construction
        with the node name + creation site instead of minutes into XLA
        tracing.  Fed-value shapes are checked per ``run()``."""
        if self.validate == "off" and self.plan is None:
            # validate='off' silences the lint — but never the plan gate
            # (below): a plan-driven executor always lints the plan rules
            return
        from ..analysis import lint as lint_graph
        # remat is a training-graph concern: eval subgraphs sharing the
        # executor must not warn "no recomputable segment" — unless NO
        # subgraph differentiates, in which case remat= really is a
        # no-op and the first subgraph's lint says so
        any_grads = any(getattr(s, "grad_ops", None)
                        for s in self.subexecutors.values())
        first = next(iter(self.eval_node_dict), None)
        plan_cov = {}    # subgraph -> its plan-coverage errors (plan= only)
        for name, fetches in self.eval_node_dict.items():
            sub_grads = getattr(self.subexecutors.get(name), "grad_ops",
                                None)
            lint_remat = self.remat if (
                sub_grads or (not any_grads and name == first)) else "off"
            try:
                report = lint_graph(fetches, mesh=self.mesh,
                                    pipeline=self.pipeline,
                                    num_microbatches=self.num_microbatches,
                                    zero=self.zero, remat=lint_remat,
                                    plan=self.plan)
            except Exception as e:
                if self.plan is not None:
                    # with a plan attached the gate below is load-bearing:
                    # a crashed lint would let an unrealizable plan
                    # compile the WRONG program and the measurement loop
                    # would time it — fail instead of warn
                    raise
                # the analyzer must never be the thing that breaks a
                # working graph — report and continue
                warnings.warn(f"graph lint crashed on subgraph "
                              f"'{name}': {type(e).__name__}: {e}",
                              RuntimeWarning)
                continue
            if self.plan is not None:
                # the plan gate: an illegal plan must fail BEFORE compile
                # regardless of validate='warn' — silently executing a
                # plan that cannot be realized (tp never applied, pp
                # never pipelined, a plan axis missing from the mesh)
                # would produce measurements of the WRONG program
                plan_bad = [
                    d for d in report.diagnostics
                    if not d.internal and d.severity == "error"
                    and d.rule in ("mesh-axis", "pipeline-stage")]
                if plan_bad:
                    from ..analysis.lint import GraphValidationError
                    raise GraphValidationError(
                        f"plan validation failed on subgraph '{name}' "
                        f"(plan {self.plan.tag()}):\n" +
                        "\n".join(f"  {d}" for d in plan_bad))
                # plan COVERAGE is an executor-level property: an
                # auxiliary fetch set (a grad-norm scalar, an eval head)
                # need not contain the plan-annotated kernels — the plan
                # is realized if ANY subgraph carries it.  Withhold this
                # subgraph's coverage errors (and strip them from the
                # report so validate='warn'/'error' does not surface a
                # per-subgraph false alarm); the gate after the loop
                # raises if EVERY subgraph missed.
                cov = [d for d in report.diagnostics
                       if not d.internal and d.severity == "error"
                       and d.rule == "plan-coverage"]
                plan_cov[name] = cov
                if cov:
                    cov_ids = {id(d) for d in cov}
                    report.diagnostics = [d for d in report.diagnostics
                                          if id(d) not in cov_ids]
            if self.validate == "off":
                continue          # plan gate only — the lint stays silenced
            if report.diagnostics:
                if self.validate == "error":
                    report.raise_errors(all_severities=True)
                warnings.warn(
                    f"graph lint found {len(report.diagnostics)} issue(s) "
                    f"in subgraph '{name}' "
                    f"(Executor(validate='off') silences):\n{report}",
                    UserWarning)
        if self.plan is not None and plan_cov \
                and all(plan_cov.values()):
            # no subgraph realizes the plan — the unrealized directives
            # are a property of the whole executor, reported once
            from ..analysis.lint import GraphValidationError
            worst = max(plan_cov.items(), key=lambda kv: len(kv[1]))
            raise GraphValidationError(
                f"plan validation failed (plan {self.plan.tag()}): no "
                f"subgraph realizes the plan — subgraph '{worst[0]}':\n"
                + "\n".join(f"  {d}" for d in worst[1]))

    def _check_feeds(self, sub, feed_dict):
        """Fed values vs declared placeholder shapes/dtypes — the run-time
        half of ``validate=`` (feeds are only known here)."""
        from ..analysis.lint import GraphValidationError
        from .node import format_site
        for node in sub.feed_nodes:
            if node not in feed_dict or node.shape is None:
                continue
            val = feed_dict[node]
            shape = tuple(val.shape) if hasattr(val, "shape") \
                else tuple(np.shape(val))
            if shape == tuple(node.shape):
                continue
            msg = (f"feed for placeholder '{node.name}' has shape "
                   f"{shape} but the placeholder declares "
                   f"{tuple(node.shape)} [created at "
                   f"{format_site(node.creation_site)}]")
            if self.validate == "error":
                raise GraphValidationError(msg)
            if node.id not in self._feed_warned:
                self._feed_warned.add(node.id)
                warnings.warn(msg, UserWarning)

    # -- variable init ----------------------------------------------------

    def _init_variables(self):
        import jax
        init_key = jax.random.key(self.seed)
        i = 0
        # checkpoint names must be unique even when layers share default
        # names (two `Linear(name='linear')` → two 'linear.weight' nodes)
        self.var_names = {}
        seen_names = {}
        for node in self.global_topo:
            if not (isinstance(node, PlaceholderOp) and node.is_variable):
                continue
            count = seen_names.get(node.name, 0)
            seen_names[node.name] = count + 1
            self.var_names[node] = node.name if count == 0 \
                else f"{node.name}~{count}"
            if node.shape is None and hasattr(node, "shape_from"):
                ref = node.shape_from
                node.shape = tuple(np.asarray(self.var_values[ref]).shape) \
                    if ref in self.var_values else tuple(ref.shape)
            val = node.get_init_value(jax.random.fold_in(init_key, i))
            i += 1
            if val is None:
                raise ValueError(f"variable {node} has no value/initializer")
            self.var_values[node] = self._place_param(np.asarray(val, np.float32)
                                                      if np.asarray(val).dtype == np.float64
                                                      else np.asarray(val), node)

    def _global_put(self, val, sharding):
        """Commit a full host value under a (possibly multi-process)
        sharding.  Cross-process shardings cannot be device_put from host
        data directly; each process contributes its addressable shards of
        the SAME full value (callers guarantee identical content — same
        seeds, same feeds)."""
        import jax
        if not self._multiprocess:
            return jax.device_put(val, sharding)
        val = np.asarray(val)
        return jax.make_array_from_callback(
            val.shape, sharding, lambda idx: val[idx])

    def _place_param(self, val, node=None):
        import jax
        if self.mesh is not None:
            from jax.sharding import NamedSharding
            spec = getattr(node, "sharding", None)
            if spec is not None:
                return self._global_put(val, NamedSharding(
                    self.mesh, _filter_spec(self.mesh, spec)))
            return self._global_put(val, self._replicated_sharding)
        return jax.device_put(val)

    def _place_feed(self, node, val):
        import jax
        if isinstance(val, NDArray):
            val = val.jax()
        val = np.asarray(val) if not hasattr(val, "dtype") else val
        if getattr(val, "dtype", None) == np.float64:
            val = np.asarray(val, np.float32)
        # feeds adopt the placeholder's declared dtype: int placeholders
        # (token ids, labels) must stay integral so the compute_dtype bf16
        # cast never rounds them (bf16 is exact only up to 256)
        want = getattr(node, "dtype", None)
        if want is not None and getattr(val, "dtype", None) != np.dtype(want):
            val = val.astype(np.dtype(want)) if hasattr(val, "astype") \
                else np.asarray(val, want)
        if self.mesh is None and isinstance(val, jax.Array):
            # pre-placed device feed (the bench fast path): re-dispatching
            # device_put on a committed array costs ~55us/step for nothing
            # — but ONLY when it already lives on the default backend; an
            # array parked on another platform (cpu feed into a tpu step)
            # must still be transferred here, not at dispatch time
            try:
                on_default = all(d.platform == jax.default_backend()
                                 for d in val.devices())
            except Exception:
                on_default = False
            if on_default:
                return val
            return jax.device_put(val)
        if self.mesh is not None:
            from jax.sharding import NamedSharding
            if node.sharding is not None:  # explicit ht.dispatch on a feed
                return self._global_put(val, NamedSharding(
                    self.mesh, _filter_spec(self.mesh, node.sharding)))
            if self.dist_strategy is not None:
                spec = self.dist_strategy.feed_spec(node, np.ndim(val))
                return self._global_put(val, NamedSharding(self.mesh, spec))
            # bare-mesh executors (no strategy): replicate — a plain
            # device_put would pin to local device 0, which is
            # incompatible with a cross-process mesh
            return self._global_put(val, self._replicated_sharding)
        return jax.device_put(val)

    # -- public API (reference parity) ------------------------------------

    def run(self, name="default", eval_node_list=None, feed_dict=None,
            convert_to_numpy_ret_vals=False, sync=True, **kwargs):
        """Run one step of subgraph ``name``.

        ``sync=False`` is NON-BLOCKING stepping: the returned fetches are
        handles backed by jax's async dispatch (``NDArray`` wrappers whose
        ``.asnumpy()`` materializes on demand) and the executor keeps a
        bounded window of dispatched steps in flight
        (``HETU_ASYNC_WINDOW``, default 4) instead of letting the host
        run arbitrarily far ahead.  Sync points are forced exactly where
        correctness needs one — ``convert_to_numpy_ret_vals``, the PS
        push boundary, checkpoint saves, the window filling — and counted
        (``async_sync_points``).  Async and sync stepping run the SAME
        jitted program in the same order, so losses and final state are
        bitwise identical."""
        if isinstance(name, dict):  # run(feed_dict) shorthand
            feed_dict = name
            name = "default"
        if isinstance(eval_node_list, dict) and feed_dict is None:
            # run(name, feed_dict) positional shorthand — a dict here is
            # unambiguously a feed_dict, not a fetch-list override
            feed_dict, eval_node_list = eval_node_list, None
        feed_dict = feed_dict or {}
        if eval_node_list:
            warnings.warn("eval_node_list override is ignored; fetches are "
                          "fixed per subgraph at construction")
        if self.timing:
            # in-training timers (reference timer_subexecutor.py:109 /
            # Executor(timing=...)); per-op timing under fusion comes from
            # HetuProfiler instead.  The timer BLOCKS on the fetches:
            # dispatch returns before the device finishes, so an
            # unblocked bracket under-reports real step time — which also
            # means timing=True measures away the pipelining/async wins
            # it is asked to time.
            import time
            t0 = time.perf_counter()
            out = self.subexecutors[name].run(feed_dict,
                                              convert_to_numpy_ret_vals,
                                              sync=sync)
            _sync_outs(out)
            self.timer_logs.setdefault(name, []).append(
                (time.perf_counter() - t0) * 1e3)
            return out
        return self.subexecutors[name].run(feed_dict,
                                           convert_to_numpy_ret_vals,
                                           sync=sync)

    def run_steps(self, feeder, n, name="default", sync=False,
                  convert_to_numpy_ret_vals=False):
        """Drive ``n`` steps with pipelined host→device feeds and (by
        default) non-blocking stepping — the convenience loop around
        ``run(..., sync=False)``.

        ``feeder``: ``callable(i) -> feed_dict`` (host arrays are fine),
        a list of feed_dicts, or ``None`` for dataloader-fed graphs
        (whose feeds the run plan double-buffers on its own).  Step
        ``i+1``'s feeds are placed on a background thread while step
        ``i``'s jitted program executes, so the H2D copy overlaps compute
        (``feeds_pipelined`` counts the overlapped arrays); step 0 is
        placed inline so the feed schema stays steady from the first
        step.  Returns the list of per-step fetch lists — handles under
        ``sync=False`` (materialize with ``.asnumpy()``), bitwise equal
        to a sync loop."""
        if not isinstance(n, int) or n < 0:
            raise ValueError(f"run_steps needs a step count, got {n!r}")
        if feeder is None:
            get_fd = None
        elif callable(feeder):
            get_fd = feeder
        else:
            fds = list(feeder)
            if len(fds) < n:
                raise ValueError(
                    f"run_steps: {n} steps but only {len(fds)} feed dicts")
            get_fd = fds.__getitem__

        def place_all(fd):
            if not _TRACE.on:
                return {node: self._place_feed(node, v)
                        for node, v in fd.items()}
            # traced: the H2D copy shows up on the run-steps-feed track
            t0 = _time.perf_counter_ns()
            out = {node: self._place_feed(node, v)
                   for node, v in fd.items()}
            _TRACE.complete("feed.h2d", t0, _time.perf_counter_ns(),
                            cat="feed", args={"n": len(out)})
            return out

        from .run_plan import feed_pipeline_enabled, pipeline_min_us
        pool = fut = None
        placed, overlap = {}, False
        if get_fd and n:
            import jax
            # warm the device_put dispatch infra with one scalar so the
            # timed placement below measures steady-state cost, without
            # paying a full redundant copy of step 0's batch
            jax.device_put(np.zeros((), np.float32))
            # adaptive: only feeds whose placement outweighs a thread
            # handoff (~60-100us) are double-buffered — pipelining a
            # 256-byte copy behind a submit/result wakeup would SLOW
            # the loop.  HETU_FEED_PIPELINE=0 kills the thread entirely
            # (this driver AND the plan's dataloader double-buffer).
            t0 = _time.perf_counter()
            placed = place_all(get_fd(0))
            overlap = feed_pipeline_enabled() \
                and (_time.perf_counter() - t0) * 1e6 >= pipeline_min_us()
        results = []
        try:
            for i in range(n):
                if overlap and i + 1 < n:
                    if pool is None:
                        import concurrent.futures
                        pool = concurrent.futures.ThreadPoolExecutor(
                            max_workers=1,
                            thread_name_prefix="run-steps-feed")
                    fut = pool.submit(place_all, get_fd(i + 1))
                else:
                    fut = None
                results.append(self.run(
                    name, feed_dict=placed, sync=sync,
                    convert_to_numpy_ret_vals=convert_to_numpy_ret_vals))
                if fut is not None:
                    placed = fut.result()
                    from ..metrics import record_run_plan
                    record_run_plan("feeds_pipelined", len(placed))
                elif get_fd and i + 1 < n:
                    placed = place_all(get_fd(i + 1))
        finally:
            if pool is not None:
                pool.shutdown(wait=False)
        return results

    def _note_async(self, outs, new_opt_states):
        """Track one non-blocking step; block on the OLDEST in-flight
        step once the window fills (bounded pipelining, not unbounded
        host run-ahead)."""
        rep = next((o for o in outs if o is not None), None)
        if rep is None:     # fetch-less step: track a state leaf instead
            import jax
            leaves = jax.tree_util.tree_leaves(new_opt_states)
            rep = leaves[0] if leaves else None
        if rep is None:
            return
        self._async_pending.append(rep)
        # LOCKSTEP with _async_pending (None when tracing was off at
        # dispatch): the fids pop positionally against the handles, so
        # a mid-run enable must not shift every later arrow onto the
        # wrong dispatch
        self._async_fids.append(
            _TRACE.flow_begin("async_step", cat="async")
            if _TRACE.on else None)
        if len(self._async_pending) > self._async_window:
            from ..metrics import record_run_plan
            record_run_plan("async_sync_points")
            fid = self._async_fids.popleft() if self._async_fids else None
            if fid is not None and _TRACE.on:
                _TRACE.flow_end("async_step", fid)
            _block_one(self._async_pending.popleft())

    def _drain_async(self):
        """Force every in-flight async step to completion (counted as one
        sync point when anything was actually in flight) — called by the
        boundaries whose correctness needs a quiesced device: checkpoint
        saves and explicit flushes."""
        from .. import race as _race
        if _race.ACTIVE is not None:   # ISSUE 14 preemption point
            _race.point("exec.drain_async")
        if not self._async_pending:
            return
        from ..metrics import record_run_plan
        record_run_plan("async_sync_points")
        while self._async_pending:
            fid = self._async_fids.popleft() if self._async_fids else None
            if fid is not None and _TRACE.on:
                _TRACE.flow_end("async_step", fid)
            _block_one(self._async_pending.popleft())

    def logOut(self, path, clear=True):
        """Write recorded step timings (reference Executor.logOut:548)."""
        with open(path, "a") as f:
            for name, times in self.timer_logs.items():
                for t in times:
                    f.write(f"{name}\t{t:.3f} ms\n")
        if clear:
            self.clearTimer()

    def clearTimer(self):
        self.timer_logs = {}

    def recordLoads(self):
        """Dump PS key-access loads (reference Executor.recordLoads:543)."""
        from ..ps import default_store
        return default_store().get_loads()

    def profile(self, name="default", feed_dict=None, log_file=None):
        return self.subexecutors[name].profile(feed_dict or {}, log_file)

    def export_step(self, name="default"):
        """Export the subgraph as a pure jittable function + example args.

        Returns ``(fn, example_args)`` where ``fn(tparams, sparams,
        opt_states, feeds, key, step_idx, lrs)`` is the exact step the
        executor jits (params update + state side-channel included; the
        5th output is ``step_idx + 1`` — the device-chained step
        counter).  Feeds in the example args are zeros of the
        dataloader/placeholder shapes.
        """
        import jax
        sub = self.subexecutors[name]
        if sub.ps_nodes:
            raise NotImplementedError(
                "export_step on a subgraph with PS embeddings is unsupported "
                "(row values are pulled host-side per step)")
        from ..data.dataloader import DataloaderOp
        feeds = {}
        for node in sub.feed_nodes:
            if isinstance(node, DataloaderOp):
                arr = np.zeros(node.get_cur_shape(name), np.float32)
            else:
                if node.shape is None:
                    raise ValueError(
                        f"feed {node} needs a static shape for export; "
                        "pass shape= to placeholder_op")
                arr = np.zeros(node.shape, node.dtype or np.float32)
            feeds[self._k(node)] = arr
        tparams, sparams = sub._pack_state()
        opt_states = {self._k(op): self.opt_states[op] for op in sub.opt_ops}
        # host lrs cover only the data-dependent schedules; traced ones
        # live inside the step (graph/run_plan.py)
        lrs = sub._host_lrs(0)
        key = jax.random.key(self.seed)
        if sub._jit is None:
            sub._build_step()
        # _step_fn is the raw pure step (the executor's own jit adds
        # donation); step_idx is int32 like the live step passes it (the
        # x64-canonicalization note in SubExecutor.run)
        return sub._step_fn, (tparams, sparams, opt_states, feeds, key,
                              np.int32(0), lrs)

    def get_batch_num(self, name="default"):
        from ..data.dataloader import DataloaderOp
        nums = [n.get_batch_num(name) for n in self.subexecutors[name].feed_nodes
                if isinstance(n, DataloaderOp)]
        return min(nums) if nums else None

    @property
    def rank(self):
        import jax
        return jax.process_index()

    @property
    def config(self):
        return self

    def _ps_async_push(self, node, grad):
        from concurrent.futures import ThreadPoolExecutor
        if self._ps_pool is None:
            self._ps_pool = ThreadPoolExecutor(max_workers=1)
        # bounded in-flight window: eventual consistency, bounded
        # staleness; completed futures are RESULT-ed (not just dropped) so
        # a failing background push raises at the next step instead of
        # silently losing gradients
        pending = []
        for f in self._ps_futures:
            if f.done():
                f.result()
            else:
                pending.append(f)
        self._ps_futures = pending
        while len(self._ps_futures) >= 32:
            self._ps_futures.pop(0).result()
        # ids are captured NOW: by the time the worker runs, the next step
        # may already have overwritten node._last_ids (via pull or prefetch
        # consumption) — a deferred read would push step-N grads onto
        # step-N+1's rows
        ids = node._last_ids
        self._ps_futures.append(self._ps_pool.submit(
            lambda: node.push_to(ids, np.asarray(grad))))

    def ps_flush(self):
        """Barrier: wait until every ASP async push has been applied."""
        for f in self._ps_futures:
            f.result()
        self._ps_futures = []

    def _flush_ps_caches(self):
        """Push every embedding cache's accumulated (push-bound-pending)
        grads to the store.  Save paths call this after :meth:`ps_flush`:
        PS tables persist SERVER-side, so grads still sitting in a client
        cache would otherwise be absent from the checkpoint — and lost
        entirely when a preempted process resumes from it.  Not part of
        ``ps_flush`` itself: that runs on per-step multiprocess barriers,
        where a forced flush would defeat ``push_bound``."""
        flushed = set()
        for se in self.subexecutors.values():
            for node in getattr(se, "ps_nodes", []):
                cache = getattr(node, "cache", None)
                if cache is not None and id(cache) not in flushed \
                        and hasattr(cache, "flush"):
                    flushed.add(id(cache))
                    cache.flush()

    # -- fault tolerance: auto-checkpoint, preemption, resume --------------

    def _post_step(self, training):
        """Step-boundary hooks: periodic auto-save, chaos schedule tick,
        PS redundancy repair, deferred preemption handling.  Called by
        SubExecutor.run AFTER the state swap, so everything below sees a
        consistent step."""
        if training:
            if self.auto_save_dir and self.auto_save_every > 0 \
                    and self.step_counter % self.auto_save_every == 0:
                self._auto_save()
            inj = _chaos_active()
            if inj is not None:
                # the injected kill lands AFTER this step's auto-save: a
                # schedule's `kill:ps@rank<r>:step<s>` is reproducibly
                # "step s completed, then the server died"
                inj.on_step(self.step_counter)
            if self._has_ps:    # dense graphs skip the PS repair hooks
                self._tick_re_replication()
        if self._preempt_signum is not None:
            self._handle_preemption()

    def _tick_re_replication(self):
        """Background re-replication driver (HETU_PS_REREPLICATE_EVERY
        steps, 0 = off): after a PS failover left a shard running without
        its backup, each tick asks every replicated store this executor's
        graphs use to try restoring redundancy onto the relaunched
        holder — a still-dead target defers quietly
        (``ps_re_replicate_deferred``) to the next tick, a repaired shard
        makes a SECOND failure survivable with no operator action."""
        import os as _os
        every = int(_os.environ.get("HETU_PS_REREPLICATE_EVERY", "0"))
        if every <= 0 or self.step_counter % every != 0:
            return
        seen = set()
        for se in self.subexecutors.values():
            for node in getattr(se, "ps_nodes", []):
                store = getattr(node, "store", None)
                if store is None or id(store) in seen \
                        or not hasattr(store, "maybe_re_replicate"):
                    continue
                seen.add(id(store))
                store.maybe_re_replicate()

    def _install_signal_handlers(self):
        """SIGTERM/SIGINT → one final emergency save, then the previous
        disposition.  Main-thread only (signal module constraint); the
        previous handlers are chained, not clobbered.  The registered
        handler holds only a WEAK reference to this executor — the signal
        module must not pin a dead executor (and its full parameter
        state) in memory; once collected, the handler falls through to
        the previous disposition."""
        import signal
        import threading
        import weakref
        if threading.current_thread() is not threading.main_thread():
            return
        ref = weakref.ref(self)
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev = signal.getsignal(sig)

                def handler(signum, frame, _ref=ref, _prev=prev):
                    ex = _ref()
                    if ex is not None:
                        return ex._on_preempt(signum, frame)
                    if callable(_prev):
                        return _prev(signum, frame)
                    if _prev == signal.SIG_IGN:
                        return      # honor an explicit prior ignore
                    if signum == signal.SIGINT:
                        raise KeyboardInterrupt
                    raise SystemExit(128 + signum)

                signal.signal(sig, handler)
                self._prev_handlers[sig] = prev
                self._installed_handlers[sig] = handler
            except (ValueError, OSError):  # non-main ctx raced, or exotic
                pass                       # platform: skip, never crash

    def uninstall_signal_handlers(self):
        """Restore the previous SIGTERM/SIGINT dispositions (only where
        this executor's handler is still the installed one — a later
        executor's handler already chains to ours and must stay)."""
        import signal
        for sig, h in list(self._installed_handlers.items()):
            try:
                if signal.getsignal(sig) is h:
                    signal.signal(sig, self._prev_handlers[sig])
            except (ValueError, OSError):
                pass
            self._installed_handlers.pop(sig, None)

    def _on_preempt(self, signum, frame):
        self._preempt_signum = signum
        if not self._in_step:
            self._handle_preemption()
        # else: the in-flight step finishes; _post_step handles it at the
        # boundary where params/opt/step are consistent

    def _handle_preemption(self):
        import signal
        from ..metrics import record_fault
        signum, self._preempt_signum = self._preempt_signum, None
        record_fault("emergency_save")
        try:
            # multiprocess: save() runs COLLECTIVE fetches + barriers; a
            # signal that reached only this rank would deadlock inside
            # them.  Cross-process preemption safety comes from the
            # periodic auto-saves (every rank saves at the same step) +
            # the supervisor relaunch, not from a one-rank handler.
            if self.auto_save_dir and not self._multiprocess:
                self._auto_save()
        finally:
            prev = self._prev_handlers.get(signum)
            if callable(prev):
                prev(signum, None)   # includes default_int_handler
            elif prev == signal.SIG_IGN:
                # the process explicitly ignored this signal before we
                # chained: save-and-continue, not save-and-die
                pass
            elif signum == signal.SIGINT:
                raise KeyboardInterrupt
            else:
                raise SystemExit(128 + signum)  # 143 for SIGTERM

    def _auto_save(self):
        """One atomic checkpoint at the current step under auto_save_dir
        (idempotent per step) + keep-last-N retention."""
        import os
        from ..metrics import record_fault
        d = self.auto_save_dir
        if not d:
            return None
        final = os.path.join(d, f"ckpt-{self.step_counter:08d}")
        if not os.path.exists(os.path.join(final, "meta.json")):
            os.makedirs(d, exist_ok=True)
            self.save(final)
            record_fault("auto_save")
            self._prune_auto_saves()
        return final

    def _prune_auto_saves(self):
        import glob
        import os
        import shutil
        import jax
        if self._multiprocess and jax.process_index() != 0:
            return                      # rank 0 owns retention
        cands = sorted(p for p in glob.glob(
            os.path.join(self.auto_save_dir, "ckpt-*"))
            if os.path.isdir(p) and not p.endswith((".saving",
                                                    ".replaced")))
        complete = [p for p in cands if self._checkpoint_complete(p)]
        for stale in complete[:-max(1, self.auto_save_keep)]:
            shutil.rmtree(stale, ignore_errors=True)

    @staticmethod
    def _checkpoint_complete(path):
        """A checkpoint is COMPLETE iff its meta.json parses, declares the
        format, and every file it names exists (with the recorded size,
        when the manifest carries one).  A preemption mid-save leaves
        either no meta.json (meta is written last, atomically) or a
        manifest naming files that are missing/short — both rejected."""
        import json
        import glob
        import os
        meta_path = os.path.join(path, "meta.json")
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, json.JSONDecodeError):
            return False
        if not str(meta.get("format", "")).startswith("hetu_tpu.ckpt"):
            return False
        manifest = meta.get("manifest", {})
        names = [os.path.join("params", fn)
                 for fn in meta.get("params", {}).values()]
        for entry in meta.get("opt", []):
            names += [os.path.join("opt", fn)
                      for fn in entry.get("leaves", {}).values()]
        for rel in names:
            fp = os.path.join(path, rel)
            if not os.path.exists(fp):
                return False
            want = manifest.get(rel)
            if want is not None and os.path.getsize(fp) != want:
                return False
        for entry in meta.get("ps_tables", []):
            # per-rank shard suffixes (".shard<r>") make exact names rank-
            # dependent; existence of any file for the entry is the check
            if not glob.glob(os.path.join(path, entry["file"]) + "*"):
                return False
        return True

    def resume(self, path_or_dir):
        """Restore the newest COMPLETE checkpoint for an exact-continuation
        restart (params, optimizer state, PS rows, dataloader cursors,
        step counter).

        ``path_or_dir`` is either one checkpoint directory (meta.json
        inside) or an auto-save directory of ``ckpt-<step>`` entries —
        the newest complete one wins; incomplete/truncated ones are
        counted (``ckpt_incomplete_skipped``) and skipped.  A crash
        between the two renames of an overwriting save can strand the
        only complete copy at ``<path>.replaced``/``<path>.saving`` —
        those are probed too (at lower priority than published
        checkpoints).  Returns the restored step, or None when nothing
        loadable exists (caller starts fresh)."""
        import glob
        import os
        import warnings as _warnings
        from ..metrics import record_fault

        def _try(cand, count_incomplete=False):
            if not os.path.isdir(cand):
                return False
            if not self._checkpoint_complete(cand):
                if count_incomplete:
                    record_fault("ckpt_incomplete_skipped")
                    _warnings.warn(f"skipping incomplete checkpoint "
                                   f"{cand}", RuntimeWarning)
                return False
            self.load(cand)
            record_fault("resume")
            return True

        # a single checkpoint path, or its rename-crash remnants
        for cand in (path_or_dir, str(path_or_dir) + ".saving",
                     str(path_or_dir) + ".replaced"):
            if os.path.exists(os.path.join(cand, "meta.json")) \
                    and _try(cand):
                return self.step_counter
        if os.path.isdir(path_or_dir):
            import re

            def order(c):
                # newest step first; a published dir outranks a stranded
                # remnant of the SAME step, but a stranded newer step
                # (complete, just never renamed into place) beats an
                # older published one — it is the more exact restore
                m = re.search(r"ckpt-(\d+)", os.path.basename(c))
                published = not c.endswith((".saving", ".replaced"))
                return (int(m.group(1)) if m else -1, published)

            for cand in sorted(glob.glob(
                    os.path.join(path_or_dir, "ckpt-*")),
                    key=order, reverse=True):
                # an incomplete .saving remnant is the EXPECTED shape of
                # a preempted save, not an anomaly worth counting
                if _try(cand, count_incomplete=not cand.endswith(
                        (".saving", ".replaced"))):
                    return self.step_counter
        return None

    def __del__(self):
        if getattr(self, "_installed_handlers", None):
            try:
                self.uninstall_signal_handlers()
            except Exception:
                pass
        pool = getattr(self, "_ps_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
        closed = set()
        for se in getattr(self, "subexecutors", {}).values():
            pp = getattr(se, "_prefetch_pool", None)
            if pp is not None:
                pp.shutdown(wait=False)
            fp = getattr(se, "_feed_pool", None)
            if fp is not None:
                fp.shutdown(wait=False)
            # embedding caches owned by this graph: flush pending grads
            # and release their resources (CacheSparseTable leaked its
            # per-table ThreadPoolExecutor without this)
            for node in getattr(se, "ps_nodes", []):
                cache = getattr(node, "cache", None)
                if cache is None or id(cache) in closed \
                        or not hasattr(cache, "close"):
                    continue
                closed.add(id(cache))
                try:
                    cache.close()
                except Exception:
                    pass

    def _opt_rename_maps(self, op):
        """(nodekey→param-name, param-name→nodekey) for one optimizer op —
        node keys ('n<id>') are process-local; param names are the stable
        checkpoint identity."""
        fwd = {self._k(p): self.var_names[p] for p in op.params}
        return fwd, {v: k for k, v in fwd.items()}

    @staticmethod
    def _rename_dict_keys(tree, ren):
        if isinstance(tree, dict):
            return {ren.get(k, k): Executor._rename_dict_keys(v, ren)
                    for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            return type(tree)(Executor._rename_dict_keys(v, ren)
                              for v in tree)
        return tree

    def _named_opt_state(self, op, st):
        return self._rename_dict_keys(st, self._opt_rename_maps(op)[0])

    def _unname_opt_state(self, op, st):
        return self._rename_dict_keys(st, self._opt_rename_maps(op)[1])

    def _dataloader_sites(self):
        """Distinct DataloaderOps across subgraphs, stable graph order —
        their positions are training state (an exact resume must continue
        at the NEXT batch, not restart the epoch)."""
        from ..data.dataloader import DataloaderOp
        seen, sites = set(), []
        for name in sorted(self.subexecutors):
            se = self.subexecutors[name]
            nodes = list(getattr(se, "feed_nodes", [])) \
                + [n.ids_node for n in getattr(se, "ps_nodes", [])]
            for node in nodes:
                if isinstance(node, DataloaderOp) and id(node) not in seen:
                    seen.add(id(node))
                    sites.append(node)
        return sites

    def _ps_table_sites(self):
        """Distinct (store, table) pairs across all subgraphs, in a stable
        graph order — the ordinal is the checkpoint identity of a table."""
        seen, sites = set(), []
        for name in sorted(self.subexecutors):
            for node in getattr(self.subexecutors[name], "ps_nodes", []):
                key = (id(node.store), node.table)
                if key not in seen:
                    seen.add(key)
                    sites.append(node)
        return sites

    def _place_opt_leaf(self, op, leaf):
        """Place a restored optimizer-state leaf: slab-shaped leaves of a
        ZeRO-planned optimizer go back dp-SHARDED (a replicated restore
        would silently pay the full moment memory the plan exists to
        shed); everything else replicates like a param."""
        plan = self._zero_plans.get(op)
        if plan is not None and getattr(leaf, "ndim", 0) == 2:
            from ..parallel import zero as _zero
            if tuple(leaf.shape) in {(b.dp, b.width) for b in plan.buckets}:
                return self._global_put(np.asarray(leaf),
                                        _zero.slab_sharding(self.mesh))
        return self._place_param(leaf)

    def _fetch_host(self, v):
        """Host copy of a (possibly cross-process-sharded) tensor.

        On a multi-process mesh this is a COLLECTIVE for non-addressable
        arrays (allgather) — every rank must call it, even ranks that then
        discard the result (save gates the file writes on rank 0)."""
        import jax
        if isinstance(v, _ZeroView):    # stage-3 ZeRO: gather from slab
            return v.materialize()
        if not self._multiprocess or getattr(v, "is_fully_addressable", True):
            return np.asarray(v)
        if getattr(v, "is_fully_replicated", False):
            return np.asarray(v.addressable_data(0))
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(v, tiled=True))

    def save(self, path, file=None):
        """Checkpoint params + optimizer state + PS tables + step.

        Default format is a DIRECTORY with one .npy per tensor (streamed —
        at no point is the whole state in host memory at once) and PS
        tables persisted server-side by their own store (per-host shard
        files under a DistributedStore — reference per-server SaveParam,
        ``ps-lite/src/python_binding.cc:111-118``).  The reference's save
        (:461) loses optimizer state; we keep it (SURVEY.md §5.4).
        ``file=`` selects the legacy single-pickle blob instead.

        Multiprocess: EVERY rank must call save (tensor fetches are
        collectives and each rank persists its own PS shard) but only rank
        0 writes params/opt/meta — concurrent same-path np.save from
        several local ranks interleaves and corrupts tensors.

        Atomicity (preemption-safe): the directory format is assembled in
        ``<path>.saving`` and PUBLISHED by one rename, with meta.json
        written last + atomically and carrying a size manifest — a
        preemption at ANY point leaves either the previous checkpoint at
        ``path`` untouched or a work dir ``resume`` never considers;
        never a half-written checkpoint that validates."""
        self._drain_async()  # async stepping: quiesce before fetching
        self.ps_flush()  # ASP pushes must land before persisting
        self._flush_ps_caches()  # cache-pending grads too: tables persist
        import json                 # server-side
        import os
        import shutil
        import jax
        rank0 = not self._multiprocess or jax.process_index() == 0
        path = os.path.normpath(path)
        if file is not None:    # legacy single-file blob (atomic replace)
            os.makedirs(path, exist_ok=True)
            blob = {
                "params": {self.var_names[n]: self._fetch_host(v)
                           for n, v in self.var_values.items()},
                "opt_states": {op.name: jax.tree.map(self._fetch_host, st)
                               for op, st in self.opt_states.items()},
                "step": self.step_counter,
            }
            if rank0:
                tmp = os.path.join(path, file + ".tmp")
                with open(tmp, "wb") as f:
                    pickle.dump(blob, f)
                os.replace(tmp, os.path.join(path, file))
            return
        work = path + ".saving"
        if rank0 and os.path.exists(work):  # leftovers of a preempted save
            shutil.rmtree(work)
        # ranks write PS shards into the SAME work dir: nobody may write
        # before rank 0's cleanup, and rank 0 must not publish before
        # everybody finished writing — hence the barriers
        self._save_barrier("clean")
        os.makedirs(os.path.join(work, "params"), exist_ok=True)
        os.makedirs(os.path.join(work, "opt"), exist_ok=True)
        meta = {"format": "hetu_tpu.ckpt.v1", "step": self.step_counter,
                "seed": self.seed, "params": {}, "opt": [],
                "ps_tables": [], "manifest": {}}

        def _persist(rel, host_val):
            fp = os.path.join(work, rel)
            np.save(fp, host_val)
            # np.save appends .npy only when missing; rel always has it
            meta["manifest"][rel] = os.path.getsize(fp)

        for i, (n, v) in enumerate(self.var_values.items()):
            fn = f"p{i}.npy"
            hv = self._fetch_host(v)        # collective: all ranks
            if rank0:
                _persist(os.path.join("params", fn), hv)
            meta["params"][self.var_names[n]] = fn
        for k, (op, st) in enumerate(self.opt_states.items()):
            named = self._named_opt_state(op, st)
            leaves = {}
            for j, (kpath, leaf) in enumerate(
                    jax.tree_util.tree_flatten_with_path(named)[0]):
                fn = f"o{k}_{j}.npy"
                hl = self._fetch_host(leaf)  # collective: all ranks
                if rank0:
                    _persist(os.path.join("opt", fn), hl)
                leaves[jax.tree_util.keystr(kpath)] = fn
            meta["opt"].append({"name": op.name, "leaves": leaves})
        for i, node in enumerate(self._ps_table_sites()):
            if not hasattr(node.store, "save"):
                continue
            fn = f"ps{i}.bin"
            # a DistributedStore (has a .server) self-suffixes .shard{rank}
            # — every rank persists its own shard.  A plain per-process
            # EmbeddingStore writes ONE path: rank 0 only (contents are
            # replicated by the one-pusher gating), or concurrent ranks
            # would interleave into the same file.
            if hasattr(node.store, "server") or rank0:
                node.store.save(node.table, os.path.join(work, fn))
            meta["ps_tables"].append({"file": fn, "node": node.name})
        meta["dataloaders"] = [
            {split: dl.state_dict() for split, dl in op.dataloaders.items()}
            for op in self._dataloader_sites()]
        # meta must land after EVERY rank's writes (PS shards included):
        # without this barrier a crash could leave a meta.json that
        # validates next to another rank's still-truncated shard file
        self._save_barrier("written")
        if rank0:
            tmp = os.path.join(work, "meta.json.tmp")
            with open(tmp, "w") as f:  # meta last + atomic: marks a
                json.dump(meta, f, indent=1)    # complete checkpoint
            os.replace(tmp, os.path.join(work, "meta.json"))
        self._save_barrier("meta")
        if rank0:
            if os.path.exists(path):
                # overwrite: two renames (dirs can't os.replace); a crash
                # between them leaves the complete old copy at .replaced
                old = path + ".replaced"
                if os.path.exists(old):
                    shutil.rmtree(old)
                os.rename(path, old)
                os.rename(work, path)
                shutil.rmtree(old, ignore_errors=True)
            else:
                os.rename(work, path)
        self._save_barrier("published")

    def _save_barrier(self, tag):
        """Cross-rank ordering for the shared-work-dir save protocol."""
        if not self._multiprocess:
            return
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(
            f"hetu-save-{tag}-{self.step_counter}")

    def save_orbax(self, path):
        """Orbax-format checkpoint — the JAX-ecosystem standard format,
        as an optional alternative to the native streamed-npy format
        (``save``); lets orbax-based tooling (inspection, cloud copies,
        emergency-restore pipelines) consume hetu_tpu state directly.

        The tree is {"params": {name: array}, "opt": {ordinal: named
        state}, "ps": {ordinal: row matrix}, "step": int} — the same
        name/ordinal identities ``load`` uses, so the two formats are
        semantically interchangeable for params, optimizer state, the
        step counter AND the PS embedding rows.  The one asymmetry:
        server-side PS optimizer slots/versions live only in the native
        format (``save`` persists full table state through the store's
        own ``save``); the orbax tree carries the ROW DATA, i.e. a
        restored Adam PS table warm-starts its server moments.
        Single-process convenience: multiprocess meshes should use
        ``save`` (its collective fetch + rank-0-write discipline).
        """
        import os
        import jax
        import orbax.checkpoint as ocp
        if self._multiprocess:
            raise NotImplementedError(
                "save_orbax is single-process; multiprocess meshes use "
                "save() (collective fetch + rank-0 writes)")
        self._drain_async()
        self.ps_flush()
        self._flush_ps_caches()
        tree = {
            "params": {self.var_names[n]: self._fetch_host(v)
                       for n, v in self.var_values.items()},
            "opt": {str(i): jax.tree.map(
                self._fetch_host, self._named_opt_state(op, st))
                for i, (op, st) in enumerate(self.opt_states.items())},
            "step": self.step_counter,
        }
        ps = {}
        for i, node in enumerate(self._ps_table_sites()):
            if not hasattr(node.store, "get_data"):
                raise NotImplementedError(
                    f"save_orbax cannot serialize PS table of "
                    f"'{node.name}': store "
                    f"{type(node.store).__name__} exposes no get_data — "
                    f"use save() (server-side table persistence)")
            ps[str(i)] = np.asarray(node.store.get_data(node.table))
        if ps:
            tree["ps"] = ps
        ocp.PyTreeCheckpointer().save(os.path.abspath(path), tree,
                                      force=True)

    def load_orbax(self, path, params_only=False):
        """Restore a ``save_orbax`` checkpoint (params by name, optimizer
        state and PS tables by ordinal; ``params_only=True`` is the
        warm-start form — like ``load`` it still restores the PS
        embedding rows, leaving optimizer moments and the step counter
        fresh)."""
        import os
        import orbax.checkpoint as ocp
        import jax
        tree = ocp.PyTreeCheckpointer().restore(os.path.abspath(path))
        self.load_dict(tree.get("params", {}))
        # PS rows restore in BOTH forms — symmetric with load(), whose
        # params_only branch also reloads the ps table files
        for i, node in enumerate(self._ps_table_sites()):
            rows = (tree.get("ps") or {}).get(str(i))
            if rows is None:
                continue     # older checkpoint without a ps subtree
            if not hasattr(node.store, "set_data"):
                # mirror save_orbax's loudness: dropping checkpointed
                # rows on the floor would "warm-start" from fresh
                # random embeddings with nothing pointing at the restore
                raise NotImplementedError(
                    f"load_orbax cannot restore PS table of "
                    f"'{node.name}': store "
                    f"{type(node.store).__name__} exposes no set_data — "
                    f"use load() (server-side table persistence)")
            node.store.set_data(node.table, np.asarray(rows))
        if params_only:
            return
        for i, (op, live) in enumerate(list(self.opt_states.items())):
            named = tree.get("opt", {}).get(str(i))
            if named is None:
                continue
            named_live = self._named_opt_state(op, live)
            paths, treedef = jax.tree_util.tree_flatten_with_path(
                named_live)
            saved = {jax.tree_util.keystr(kp): leaf for kp, leaf in
                     jax.tree_util.tree_flatten_with_path(named)[0]}
            leaves = [saved.get(jax.tree_util.keystr(kp), old)
                      for kp, old in paths]
            self.opt_states[op] = self._unname_opt_state(
                op, jax.tree.unflatten(
                    treedef, [self._place_opt_leaf(op, l) for l in leaves]))
        self.step_counter = int(tree.get("step", 0))

    def load(self, path, file=None, consider_splits=False,
             params_only=False):
        """Restore a checkpoint.  ``params_only=True`` is the WARM-START
        form (pretrain → fine-tune): it restores parameters (and PS
        embedding rows) by name and leaves optimizer moments, the step
        counter, and dataloader cursors at their fresh state — a full
        restore would resume the pretrain LR schedule mid-curve and
        apply stale Adam second moments to the new task."""
        import json
        import os
        import jax
        meta_path = os.path.join(path, "meta.json") \
            if os.path.isdir(path) else None
        if file is None and meta_path and os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            by_name = {self.var_names[n]: n for n in self.var_values}
            # streamed one tensor at a time, except stage-3 ZeRO params:
            # those accumulate and land as ONE slab write per bucket (the
            # transient host copy is bounded by the slab total)
            pending = {}
            for name, fn in meta["params"].items():
                node = by_name.get(name)
                if node is None:
                    continue
                val = np.load(os.path.join(path, "params", fn))
                if node in self._zero_covered:
                    pending[node] = val
                else:
                    self._set_var_host(node, val)
            if pending:
                self._set_vars_host(pending)
            if params_only:
                entries = {e["file"] for e in meta["ps_tables"]}
                for i, node in enumerate(self._ps_table_sites()):
                    fn = f"ps{i}.bin"
                    if fn in entries and hasattr(node.store, "load"):
                        node.store.load(node.table,
                                        os.path.join(path, fn))
                return
            # optimizer states match by ORDINAL (graph order is the stable
            # identity; auto-generated op names are not) and leaves match
            # by param-name-translated tree path (raw paths embed node-id
            # keys, which differ across processes)
            for entry, (op, live) in zip(meta["opt"],
                                         list(self.opt_states.items())):
                named_live = self._named_opt_state(op, live)
                paths, treedef = jax.tree_util.tree_flatten_with_path(
                    named_live)
                host_leaves, missed = [], []
                for kpath, old_leaf in paths:
                    fn = entry["leaves"].get(jax.tree_util.keystr(kpath))
                    if fn is None:
                        missed.append(jax.tree_util.keystr(kpath))
                        host_leaves.append(old_leaf)
                    else:
                        host_leaves.append(
                            np.load(os.path.join(path, "opt", fn)))
                if not missed:
                    # dp portability (elastic resizes change the world
                    # between save and restore): slab moments written
                    # under a different dp transcode to this world's
                    # bucket layout instead of failing shape placement
                    tree = self._maybe_transcode_loaded_opt(
                        op, jax.tree.unflatten(treedef, host_leaves))
                    host_leaves = jax.tree_util.tree_leaves(tree)
                leaves = [self._place_opt_leaf(op, leaf)
                          if isinstance(leaf, np.ndarray) else leaf
                          for leaf in host_leaves]
                if missed and entry["leaves"]:
                    # ZeRO slab state is keyed by bucket layout: loading
                    # across a zero-stage / graph-structure change finds
                    # no matching leaves and would otherwise resume with
                    # FRESH moments silently
                    warnings.warn(
                        f"checkpoint optimizer state for '{op.name}': "
                        f"{len(missed)}/{len(paths)} live leaves absent "
                        f"from the checkpoint (e.g. {missed[0]}) — "
                        "keeping existing values. A ZeRO stage or "
                        "bucket-layout mismatch between save and load "
                        "resumes with fresh moments.")
                self.opt_states[op] = self._unname_opt_state(
                    op, jax.tree.unflatten(treedef, leaves))
            entries = {e["file"] for e in meta["ps_tables"]}
            for i, node in enumerate(self._ps_table_sites()):
                fn = f"ps{i}.bin"
                if fn in entries and hasattr(node.store, "load"):
                    node.store.load(node.table, os.path.join(path, fn))
            for op, states in zip(self._dataloader_sites(),
                                  meta.get("dataloaders", [])):
                for split, st in states.items():
                    if split in op.dataloaders:
                        op.dataloaders[split].load_state(st)
            self.step_counter = meta.get("step", 0)
            return
        if os.path.isdir(path):
            path = os.path.join(path, file or "checkpoint.hetu")
        with open(path, "rb") as f:
            blob = pickle.load(f)
        self.load_dict(blob["params"])
        if params_only:
            return
        ops = list(self.opt_states)
        by_name = {op.name: op for op in ops}
        blob_states = list(blob.get("opt_states", {}).items())
        matched = [by_name.get(name) for name, _ in blob_states]
        if not any(op is not None for op in matched) \
                and len(blob_states) == len(ops):
            # auto-generated OptimizerOp names embed a process-global
            # counter, so a same-process rebuild never name-matches —
            # fall back to graph order (the dir format's identity)
            # instead of silently resuming with fresh moments.  Only
            # when NO name matched: under partial overlap, positionally
            # installing the leftovers could cross-wire one optimizer's
            # moments into another
            matched = ops
        for op, (name, st) in zip(matched, blob_states):
            if op is None:
                continue
            # slab-shaped leaves of a ZeRO-planned optimizer go back
            # dp-SHARDED (_place_opt_leaf) — a replicated restore of the
            # moments would pay the full dp x memory the plan exists to
            # shed, at exactly the resume moment
            self.opt_states[op] = jax.tree.map(
                lambda l, op=op: self._place_opt_leaf(op, l), st)
        self.step_counter = blob.get("step", 0)

    def load_dict(self, state_dict):
        by_name = {self.var_names[n]: n for n in self.var_values}
        self._set_vars_host({by_name[name]: np.asarray(val)
                             for name, val in state_dict.items()
                             if name in by_name})

    def return_tensor_values(self):
        return {self.var_names[n]: self._fetch_host(v)
                for n, v in self.var_values.items()}

    def memory_accounting(self, feed_dict=None, name=None):
        """Per-device byte accounting of the persistent training state —
        the numbers the ZeRO memory claim is judged on (``bench.py``
        artifact schema; works on CPU where ``memory_stats`` reports
        nothing).

        * ``param_bytes_per_device`` — full per-param master arrays
          (replicated: each device pays all of it).  Stage-3 ZeRO params
          live in slabs and are counted there instead.
        * ``zero_slab_bytes_per_device`` — dp-sharded master slabs
          (each device holds 1/dp, padding included).
        * ``opt_state_bytes_per_device`` — optimizer moments etc.;
          dp-sharded leaves count their one-device shard only.
        * ``grad_bytes_per_device`` — ANALYTIC layout of the transient
          backward output: full per-param unless the plan pins the grad
          slab sharded (stage >= 2).
        * ``live_buffer_bytes_per_device`` — every live jax array's
          worst-device residency (process-wide).
        * ``peak_hbm_gb`` — backend-reported peak, None where the
          backend (XLA-CPU) keeps no stats.

        With ``feed_dict`` (ISSUE 13 — the remat claims' evidence) two
        more keys land, from XLA's own buffer assignment of the compiled
        step (AOT compile; hits jax's jit cache after the first run, so
        this is cheap on a warm executor):

        * ``step_temp_bytes_per_device`` — the compiled step's TEMP
          allocation (``memory_analysis().temp_size_in_bytes``): the
          transient activation/workspace peak INSIDE one step, which
          between-steps live-array sums cannot see — exactly what
          ``remat=`` trades.  None where the backend/tunnel does not
          answer AOT analysis.
        * ``live_buffer_peak_bytes_per_device`` — live buffers + step
          temp: the projected worst in-step residency.
        """
        import jax

        def per_dev(arr):
            if isinstance(arr, _ZeroView):
                return 0            # master bytes counted under the slab
            shards = getattr(arr, "addressable_shards", None)
            if shards:
                by_dev = {}
                for s in shards:
                    by_dev[s.device.id] = \
                        by_dev.get(s.device.id, 0) + s.data.nbytes
                return max(by_dev.values())
            return int(getattr(arr, "nbytes", 0))

        params = sum(per_dev(v) for v in self.var_values.values())
        slabs = sum(per_dev(v) for v in self._zero_slabs.values())
        opt = sum(per_dev(leaf) for st in self.opt_states.values()
                  for leaf in jax.tree_util.tree_leaves(st))
        grads = 0
        from ..optim.optimizer import OptimizerOp
        for node in self.global_topo:
            if not isinstance(node, OptimizerOp):
                continue
            plan = self._zero_plans.get(node)
            if plan is None:
                grads += sum(
                    int(np.prod(p.shape, dtype=np.int64))
                    * np.dtype(getattr(self.var_values.get(p), "dtype",
                                       np.float32)).itemsize
                    for p in node.params if p.shape is not None)
            else:
                for b in plan.buckets:
                    grads += b.nbytes // (plan.dp if plan.stage >= 2 else 1)
        try:
            live = sum(per_dev(a) for a in jax.live_arrays())
        except Exception:
            live = None
        peak = None
        try:
            st = jax.devices()[0].memory_stats() or {}
            peak = round(st.get("peak_bytes_in_use", 0) / 2**30, 3) or None
        except Exception:
            pass
        out = {
            "n_devices": len(jax.devices()),
            "zero_stage": self.zero if self._zero_plans else 0,
            "param_bytes_per_device": int(params),
            "zero_slab_bytes_per_device": int(slabs),
            "opt_state_bytes_per_device": int(opt),
            "grad_bytes_per_device": int(grads),
            "live_buffer_bytes_per_device": live,
            "peak_hbm_gb": peak,
        }
        if feed_dict is not None:
            temp = None
            try:
                from ..profiler import HetuProfiler
                sub_name = name or ("train" if "train" in
                                    self.subexecutors
                                    else next(iter(self.subexecutors)))
                ma = HetuProfiler(self, name=sub_name) \
                    ._compiled(feed_dict).memory_analysis()
                temp = int(ma.temp_size_in_bytes)
            except Exception:
                temp = None
            out["step_temp_bytes_per_device"] = temp
            out["live_buffer_peak_bytes_per_device"] = \
                None if (temp is None or live is None) else live + temp
        return out

    def remat_plan(self, name=None):
        """The resolved selective-remat plan (``parallel/remat.py``).

        Returns ``{"policy": ..., "plans": {subgraph: plan report}}``;
        with ``name``, just that subgraph's report (or None).  Plans
        exist only for the segmented policies (``'full'``/``'auto'``) on
        differentiating subgraphs — the wrap policies (``'dots'``/
        ``'offload'``) have no per-segment decisions to report."""
        plans = {}
        for sname, sub in self.subexecutors.items():
            plan = getattr(sub, "_remat_plan", None)
            if plan is not None:
                plans[sname] = plan.report()
        if name is not None:
            return plans.get(name)
        return {"policy": self.remat, "plans": plans}


# reference-parity no-op shims (MPI/PS boilerplate not needed under XLA SPMD)
def worker_init():
    pass


def worker_finish():
    pass


def server_init():
    pass


def server_finish():
    pass


def scheduler_init():
    pass


def scheduler_finish():
    pass
