"""Reverse-mode autodiff over the symbolic graph.

The reference implements graph-transform autodiff with one hand-written
``gradient()`` rule per op (``gpu_ops/executor.py:1071-1189``).  TPU-native
redesign: gradients are *symbolic markers* resolved by the executor with
``jax.grad`` over the lowered forward function — one fused backward XLA
computation, correct for every op that has a JAX lowering, no per-op rules.
The user-facing contract is identical: ``ht.gradients(loss, [w1, w2])``
returns graph nodes that can be fetched or fed to an optimizer.
"""
from __future__ import annotations

from .node import Op


class GradientOp(Op):
    """Marker node: d(loss)/d(wrt). Resolved inside the executor's jitted step."""

    op_type = "Gradient"

    def __init__(self, loss, wrt, name=None):
        super().__init__([loss, wrt], name=name or f"grad_{wrt.name}")
        self.loss = loss
        self.wrt = wrt

    def lower(self, ctx, *vals):  # resolved specially by the executor
        raise RuntimeError("GradientOp must be resolved by the executor")

    def infer_shape(self, input_shapes):
        return input_shapes[1]


def gradients(loss, node_list, insert_grad=None):
    """Return gradient nodes of ``loss`` w.r.t. each node in ``node_list``.

    Parity with reference ``ht.gradients`` (executor.py:1071). ``insert_grad``
    (initial output cotangent) is accepted for API parity.
    """
    del insert_grad
    return [GradientOp(loss, n) for n in node_list]
