"""Inter-op model parallelism — ``ht.context`` placement made real.

Reference path (``python/hetu/context.py:237`` per-rank ctx assignment +
``gpu_ops/PipelineSend.py:5``/``PipelineReceive.py:5`` explicit edges, demo
``examples/runner/parallel/complex_pipeline_mlp.py``): each op runs on the
device its ``ht.context(...)`` scope assigned, and activations cross devices
through explicit transfers.

TPU-native realization: arbitrary per-op device pinning inside ONE XLA
program is not SPMD, so placement is honored at *segment* granularity —
the topo is cut into maximal runs of ops sharing a ``DeviceGroup``, each
segment is jitted with its parameters committed to its device, and
activations flow segment→segment as committed arrays (XLA issues the
device-to-device copies — the reference's PipelineSend/Recv, minus the
hand-written NCCL calls).  Backward chains per-segment ``jax.vjp`` in
reverse order, so each device computes exactly its own layers' grads —
true inter-op model parallelism: no device ever materialises another
segment's weights.

For the SPMD/homogeneous-stage path (overlapped microbatches) use
``ht.parallel.pipeline_block``; this module covers the reference's manual
heterogeneous placement semantics.
"""
from __future__ import annotations

import numpy as np

from .node import PlaceholderOp, LowerCtx

__all__ = ["detect_interop", "InterOpSubExecutor"]


def _node_dev(node, dev_of):
    return dev_of.get(node)


def detect_interop(topo):
    """True if any non-placeholder op carries an ``ht.context`` placement."""
    return any(n.raw_ctx is not None and not isinstance(n, PlaceholderOp)
               for n in topo)


def _resolve_device(dctx):
    """DLContext -> concrete jax device."""
    import jax
    if dctx.is_host:
        return jax.devices("cpu")[0]
    devs = jax.devices()
    if dctx.device_id >= len(devs):
        raise ValueError(
            f"ht.context device {dctx} out of range: {len(devs)} devices")
    return devs[dctx.device_id]


class InterOpSubExecutor:
    """Executes a placed (raw_ctx) subgraph as a chain of per-device jits.

    Supports the reference's manual-placement training flow: feeds,
    variables, one loss, one optimizer, fetches.  The segment chain must be
    *linear* (every cross-segment edge goes forward), the same contract the
    reference's manual pipeline examples satisfy.

    A ``DeviceGroup`` with SEVERAL devices gives that segment its own
    data-parallel width — the reference's *heterogeneous-DP pipeline*
    (``pipeline_subexecutor.py:83-106``): stage activations shard their
    batch dim over the segment's private 1-D mesh, boundary transfers
    reshard between differently-sized stages (subsuming the gcd-cycle
    routing schedule — see ``parallel.pipeline.heterogeneous_dp_schedule``
    for the reference's explicit order), and parameter grads come out
    replicated within the group (XLA inserts the cross-replica psum).
    """

    def __init__(self, name, fetches, executor):
        import jax
        from .node import topo_sort
        from ..optim.optimizer import OptimizerOp
        from .gradients import GradientOp

        self.name = name
        self.ex = executor
        self.fetches = list(fetches)
        self.topo = topo_sort([f for f in self.fetches if f is not None])
        self.opt_ops = [n for n in self.topo if isinstance(n, OptimizerOp)]
        self.grad_ops = [n for n in self.topo if isinstance(n, GradientOp)]
        self.training = bool(self.opt_ops or self.grad_ops)
        if len(self.opt_ops) > 1:
            raise NotImplementedError("interop: one optimizer per subgraph")

        # ---- device assignment: explicit raw_ctx, else inherit from inputs
        # each ordinal is a device GROUP: len 1 = plain placement, len k =
        # this segment runs k-way data-parallel (heterogeneous-DP pipeline).
        # Segmentation is RUN-LENGTH over topo order, not dedup-by-device:
        # a chain that revisits a device (d1 → d0 → d1, the reference's
        # manual-pipeline shape, complex_pipeline_mlp.py:98-174) becomes
        # three segments executing in order, and the reverse-vjp backward
        # schedules across all of them
        self.device_groups = []
        prev_key = [None]
        dev_of = {}

        def ordinal(raw_ctx):
            devs = []
            for c in raw_ctx.contexts:
                if isinstance(c, tuple):
                    # a tuple is ONE model-parallel unit (context.py:77-78);
                    # intra-op splitting is the mesh/ht.dispatch path, not
                    # the placement chain — refuse rather than silently
                    # reinterpreting it as data parallelism
                    raise NotImplementedError(
                        "interop placement treats a DeviceGroup list as a "
                        "data-parallel group; tuple (model-parallel unit) "
                        "contexts are not supported here — use ht.dispatch "
                        "with a mesh for intra-op parallelism")
                devs.append(_resolve_device(c))
            k = tuple(repr(d) for d in devs)
            if prev_key[0] == k:
                return len(self.device_groups) - 1
            prev_key[0] = k
            self.device_groups.append(devs)
            return len(self.device_groups) - 1

        for n in self.topo:
            if isinstance(n, (OptimizerOp, GradientOp)):
                continue
            if n.raw_ctx is not None and not isinstance(n, PlaceholderOp):
                dev_of[n] = ordinal(n.raw_ctx)
            elif n.inputs:
                ins = [dev_of[i] for i in n.inputs if i in dev_of]
                dev_of[n] = max(ins) if ins else 0
            else:
                dev_of[n] = None  # leaf: placed with first consumer
        # leaves (feeds/variables) adopt their first consumer's device
        for n in self.topo:
            if dev_of.get(n) is None:
                consumers = [dev_of[c] for c in self.topo
                             if n in c.inputs and dev_of.get(c) is not None]
                dev_of[n] = min(consumers) if consumers else 0
        # NOTE: segment ordinals are nondecreasing along topo order by
        # construction (explicit placements always take the newest segment,
        # inherited nodes the max of their inputs), so every input edge
        # points backward — no chain-shape check needed.  But warn when
        # run-length segmentation fragments badly: topo-interleaved
        # independent branches on alternating devices produce one segment
        # per alternation (correct, but each boundary is a device
        # transfer + separate jit)
        distinct = len({tuple(repr(d) for d in g)
                        for g in self.device_groups}) or 1
        if len(self.device_groups) > 2 * distinct:
            import warnings
            warnings.warn(
                f"interop placement produced {len(self.device_groups)} "
                f"segments over {distinct} distinct device groups — "
                "topo-interleaved branches are fragmenting the chain; "
                "group ops per device contiguously to reduce boundary "
                "transfers")
        self.dev_of = dev_of
        self.n_segments = len(self.device_groups) or 1
        if not self.device_groups:
            self.device_groups = [[jax.devices()[0]]]
        # per-segment 1-D meshes for dp>1 groups
        self._seg_meshes = []
        for devs in self.device_groups:
            if len(devs) > 1:
                from jax.sharding import Mesh
                self._seg_meshes.append(Mesh(np.asarray(devs), ("dp",)))
            else:
                self._seg_meshes.append(None)

        # segment bodies hold compute ops only; feeds/variables enter as
        # segment parameters/external inputs
        compute = [n for n in self.topo
                   if not isinstance(n, (OptimizerOp, GradientOp,
                                         PlaceholderOp))]
        self.segments = [[n for n in compute if dev_of[n] == i]
                         for i in range(self.n_segments)]

        self.feed_nodes = [n for n in self.topo
                           if isinstance(n, PlaceholderOp) and not n.is_variable]
        losses = {g.loss for g in self.grad_ops}
        if len(losses) > 1:
            raise ValueError("multiple losses in interop subgraph")
        self.loss_node = next(iter(losses)) if losses else None
        self.trainable = sorted({g.wrt for g in self.grad_ops},
                                key=lambda n: n.id)

        # commit each variable's value to its segment device(s)
        for n in self.topo:
            if isinstance(n, PlaceholderOp) and n.is_variable:
                self.ex.var_values[n] = jax.device_put(
                    self.ex.var_values[n], self._param_target(dev_of[n]))
        self._seg_fns = None

    # ---- placement targets ----------------------------------------------
    def _param_target(self, seg):
        """Params/grads: replicated over the segment's group."""
        if self._seg_meshes[seg] is None:
            return self.device_groups[seg][0]
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self._seg_meshes[seg], P())

    def _act_target(self, seg, val):
        """Activations: batch dim sharded over the segment's dp group;
        arrays whose leading dim does not divide (broadcast rows, masks,
        ragged batches) replicate instead."""
        if self._seg_meshes[seg] is None:
            return self.device_groups[seg][0]
        from jax.sharding import NamedSharding, PartitionSpec as P
        shape = np.shape(val)
        if not shape or shape[0] % len(self.device_groups[seg]):
            return NamedSharding(self._seg_meshes[seg], P())
        return NamedSharding(self._seg_meshes[seg],
                             P("dp", *([None] * (len(shape) - 1))))

    # ---- per-segment pure functions -------------------------------------
    def _build_segments(self):
        import jax

        seg_fns = []
        for i, seg_nodes in enumerate(self.segments):
            seg_set = set(seg_nodes)
            ext_in = []      # nodes produced before this segment
            variables = []
            for n in seg_nodes:
                for a in n.inputs:
                    if a in seg_set or a in ext_in or a in variables:
                        continue
                    if isinstance(a, PlaceholderOp) and a.is_variable:
                        (variables if self.dev_of[a] == i else ext_in).append(a)
                    else:
                        ext_in.append(a)
            outs = []
            later = {n for j in range(i + 1, self.n_segments)
                     for n in self.segments[j]}
            for n in seg_nodes:
                fetched = n in self.fetches or n is self.loss_node
                if fetched or any(n in c.inputs for c in later):
                    outs.append(n)

            def seg_fn(params, ext_vals, key, training,
                       seg_nodes=seg_nodes, variables=variables,
                       ext_in=ext_in, outs=outs):
                ctx = LowerCtx(training, key, mesh=None)
                env = dict(zip(variables, params))
                env.update(dict(zip(ext_in, ext_vals)))
                for n in seg_nodes:
                    if n in env:
                        continue
                    if isinstance(n, PlaceholderOp):
                        raise ValueError(f"missing feed for {n}")
                    env[n] = n.lower(ctx, *[env[a] for a in n.inputs])
                if ctx.state_updates:
                    raise NotImplementedError(
                        "stateful ops in interop segments unsupported")
                return [env[o] for o in outs]

            seg_fns.append({"fn": seg_fn, "vars": variables,
                            "ext_in": ext_in, "outs": outs})
        self._seg_fns = seg_fns

    # ---- execution -------------------------------------------------------
    def run(self, feed_dict, convert_to_numpy_ret_vals=False, sync=True):
        # `sync` accepted for signature parity with SubExecutor.run; the
        # inter-op segment chain materializes per segment boundary, so
        # non-blocking stepping has nothing to overlap here
        import jax
        from .executor import NDArray
        ex = self.ex
        if self._seg_fns is None:
            self._build_segments()

        env = {}
        for node in self.feed_nodes:
            if node in feed_dict:
                val = feed_dict[node]
            else:
                raise ValueError(f"missing feed for {node}")
            # shared placement logic (dtype adoption, float64 downcast,
            # NDArray unwrap), then commit to the segment's device(s)
            placed = ex._place_feed(node, val)
            env[node] = jax.device_put(
                placed, self._act_target(self.dev_of[node], placed))

        key = jax.random.fold_in(ex.master_key, ex.step_counter)
        vjps = []
        for i, seg in enumerate(self._seg_fns):
            params = [ex.var_values[v] for v in seg["vars"]]
            # explicit cross-device transfer of boundary activations — the
            # reference's PipelineSend/Recv edge (PipelineSend.py:5); the
            # reshard between differently-sized dp groups is the gcd-cycle
            # routing, done by XLA resharding. Shared variables ride the
            # replicated path
            ext_vals = [jax.device_put(
                env[a] if a in env else ex.var_values[a],
                self._param_target(i)
                if (isinstance(a, PlaceholderOp) and a.is_variable)
                else self._act_target(i, env[a] if a in env
                                      else ex.var_values[a]))
                for a in seg["ext_in"]]
            k = jax.random.fold_in(key, i)

            if self.training:
                out_vals, vjp = jax.vjp(
                    lambda p, e: seg["fn"](p, e, k, True), params, ext_vals)
                vjps.append(vjp)
            else:
                out_vals = seg["fn"](params, ext_vals, k, False)
            env.update(dict(zip(seg["outs"], out_vals)))

        grads = {}
        if self.training:
            # reverse chain: seed d(loss)=1, route cotangents backward
            cot = {self.loss_node: np.ones((), np.float32)}
            for i in range(len(self._seg_fns) - 1, -1, -1):
                seg = self._seg_fns[i]
                d_outs = [cot.get(o, None) for o in seg["outs"]]
                d_outs = [jax.numpy.zeros_like(env[o]) if d is None
                          else jax.device_put(d, self._act_target(i, d))
                          for d, o in zip(d_outs, seg["outs"])]
                d_params, d_ext = vjps[i](d_outs)
                for v, g in zip(seg["vars"], d_params):
                    grads[v] = grads[v] + g if v in grads else g
                for a, g in zip(seg["ext_in"], d_ext):
                    if isinstance(a, PlaceholderOp):
                        if a.is_variable:
                            # variable shared across segments: its grad
                            # accumulates on the home device(s)
                            g = jax.device_put(
                                g, self._param_target(self.dev_of[a]))
                            grads[a] = grads[a] + g if a in grads else g
                        continue
                    # activation fan-out across segments: accumulate on the
                    # producer's device (committed arrays must agree)
                    g = jax.device_put(
                        g, self._act_target(self.dev_of[a], g))
                    if a in cot:
                        cot[a] = cot[a] + g
                    else:
                        cot[a] = g
            # optimizer update per segment (stays on each device)
            opt_op = self.opt_ops[0] if self.opt_ops else None
            if opt_op is not None:
                opt = opt_op.optimizer
                lr = opt.host_lr(ex.step_counter)
                state = ex.opt_states.setdefault(
                    opt_op, opt.init_state(
                        {ex._k(v): ex.var_values[v]
                         for v in opt_op.params}))
                p_all = {ex._k(v): ex.var_values[v] for v in opt_op.params}
                g_all = {ex._k(v): grads[v] for v in opt_op.params
                         if v in grads}
                new_p, new_state = opt.apply(p_all, g_all, state, lr)
                ex.opt_states[opt_op] = new_state
                for v in opt_op.params:
                    ex.var_values[v] = new_p[ex._k(v)]
            ex.step_counter += 1

        results = []
        for f in self.fetches:
            from .gradients import GradientOp
            if isinstance(f, GradientOp):
                val = grads.get(f.wrt)
            elif f is not None and f in env:
                val = env[f]
            else:
                val = None
            if val is None:
                results.append(None)
            elif convert_to_numpy_ret_vals:
                results.append(np.asarray(val))
            else:
                results.append(NDArray(val))
        return results

    def profile(self, feed_dict, log_file=None):
        import time
        t0 = time.perf_counter()
        self.run(feed_dict)
        return time.perf_counter() - t0
