"""Graph node (Op) base for the define-then-run frontend.

TPU-native redesign of the reference's ``python/hetu/gpu_ops/Node.py:18`` (class
``Op``): instead of each node dispatching a CUDA kernel at run time, nodes here
are *symbolic*: they record the op kind, inputs and attributes. The executor
(:mod:`hetu_tpu.graph.executor`) topologically lowers an entire fetch subgraph
into ONE pure JAX function and ``jax.jit``-compiles it, so XLA sees the whole
program and can fuse / schedule it (no per-op kernel launches, no streams, no
events — cf. SURVEY.md §3.1).

Each concrete op provides a ``lower(ctx, *jax_vals) -> jax value`` rule, which
maps to ``jax.numpy`` / ``lax`` / Pallas.  Autodiff is NOT per-op ``gradient()``
rules as in the reference (``executor.py:1071``); gradients are taken with
``jax.grad`` over the lowered function (see :mod:`hetu_tpu.graph.gradients`).
"""
from __future__ import annotations

import os
import sys

import numpy as np

# Global monotonically increasing id for deterministic topo-order tie-breaking.
_NODE_COUNTER = 0

#: package root — frames inside it are framework internals, not the user's
#: graph-building code (provenance wants the USER call site)
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _creation_site(skip=2, max_depth=25):
    """(filename, lineno, function) of the innermost frame OUTSIDE the
    hetu_tpu package — the user line that created this node.  Captured on
    every ``Op.__init__`` so graph diagnostics (``ht.lint``, executor
    ``validate=``) can say *where* a bad node came from, not just its
    auto-generated name.  A frame walk (no traceback object) keeps this
    cheap enough to run unconditionally."""
    try:
        f = sys._getframe(skip)
    except ValueError:  # pragma: no cover - shallow stack
        return None
    last = None
    for _ in range(max_depth):
        if f is None:
            break
        fn = f.f_code.co_filename
        last = (fn, f.f_lineno, f.f_code.co_name)
        if not fn.startswith(_PKG_DIR):
            return last
        f = f.f_back
    return last


def format_site(site):
    """Human-readable creation site ('file:line in func')."""
    if not site:
        return "<unknown site>"
    fn, line, func = site
    return f"{fn}:{line} in {func}"


def _next_id() -> int:
    global _NODE_COUNTER
    _NODE_COUNTER += 1
    return _NODE_COUNTER


class LowerCtx:
    """Per-build lowering context threaded through ``Op.lower``.

    Carries everything that is *not* part of the dataflow value flow:

    - ``training``: whether we are lowering the train subgraph (enables
      dropout, batch-norm stat updates, ...).
    - ``rng()``: returns a fresh ``jax.random`` key (split from the per-step
      key the executor feeds in), for dropout / stochastic ops.
    - ``state_updates``: side-channel dict ``{variable_node: new_value}`` for
      non-trainable state written during forward (e.g. BN running stats).
      The executor returns these as extra outputs and commits them to the
      variable store after the step (functional state, no mutation in trace).
    - ``mesh`` / ``axis_env``: the active device mesh (if distributed) so comm
      ops can emit sharding constraints or shard_map collectives.
    """

    def __init__(self, training: bool, base_key=None, mesh=None,
                 num_microbatches=None, pipeline=None):
        self.training = training
        self._base_key = base_key
        self._rng_count = 0
        self.state_updates = {}
        self.mesh = mesh
        # executor-level microbatch setting; pipeline_block inherits it
        # when its own n_microbatches is unset
        self.num_microbatches = num_microbatches
        # executor-level schedule choice ('gpipe' | 'pipedream' | 'hetpipe');
        # pipeline_block picks the 1F1B program for 'pipedream'
        self.pipeline = pipeline

    def rng(self):
        if self._base_key is None:
            raise RuntimeError(
                "This subgraph uses randomness (dropout etc.) but the executor "
                "did not thread a PRNG key; pass seed= to Executor.")
        import jax
        key = jax.random.fold_in(self._base_key, self._rng_count)
        self._rng_count += 1
        return key


class Op:
    """Symbolic graph node.

    Mirrors the user-facing surface of the reference ``Op``
    (``gpu_ops/Node.py:48-109`` operator overloads) so that model code written
    against ``ht.*`` ports over unchanged.
    """

    #: subclasses set this; used for naming and debugging
    op_type: str = "Op"

    def __init__(self, inputs, name=None, **attrs):
        self.id = _next_id()
        self.inputs = list(inputs)
        self.attrs = attrs
        self.name = name or f"{self.op_type}_{self.id}"
        # Provenance: the user line that created this node (diagnostics)
        self.creation_site = _creation_site()
        # Placement metadata (DeviceGroup / sharding spec); consumed by the
        # distribution layer, ignored in single-device runs.
        from ..context import current_context
        self.raw_ctx = current_context()
        self.sharding = None  # optional PartitionSpec-like annotation

    # -- lowering ---------------------------------------------------------
    def lower(self, ctx: LowerCtx, *vals):
        raise NotImplementedError(f"{self.op_type} has no lowering rule")

    def infer_shape(self, input_shapes):
        """Static output shape from input shapes.

        Ops without a hand-written rule fall back to the abstract
        interpreter (:mod:`hetu_tpu.analysis.shapes`): ``jax.eval_shape``
        of this node's ``lower`` rule over ``ShapeDtypeStruct``s — zero
        FLOPs, real shapes for EVERY op instead of ``None`` holes.
        Returns ``None`` only when the inputs are unknown or the lowering
        cannot be abstractly evaluated outside its runtime context.
        """
        from ..analysis.shapes import abstract_infer_shape
        return abstract_infer_shape(self, input_shapes)

    # -- python operator sugar (parity with Node.py:48-109) ---------------
    def __add__(self, other):
        from ..ops.arithmetic import add_op, addbyconst_op
        if isinstance(other, Op):
            return add_op(self, other)
        return addbyconst_op(self, const_attr=other)

    __radd__ = __add__

    def __sub__(self, other):
        from ..ops.arithmetic import minus_op, minusbyconst_op
        if isinstance(other, Op):
            return minus_op(self, other)
        return minusbyconst_op(self, const_attr=other)

    def __rsub__(self, other):
        from ..ops.arithmetic import minusbyconst_op, opposite_op
        if isinstance(other, Op):  # pragma: no cover - handled by __sub__
            raise TypeError
        return minusbyconst_op(opposite_op(self), const_attr=-other)

    def __neg__(self):
        from ..ops.arithmetic import opposite_op
        return opposite_op(self)

    def __mul__(self, other):
        from ..ops.arithmetic import mul_op, mulbyconst_op
        if isinstance(other, Op):
            return mul_op(self, other)
        return mulbyconst_op(self, const_attr=other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        from ..ops.arithmetic import div_op, div_const_op
        if isinstance(other, Op):
            return div_op(self, other)
        return div_const_op(self, const_attr=1.0 / other)

    def __rtruediv__(self, other):
        from ..ops.arithmetic import const_div_op
        if isinstance(other, Op):  # pragma: no cover
            raise TypeError
        return const_div_op(self, const_attr=other)

    def __pow__(self, p):
        from ..ops.arithmetic import pow_op
        return pow_op(self, p=p)

    def __matmul__(self, other):
        from ..ops.matmul import matmul_op
        return matmul_op(self, other)

    def __repr__(self):
        return f"<{self.op_type} '{self.name}' id={self.id}>"

    __str__ = __repr__


class PlaceholderOp(Op):
    """A graph input: either a fed value (placeholder) or a Variable.

    Reference: ``gpu_ops/Variable.py:19`` (PlaceholderOp doubles as both).
    """

    op_type = "Placeholder"

    def __init__(self, name, value=None, initializer=None, trainable=False,
                 dtype=None, shape=None, is_embed=False):
        super().__init__([], name=name)
        self.initializer = initializer
        self.trainable = trainable
        self.is_embed = is_embed
        self.dtype = dtype
        self.shape = tuple(shape) if shape is not None else None
        self._value = None
        if value is not None:
            self.set_value(value)

    @property
    def is_variable(self):
        return self.initializer is not None or self._value is not None

    def set_value(self, value):
        value = np.asarray(value)
        self._value = value
        self.shape = value.shape
        if self.dtype is None:
            self.dtype = value.dtype

    def get_init_value(self, seed_key=None):
        """Materialise the initial value as a numpy/jax array."""
        if self._value is not None:
            return self._value
        if self.initializer is not None:
            if hasattr(self.initializer, "materialize"):
                return self.initializer.materialize(self.shape, seed_key)
            return self.initializer(self.shape, seed_key)
        return None

    def lower(self, ctx, *vals):  # never called: executor feeds these
        raise RuntimeError("Placeholder values are supplied by the executor")

    def infer_shape(self, input_shapes):
        return self.shape


def Variable(name, value=None, initializer=None, trainable=True, dtype=None,
             shape=None, is_embed=False):
    """Create a trainable (or stateful) graph variable.

    Parity with ``ht.Variable`` in the reference (``gpu_ops/Variable.py``).
    """
    return PlaceholderOp(name, value=value, initializer=initializer,
                         trainable=trainable, dtype=dtype, shape=shape,
                         is_embed=is_embed)


def placeholder_op(name="placeholder", dtype=np.float32, shape=None):
    return PlaceholderOp(name, dtype=dtype, shape=shape)


def topo_sort(fetches):
    """Deterministic post-order topological sort of the fetch subgraph."""
    visited = set()
    order = []

    def visit(node):
        if node.id in visited:
            return
        visited.add(node.id)
        for inp in node.inputs:
            visit(inp)
        order.append(node)

    for f in fetches:
        visit(f)
    return order


def find_placeholders(topo):
    feeds, variables = [], []
    for n in topo:
        if isinstance(n, PlaceholderOp):
            (variables if n.is_variable else feeds).append(n)
    return feeds, variables
