"""Cached run plans: the per-step Python of ``SubExecutor._run_impl``
resolved ONCE per (subgraph, feed schema).

The round-5 host-overhead artifact (``artifacts/host_overhead.json``)
measured the executor's dispatch path at 5.2x a raw ``jax.jit`` call —
at real TPU step rates the per-step Python (feed-key resolution,
``_place_feed`` placement/cast introspection, ``_check_feeds``
validation, the ``host_lr`` calls and the little dicts rebuilt every
step) IS the step time floor, no matter what XLA does.  Everything in
that list depends only on the *feed schema* — which placeholders are
fed, with what container type / dtype / shape — so it is resolved once
into a :class:`RunPlan` and replayed as a flat loop of prebound
closures:

* **feed placement** — one specialized closure per feed node
  (device-committed fast path, dtype-adopting numpy path, mesh
  placement with the strategy's ``PartitionSpec`` prebound), replacing
  the per-step isinstance/dtype/device introspection of
  ``Executor._place_feed``;
* **validation** — the ``validate='warn'|'error'`` fed-shape check runs
  once per schema (an ``error`` verdict raises at plan build, so a bad
  schema still fails every ``run()``);
* **pipelined feeds** — dataloader-fed placeholders are double-buffered:
  step N+1's batch is peeked (``get_next_arr``) and ``device_put`` on a
  background thread while step N's jitted program executes, so the
  host→device copy overlaps compute instead of serializing in front of
  the dispatch (composing with, not duplicating, the PS row prefetch).
  The consume check is by host-array IDENTITY — ``get_arr`` returns the
  exact peeked object — so a restored dataloader position can never
  serve a stale prefetched batch.

A schema change (new shapes, dtypes, feed set) transparently re-plans;
``plan_cache_hit``/``plan_cache_miss`` counters (``hetu_tpu.metrics``,
surfaced by ``HetuProfiler.run_plan_counters()``) prove the reuse, and
sustained misses from ping-ponging feed shapes raise the
``feed-schema-churn`` warning (PR 5 diagnostic style: the churning
placeholder and its creation site are named) pointing at batch
bucketing as the fix.  ``HETU_FEED_PIPELINE=0`` disables the
double-buffer; ``HETU_RUN_PLAN_CACHE`` bounds the per-subgraph plan
cache (default 8, LRU).
"""
from __future__ import annotations

import os
import time as _time
import warnings
from collections import OrderedDict

import numpy as np

from ..metrics import record_run_plan
from ..ndarray import NDArray, wrap_device
from ..obs.trace import TRACER as _TR


#: marks "this feed node is dataloader-fed (absent from feed_dict)" in
#: the identity memo — None would collide with a feed that disappeared
_DL_SENTINEL = object()

#: jax.Array class, resolved on first schema computation (keeps the jax
#: import off the module import path, like the executor's discipline)
_JaxArray = None


def feed_pipeline_enabled():
    return os.environ.get("HETU_FEED_PIPELINE", "1") != "0"


def pipeline_min_us():
    """Feed placements cheaper than this run INLINE: a Python thread
    handoff (submit + result wakeup + GIL churn) costs ~60-100us, so
    double-buffering a cheap host→device copy would SLOW the step down.
    Real batches (100KB+) clear this easily; microbench-sized feeds
    stay inline."""
    try:
        return float(os.environ.get("HETU_FEED_PIPELINE_MIN_US", "150"))
    except ValueError:
        return 150.0


def _schema_of(sub, feed_dict):
    """Hashable fingerprint of HOW this run is fed: per feed node, the
    container kind + dtype + shape (the inputs every placement/validation
    decision in ``_run_impl`` depends on).  Cheap on purpose — it runs
    every step as the plan-cache key."""
    global _JaxArray
    if _JaxArray is None:
        import jax
        _JaxArray = jax.Array
    from ..data.dataloader import DataloaderOp
    items = []
    for node in sub.feed_nodes:
        if node in feed_dict:
            v = feed_dict[node]
            # dtype OBJECTS, not strings: np.dtype hashes/compares fast,
            # while str(dtype) walks numpy's name machinery (~3us — real
            # money at per-step rates)
            if type(v) is np.ndarray:
                items.append(("np", v.dtype, v.shape))
            elif isinstance(v, _JaxArray):
                items.append(("jax", v.dtype, v.shape))
            elif isinstance(v, NDArray):
                a = v.jax()
                items.append(("ndarray", a.dtype, tuple(a.shape)))
            elif isinstance(v, np.ndarray):     # ndarray subclass
                items.append(("np", v.dtype, v.shape))
            else:   # list / scalar / exotic: the generic placement path
                items.append(("py", np.shape(v)))
        elif isinstance(node, DataloaderOp):
            items.append(("dl",))
        else:
            raise ValueError(f"missing feed for {node}")
    return tuple(items)


def _feed_dtype(node, src_dtype):
    """The dtype a feed of ``src_dtype`` is placed AS — the one
    resolution rule (``Executor._place_feed``'s float64 demotion +
    declared-dtype adoption), shared by every specialized placer so the
    fast paths cannot drift from the general one."""
    want = np.dtype(src_dtype)
    if want == np.float64:
        want = np.dtype(np.float32)
    declared = getattr(node, "dtype", None)
    if declared is not None:
        want = np.dtype(declared)
    return want


def _np_placer(ex, node, src_dtype):
    """Specialized placement for a numpy feed of known dtype: the dtype
    resolution happens HERE, once, leaving a cast-or-not + put closure
    for the hot path.  Returns ``None`` when placement needs the value's
    ndim under a dist strategy (``_bind_strategy_specs`` rebinds those
    once shapes are known)."""
    import jax
    want = _feed_dtype(node, src_dtype)
    cast = want != np.dtype(src_dtype)
    if ex.mesh is None:
        if cast:
            return lambda v: jax.device_put(v.astype(want))
        return jax.device_put
    from jax.sharding import NamedSharding
    from .executor import _filter_spec
    if node.sharding is not None:
        sh = NamedSharding(ex.mesh, _filter_spec(ex.mesh, node.sharding))
    elif ex.dist_strategy is not None:
        return None     # ndim-dependent spec: bound by the schema pass
    else:
        sh = ex._replicated_sharding
    if cast:
        return lambda v: ex._global_put(v.astype(want), sh)
    return lambda v: ex._global_put(v, sh)


class RunPlan:
    """One feed schema's resolved dispatch path (see module docstring)."""

    def __init__(self, sub, schema, feed_dict):
        ex = sub.ex
        self.sub = sub
        self.ex = ex
        self.schema = schema
        # validation verdict: once per schema.  'error' raises HERE —
        # the failed plan is never cached, so every run() with the bad
        # schema fails exactly like the per-step check did.
        if getattr(ex, "validate", "off") != "off" and feed_dict:
            ex._check_feeds(sub, feed_dict)
        self._steps = []        # (key, fetch(feed_dict) -> device value)
        self._dl_entries = []   # (node, placer) — feed-pipeline sources
        self._pre = {}          # node -> (host batch, Future[device val])
        self._dl_cost = {}      # node -> last inline placement cost (us)
        self._pipelined = 0     # consumed prefetches since last flush
        # id(arr) -> arr vetted as committed-on-default-backend.  WEAK
        # values: a fresh-array-per-step feeder (the run_steps driver)
        # must not pin dead batch buffers alive, and a dead entry's id
        # is auto-removed before the id can be recycled
        import weakref
        self._vetted = weakref.WeakValueDictionary()
        for node, item in zip(sub.feed_nodes, schema):
            key = ex._k(node)
            kind = item[0]
            if kind == "dl":
                fetch = self._dataloader_fetch(node, sub.name)
            elif kind == "np":
                place = _np_placer(ex, node, item[1])
                if place is None:
                    place = lambda v, n=node: ex._place_feed(n, v)
                fetch = (lambda fd, n=node, p=place: p(fd[n]))
            elif kind == "jax":
                fetch = self._jax_fetch(node)
            else:   # "ndarray" / "py": the generic path, prebound
                fetch = (lambda fd, n=node: ex._place_feed(n, fd[n]))
            self._steps.append((key, fetch))
        if feed_pipeline_enabled():
            for node, item in zip(sub.feed_nodes, schema):
                if item[0] == "dl":
                    self._dl_entries.append(
                        (node, lambda v, n=node: ex._place_feed(n, v)))
        # mesh strategies place numpy feeds per-ndim; resolve now that
        # shapes are known (replaces the None spec from _mesh_put)
        if ex.mesh is not None and ex.dist_strategy is not None:
            self._bind_strategy_specs(schema)
        # fast lane (see _make_fast): the dense, no-ZeRO-slab common case
        # replays as ONE prebound closure instead of the general
        # _run_impl walk — built lazily so the jitted step exists first
        self._fast = None
        self._fast_eligible = (
            os.environ.get("HETU_RUN_PLAN_FAST", "1") != "0"
            and not sub._ps_items and not sub._zero3
            and not sub._t_view and not sub._s_view)

    def _make_fast(self):
        """The per-step residue of ``SubExecutor._run_impl`` for the
        dense common case, compiled into one closure with every
        attribute chain prebound as a cell variable (LOAD_DEREF beats
        LOAD_ATTR walks at microsecond step rates).  MUST stay in
        lockstep with the general ``_run_impl`` path — the run-plan
        tests hold the two bitwise-equal (``HETU_RUN_PLAN_FAST=0``
        forces the general path for comparison)."""
        plan = self
        sub = self.sub
        ex = sub.ex
        jit = sub._jit
        steps = self._steps
        t_plain = sub._t_plain
        s_plain = sub._s_plain
        opt_items = sub._opt_items
        writeback = sub._writeback_pairs
        state_pairs = sub._state_pairs
        sched_ops = sub._sched_ops
        training = sub.training
        host_lrs = sub._host_lrs
        # all-traced lrs: ONE committed device constant, prebound (the
        # per-step call would just return it anyway)
        lrs_const = host_lrs(0) if not sub._host_lr_ops else None
        start_prefetch = self.start_feed_prefetch if self._dl_entries \
            else None
        step_input = ex._step_input
        tracer = _TR      # cell-bound: LOAD_DEREF beats LOAD_GLOBAL

        def fast(feed_dict, sync, t_pl=0, t0=0):
            # trace stamps ride INLINE in the one shared body (a traced
            # twin would drift from this path; the off cost is three
            # flag reads).  Emission is BATCHED — one buffer fetch for
            # all three phase spans, boundary timestamps shared —
            # because this closure is the dispatch-gap hot path the
            # <=25% tracing-tax gate measures.  ``t_pl``/``t0`` carry
            # the caller's run-plan-lookup window; the step span lives
            # in SubExecutor.run.
            tr = tracer if tracer.on else None
            if tr is not None and not t0:
                t0 = _time.perf_counter_ns()
            feeds = {}
            for key, fetch in steps:
                feeds[key] = fetch(feed_dict)
            piped = plan._pipelined
            if piped:
                plan._pipelined = 0
                record_run_plan("feeds_pipelined", piped)
            vv = ex.var_values
            tparams = {k: vv[n] for k, n in t_plain}
            sparams = {k: vv[n] for k, n in s_plain}
            os_ = ex.opt_states
            opt_states = {k: os_[op] for k, op in opt_items}
            step = ex._step_counter
            if tr is not None:
                t1 = _time.perf_counter_ns()
            outs, new_tparams, updates, new_opt_states, new_step = jit(
                tparams, sparams, opt_states, feeds, ex.master_key,
                step_input(),
                lrs_const if lrs_const is not None else host_lrs(step))
            if tr is not None:
                # ONE packed record for the whole phase set ("P" —
                # expanded to three spans by the exporter): one
                # allocation, one ring store, no per-step dicts; GC
                # churn was a measurable slice of the tracing tax
                b = getattr(tr._tl, "buf", None)
                if b is None or b.gen != tr._gen:
                    b = tr._buf()
                i = b.i
                b.items[i % b.cap] = ("P", t_pl, t0, t1,
                                      _time.perf_counter_ns())
                b.i = i + 1
            if start_prefetch is not None:
                start_prefetch()
            for n, k in writeback:
                vv[n] = new_tparams[k]
            if updates:
                for n, k in state_pairs:
                    if k in updates:
                        vv[n] = updates[k]
            for k, op in opt_items:
                os_[op] = new_opt_states[k]
            if training:
                # host and device counters advance together (the device
                # scalar came back from the step — zero host conversion)
                ex._step_counter = step + 1
                ex._step_dev = new_step
                for op in sched_ops:
                    op.optimizer.on_step(step + 1)
            results = [None if v is None else wrap_device(v)
                       for v in outs]
            if not sync:
                ex._note_async(outs, new_opt_states)
            return results
        return fast

    # -- feed fetch closures ------------------------------------------------

    def _bind_strategy_specs(self, schema):
        """Rebind numpy placers under a dist strategy with the ndim-
        resolved PartitionSpec prebound (feed_spec needs the value's
        ndim, which the schema fixes)."""
        import jax
        from jax.sharding import NamedSharding
        ex = self.ex
        steps = []
        for (key, fetch), (node, item) in zip(
                self._steps, zip(self.sub.feed_nodes, schema)):
            if item[0] == "np" and node.sharding is None:
                spec = ex.dist_strategy.feed_spec(node, len(item[2]))
                sh = NamedSharding(ex.mesh, spec)
                want = _feed_dtype(node, item[1])
                if want != np.dtype(item[1]):
                    fetch = (lambda fd, n=node, s=sh, w=want:
                             ex._global_put(fd[n].astype(w), s))
                else:
                    fetch = (lambda fd, n=node, s=sh:
                             ex._global_put(fd[n], s))
            steps.append((key, fetch))
        self._steps = steps

    def _jax_fetch(self, node):
        """Fed device arrays: an identity memo skips the per-step
        committed-on-default-backend device walk for feeds that are the
        SAME array object step after step (the steady-state training
        loop); anything else takes the full ``_place_feed`` path once
        and is memoized if it came back untouched (weakly — see
        ``_vetted``)."""
        ex = self.ex
        vetted = self._vetted

        def fetch(fd):
            v = fd[node]
            if vetted.get(id(v)) is v:
                return v
            out = ex._place_feed(node, v)
            if out is v:
                vetted[id(v)] = v
            return out
        return fetch

    def _dataloader_fetch(self, node, name):
        """Dataloader feed: consume a pipelined device_put when the
        prefetched host batch is identical (by identity) to the batch
        the loader hands out; otherwise place inline through the general
        ``_place_feed`` (a ``func``-transformed loader may change
        container types batch to batch, so no dtype is baked here)."""
        ex = self.ex
        pre = self._pre
        import time as _time

        def fetch(fd, _node=node, _name=name):
            val = _node.get_arr(_name)
            entry = pre.pop(_node, None)
            if entry is not None and entry[0] is val:
                self._pipelined += 1
                return entry[1].result()
            # inline placement: timed, so start_feed_prefetch only
            # double-buffers batches whose copy outweighs the handoff
            t0 = _time.perf_counter()
            out = ex._place_feed(_node, val)
            self._dl_cost[_node] = (_time.perf_counter() - t0) * 1e6
            return out
        return fetch

    # -- per-step entry points ----------------------------------------------

    def place_feeds(self, feed_dict):
        feeds = {}
        for key, fetch in self._steps:
            feeds[key] = fetch(feed_dict)
        n = self._pipelined
        if n:
            self._pipelined = 0
            record_run_plan("feeds_pipelined", n)
        return feeds

    def start_feed_prefetch(self):
        """Issue step N+1's host→device feed transfers on a background
        thread (called right after step N's dispatch, so the copy
        overlaps the in-flight device work).  Only dataloader-backed
        feeds have a knowable next batch; ``run_steps`` pipelines
        caller-fed placeholders the same way from the driver side."""
        if not self._dl_entries:
            return
        pool = None
        min_us = pipeline_min_us()
        for node, place in self._dl_entries:
            if node in self._pre:
                continue
            # adaptive: a batch whose inline copy is cheaper than the
            # thread handoff stays inline (cost measured by the fetch
            # closure; unmeasured nodes stay inline too — step 0 always
            # places inline, so the measurement exists from step 1 on)
            cost = self._dl_cost.get(node)
            if cost is None or cost < min_us:
                continue
            if pool is None:
                pool = self.sub._ensure_feed_pool()
            try:
                host = node.get_next_arr(self.sub.name)
            except KeyError:    # no dataloader registered for this split
                continue
            self._pre[node] = (host,
                               pool.submit(_place_traced, place, host))
        if self._pre:
            record_run_plan("feed_pipeline_depth_hw", len(self._pre))


def _place_traced(place, host):
    """The prefetch pool's unit of work: the H2D copy, shown as a
    ``feed.h2d`` span on the feed-pipeline thread's track when tracing
    (one extra frame on a background thread otherwise)."""
    if not _TR.on:
        return place(host)
    t0 = _time.perf_counter_ns()
    out = place(host)
    _TR.complete("feed.h2d", t0, _time.perf_counter_ns(), cat="feed")
    return out


class PlanCache:
    """Per-SubExecutor schema → :class:`RunPlan` map (LRU-bounded) with
    hit/miss accounting and feed-schema-churn detection."""

    #: misses before churn detection speaks up
    _CHURN_MISSES = 4
    #: distinct shapes one feed node must show to count as churning
    _CHURN_SHAPES = 3

    def __init__(self, sub):
        self.sub = sub
        self.plans = OrderedDict()
        try:
            self.max = max(1, int(os.environ.get("HETU_RUN_PLAN_CACHE",
                                                 "8")))
        except ValueError:
            self.max = 8
        self.misses = 0
        self._last = None           # (nodes, vals, plan) identity memo
        self._shapes_seen = {}      # feed node -> set of shapes at misses
        self._schemas_seen = set()  # distinct schemas ever missed (capped)
        self._repeat_misses = 0     # misses on a schema seen BEFORE
        self._churn_warned = False

    def lookup(self, feed_dict):
        # identity fast path: the steady-state training loop feeds the
        # SAME array objects step after step — identical objects imply an
        # identical schema, so the schema fingerprint itself is skipped
        last = self._last
        if last is not None and len(feed_dict) == last[2]:
            nodes, vals, _, plan = last
            for node, v in zip(nodes, vals):
                if feed_dict.get(node, _DL_SENTINEL) is not v:
                    break
            else:
                record_run_plan("plan_cache_hit")
                return plan
        schema = _schema_of(self.sub, feed_dict)
        plan = self.plans.get(schema)
        if plan is not None:
            self.plans.move_to_end(schema)
            record_run_plan("plan_cache_hit")
        else:
            record_run_plan("plan_cache_miss")
            self.misses += 1
            self._note_churn(schema)
            plan = RunPlan(self.sub, schema, feed_dict)
            self.plans[schema] = plan
            while len(self.plans) > self.max:
                self.plans.popitem(last=False)
        nodes = tuple(self.sub.feed_nodes)
        vals = tuple(feed_dict.get(n, _DL_SENTINEL) for n in nodes)
        nfed = sum(1 for v in vals if v is not _DL_SENTINEL)
        self._last = (nodes, vals, nfed, plan)
        return plan

    def _note_churn(self, schema):
        """feed-schema-churn: successive ``run()`` calls KEEP missing the
        plan cache because some feed's shape ping-pongs (an unbucketed
        ragged batch) — every re-plan retraces/compiles a fresh XLA
        program, which swamps any dispatch-path win.  A fixed bucket set
        is NOT churn: each bucket misses once while warming and hits
        forever after, so the warning requires SUSTAINED misses — either
        a schema missing AGAIN after it was already planned (evicted and
        cycling back), or more distinct schemas than the cache can hold.
        Warned once per subgraph, PR 5 diagnostic style (rule name,
        offending node, creation site, concrete fix)."""
        if self._churn_warned:
            return
        if schema in self._schemas_seen:
            self._repeat_misses += 1
        elif len(self._schemas_seen) < 64:
            self._schemas_seen.add(schema)
        for node, item in zip(self.sub.feed_nodes, schema):
            if len(item) < 3:
                continue    # no shape to track (dl / py feeds)
            seen = self._shapes_seen.setdefault(node, set())
            if len(seen) < 8:
                seen.add(tuple(item[2]))
        if self.misses < self._CHURN_MISSES:
            return
        if self._repeat_misses < 2 and len(self._schemas_seen) <= self.max:
            return      # bucket warm-up, not sustained churn
        churners = [(node, shapes) for node, shapes in
                    self._shapes_seen.items()
                    if len(shapes) >= self._CHURN_SHAPES]
        if not churners:
            return
        self._churn_warned = True
        from ..analysis.lint import Diagnostic
        node, shapes = churners[0]
        shown = ", ".join(str(s) for s in sorted(shapes)[:4])
        if len(self._schemas_seen) > self.max and len(shapes) <= 16:
            # a FIXED bucket set merely larger than the plan cache: the
            # per-shape XLA executables stay cached inside the one jit —
            # only the cheap Python plan rebuilds — so the actionable
            # fix is a bigger plan cache, not (re-)bucketing
            fix = (f"this looks like a fixed bucket set larger than the "
                   f"plan cache (bound {self.max}) — raise "
                   f"HETU_RUN_PLAN_CACHE to cover every bucket")
        else:
            fix = ("each genuinely new shape also retraces/compiles a "
                   "fresh XLA program; bucket ragged batches to a small "
                   "fixed set of shapes (pad to the mod-128 buckets the "
                   "flash kernel entry uses, or fix the dataloader "
                   "batch size)")
        diag = Diagnostic(
            "feed-schema-churn", "warn",
            f"feed shapes for placeholder '{node.name}' keep missing "
            f"the run-plan cache across run() calls (saw {shown}"
            f"{', ...' if len(shapes) > 4 else ''}; {self.misses} misses "
            f"so far) — {fix}", node)
        warnings.warn(str(diag), UserWarning, stacklevel=5)


class KeyedPlanCache:
    """Keyed dispatch-plan cache for planes that resolve their own step
    closures (the decode engine's per-(batch, len)-bucket step plans:
    feed-key order, donation layout, placement — the serving analogue of
    what :class:`RunPlan` prebinds per schema).  Same accounting contract
    as :class:`PlanCache`: every lookup records ``plan_cache_hit`` or
    ``plan_cache_miss``, so the steady-state claim — the 100-request
    stream's per-token dispatch is a plan-cache hit — is provable from
    the one counter family the overhead bench already watches."""

    def __init__(self, max_entries=32):
        self.plans = OrderedDict()
        self.max = max(1, int(max_entries))

    def lookup(self, key, build):
        """The plan for ``key`` — built by ``build()`` on first sight,
        replayed from the cache (LRU-refreshed) after."""
        plan = self.plans.get(key)
        if plan is not None:
            self.plans.move_to_end(key)
            record_run_plan("plan_cache_hit")
            return plan
        record_run_plan("plan_cache_miss")
        plan = build()
        self.plans[key] = plan
        while len(self.plans) > self.max:
            self.plans.popitem(last=False)
        return plan


__all__ = ["RunPlan", "PlanCache", "KeyedPlanCache",
           "feed_pipeline_enabled"]
