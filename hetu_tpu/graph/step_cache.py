"""Compiled-step cache: reuse jitted training steps across Executor
instances.

Rebuilding an Executor over a structurally identical graph (bench re-runs
in one process, `tools/hlo_audit.py --config all`, a supervisor-driven
reconstruction) used to pay the full trace + XLA compile again, because
each SubExecutor owned a private ``jax.jit``.  Here the jitted step is
cached process-wide, keyed on a structural SIGNATURE of everything that
determines the traced program: the topo (op types, attrs — constant
arrays hashed by content —, edges, placeholder shapes/dtypes), the fetch
layout, the optimizer hyperparameters, the mesh fingerprint, and the
executor knobs (compute_dtype, zero stage + bucket size, pipeline,
microbatches, remat, matmul precision).  Canonical topo-ordinal input
keys (``Executor._k``) make two same-shaped graphs produce byte-identical
pytree structures, so the cached callable accepts the new instance's
inputs directly.

Anything the signature cannot prove hashable (an Op or unknown object
inside ``attrs``) makes the graph UNCACHABLE — counted, never
wrong-cached.  PS-backed subgraphs are uncachable by policy: a cached
step pins its builder executor alive through the closure, and a PS
executor's teardown contract ("del executor closes its embedding
caches/pools") must keep working.  ``HETU_STEP_CACHE=0`` disables the
cache; entries are LRU-bounded (``HETU_STEP_CACHE_MAX``, default 8)
because of that same executor pinning.

Cross-process reuse (the supervisor's post-restart resume) rides jax's
persistent compilation cache instead: set ``HETU_COMPILE_CACHE_DIR`` (the
launcher defaults it under ``--ckpt-dir``) and the byte-identical HLO a
canonical-key rebuild produces becomes a disk cache hit.
"""
from __future__ import annotations

import hashlib
import os
from collections import OrderedDict

import numpy as np

from ..metrics import record_step_cache
from ..obs.lock_witness import make_lock as _make_lock

_CACHE = OrderedDict()          # signature -> jitted step
#: serving executables (hetu_tpu.serving.InferenceExecutor): signature
#: already folds the bucket in, so one entry pins one (graph, bucket)
#: compiled program.  Separate from _CACHE because serving graphs MAY be
#: PS-backed (rows ride as per-call inputs, so the compiled code never
#: touches the store — the teardown-contract argument that makes PS
#: training graphs uncachable does not apply) and because a serving fleet
#: legitimately pins one executable per bucket (own size bound).
_SERVE_CACHE = OrderedDict()
_LOCK = _make_lock("step_cache._LOCK")


class _Uncachable(Exception):
    pass


def enabled():
    return os.environ.get("HETU_STEP_CACHE", "1") != "0"


def _max_entries():
    try:
        return max(1, int(os.environ.get("HETU_STEP_CACHE_MAX", "8")))
    except ValueError:
        return 8


def _feed(h, *parts):
    for p in parts:
        h.update(str(p).encode())
        h.update(b"\x00")


def _hash_value(h, v, depth=0):
    """Hash an attr value by CONTENT; unknown types raise _Uncachable
    (silently skipping them could alias two different programs)."""
    if depth > 6:
        raise _Uncachable("attr nesting too deep")
    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        _feed(h, type(v).__name__, repr(v))
    elif isinstance(v, (np.generic,)):
        _feed(h, "npscalar", v.dtype.str, repr(v.item()))
    elif isinstance(v, np.ndarray):
        _feed(h, "ndarray", v.dtype.str, v.shape)
        h.update(np.ascontiguousarray(v).tobytes())
    elif isinstance(v, (list, tuple)):
        _feed(h, type(v).__name__, len(v))
        for item in v:
            _hash_value(h, item, depth + 1)
    elif isinstance(v, dict):
        _feed(h, "dict", len(v))
        for k in sorted(v, key=repr):
            _feed(h, repr(k))
            _hash_value(h, v[k], depth + 1)
    elif callable(v):
        # hash by CODE + captured state, not by name: op lowering fns are
        # often module-level lambdas (same code every build), and factory-
        # made closures are equal iff their cell contents are
        code = getattr(v, "__code__", None)
        if code is None:
            import functools
            if isinstance(v, functools.partial):
                _hash_value(h, v.func, depth + 1)
                _hash_value(h, list(v.args), depth + 1)
                _hash_value(h, dict(v.keywords), depth + 1)
                return
            raise _Uncachable(
                f"callable of type {type(v).__name__} has no code object")
        _feed(h, "fn", getattr(v, "__module__", ""),
              getattr(v, "__qualname__", ""))
        _hash_code(h, code)
        for cell in getattr(v, "__closure__", None) or ():
            _hash_value(h, cell.cell_contents, depth + 1)
        for d in getattr(v, "__defaults__", None) or ():
            _hash_value(h, d, depth + 1)
    elif hasattr(v, "dtype") and hasattr(v, "shape"):   # jax array const
        _feed(h, "devarray", str(v.dtype), tuple(v.shape))
        h.update(np.ascontiguousarray(np.asarray(v)).tobytes())
    else:
        raise _Uncachable(f"unhashable attr of type {type(v).__name__}")


def _hash_code(h, code, depth=0):
    """Hash a code object by content (bytecode + names + nested code) —
    address-free, so two module reloads of the same source agree."""
    if depth > 4:
        raise _Uncachable("code nesting too deep")
    _feed(h, "code", code.co_code.hex(), code.co_names,
          code.co_varnames[:code.co_argcount])
    for c in code.co_consts:
        if hasattr(c, "co_code"):
            _hash_code(h, c, depth + 1)
        else:
            _feed(h, repr(c))


def _hash_optimizer(h, opt):
    from ..optim.lr_scheduler import LRScheduler
    from ..optim.optimizer import traced_lr_fn
    _feed(h, "opt", type(opt).__module__, type(opt).__qualname__)
    # lr: a TRACED schedule (constant float or pure step-indexed
    # scheduler, graph/run_plan.py) is baked into the compiled program —
    # hash its full definition, or two executors differing only in lr
    # would alias one compiled step.  A host-path lr (data-dependent
    # scheduler, HETU_TRACED_LR=0) rides as a runtime input, never baked.
    if traced_lr_fn(opt) is not None:
        sched = opt.lr
        if isinstance(sched, LRScheduler):
            _feed(h, "lr-sched", type(sched).__module__,
                  type(sched).__qualname__)
            for k in sorted(sched.__dict__):
                _feed(h, k)
                _hash_value(h, sched.__dict__[k])
        else:
            _feed(h, "lr-const")
            _hash_value(h, float(sched))
    for k in sorted(opt.__dict__):
        if k == "lr":
            continue    # handled above (traced) or a runtime input (host)
        v = opt.__dict__[k]
        if isinstance(v, LRScheduler):
            continue    # schedulers only shape host_lr, never the trace
        # every other attr may be baked into apply()'s traced math —
        # content-hash it; an unhashable type raises _Uncachable (the
        # _hash_value policy: silently skipping could alias two programs)
        _feed(h, k)
        _hash_value(h, v)


def _mesh_fingerprint(mesh):
    if mesh is None:
        return "nomesh"
    devs = tuple((d.id, d.platform, d.process_index)
                 for d in mesh.devices.flat)
    return f"{tuple(mesh.axis_names)}|{tuple(mesh.devices.shape)}|{devs}"


def _hash_nodes(h, topo, fetches, key_fn):
    """Hash the structural graph content shared by the training and
    serving signatures: the fetch layout + every node's type, canonical
    key, edges, placeholder declaration, optimizer hypers and attrs.
    Returns the topo-ordinal map for callers that hash extras.

    Op entries hash as topo ordinals, NOT repr: node reprs embed
    process-global ids that differ on every structurally identical
    rebuild, which would guarantee a cache miss for exactly the rebuilds
    the cache exists for."""
    from .node import PlaceholderOp
    from ..optim.optimizer import OptimizerOp
    ordinal = {n: i for i, n in enumerate(topo)}
    _feed(h, "fetches",
          tuple(None if f is None else ordinal.get(f, -1)
                for f in fetches))
    for i, node in enumerate(topo):
        # key_fn(node) is part of the signature: the cached closure
        # addresses its inputs by the BUILDER's canonical keys, so a
        # same-shaped subgraph living at different global-topo
        # ordinals (extra sibling subgraphs) must not hit
        _feed(h, i, node.op_type, key_fn(node),
              tuple(ordinal[inp] for inp in node.inputs),
              node.sharding, getattr(node, "is_ps", False))
        lf = getattr(node, "_lower_fn", None)
        if lf is not None:
            _hash_value(h, lf)
        if isinstance(node, PlaceholderOp):
            _feed(h, "ph", node.shape, np.dtype(node.dtype).str
                  if node.dtype is not None else None,
                  node.trainable, node.is_variable,
                  getattr(node, "is_embed", False),
                  getattr(node, "width", None))
        if isinstance(node, OptimizerOp):
            _hash_optimizer(h, node.optimizer)
        if getattr(node, "index", None) is not None:
            _feed(h, "idx", node.index)
        for k in sorted(node.attrs):
            _feed(h, "attr", k)
            _hash_value(h, node.attrs[k])
    return ordinal


def signature(sub):
    """Structural fingerprint of one SubExecutor's step, or None when the
    graph contains something content-hashing cannot cover."""
    from .node import Op
    ex = sub.ex
    h = hashlib.sha256()
    try:
        if getattr(sub, "ps_nodes", None):
            # a cached step pins its builder executor alive — fine for
            # pure-tensor graphs, but a PS-backed executor owns host
            # resources (embedding caches, worker pools) whose teardown
            # contract is "del executor closes them"
            raise _Uncachable("PS-backed subgraph pins host resources")
        import jax
        # v4: traced-lr schedules are part of the program (hashed in
        # _hash_optimizer); the env gate flips every optimizer between
        # the traced and host-input paths, so it keys the signature too.
        # ex.remat is the ISSUE 13 POLICY string, and the auto/full
        # segment plan's decision fingerprint rides along — two policies
        # (or two auto plans under different HBM budgets) must never
        # alias one compiled executable.  The auto-parallel plan
        # fingerprint (ISSUE 15) keys candidate plans measured
        # back-to-back: node shardings already hash below, but a plan can
        # differ with identical annotations (fsdp-via-zero defaults,
        # microbatch pricing) — and the measurement loop's
        # one-compile-per-candidate accounting needs distinct candidates
        # to be distinct entries
        _feed(h, "v4", os.environ.get("HETU_TRACED_LR", "1"),
              jax.__version__, jax.default_backend(),
              _mesh_fingerprint(ex.mesh),
              ex.compute_dtype, ex.matmul_precision, ex.remat,
              getattr(sub, "_remat_fingerprint", None),
              getattr(ex, "_plan_fingerprint", None),
              ex.pipeline, ex.num_microbatches, sub.name, sub.training,
              ex.zero, os.environ.get("HETU_ZERO_BUCKET_MB", ""),
              type(ex.dist_strategy).__name__ if ex.dist_strategy else "")
        ordinal = _hash_nodes(h, sub.topo, sub.fetches, ex._k)
        mf = ex._extra_config.get("microbatch_feeds")
        _feed(h, "mbf", None if mf is None else tuple(
            sorted((f"o{ordinal[n]}" if n in ordinal
                    else f"name:{n.name}") if isinstance(n, Op)
                   else str(n) for n in mf)))
    except _Uncachable:
        return None
    except Exception:
        return None     # a signature bug must never break step building
    return h.hexdigest()


def serve_signature(iex, bucket):
    """Structural fingerprint of one serving executable: the inference
    fetch subgraph (PS embedding leaves INCLUDED — their rows ride as
    per-call inputs, keyed like any feed) + the padded batch bucket +
    everything that shapes the compiled program (backend, mesh, donation,
    RNG seed — the serving key is baked into the trace; the auto-parallel
    plan fingerprint when the executor compiles under ``plan=``).  A
    rebuilt :class:`~hetu_tpu.serving.InferenceExecutor` over a
    structurally identical graph reuses the compiled executable per
    bucket instead of retracing (the serving analogue of the training
    step cache; restart reuse across processes rides
    ``HETU_COMPILE_CACHE_DIR`` exactly like training).

    ``bucket``: the padded batch bucket (int), or a tuple for the
    autoregressive-decode plane — a (batch_bucket, len_bucket) pair for
    the one-token entry, a (batch_bucket, chunk_bucket, len_bucket)
    triple for the chunked-prefill entry (ISSUE 18) — each key pins its
    own executable, which is what lets the decode counters prove at
    most one compile per bucket key."""
    h = hashlib.sha256()
    try:
        import jax
        bkey = tuple(int(b) for b in bucket) \
            if isinstance(bucket, (tuple, list)) else int(bucket)
        _feed(h, "serve-v2", jax.__version__, jax.default_backend(),
              _mesh_fingerprint(iex.mesh), bkey,
              bool(iex.donate), iex.seed,
              getattr(iex, "_plan_fingerprint", None))
        _hash_nodes(h, iex.topo, iex.fetches, iex._k)
    except _Uncachable:
        return None
    except Exception:
        return None     # a signature bug must never break serving
    return h.hexdigest()


def lookup_or_build(sub, step_fn):
    """Return a jitted step for ``sub``: a cached one when an identical
    build exists, else ``jax.jit(step_fn)`` (stored for the next build)."""
    import jax
    if not enabled():
        return jax.jit(step_fn, donate_argnums=(0, 2))
    sig = signature(sub)
    if sig is None:
        record_step_cache("step_cache_uncachable")
        return jax.jit(step_fn, donate_argnums=(0, 2))
    with _LOCK:
        hit = _CACHE.get(sig)
        if hit is not None:
            _CACHE.move_to_end(sig)
            record_step_cache("step_cache_hit")
            return hit
    fn = jax.jit(step_fn, donate_argnums=(0, 2))
    with _LOCK:
        record_step_cache("step_cache_miss")
        _CACHE[sig] = fn
        while len(_CACHE) > _max_entries():
            _CACHE.popitem(last=False)
    return fn


def _max_serve_entries():
    """Serving pins one executable per (graph, bucket) — a router over 8
    buckets must not evict its own working set, so the bound is separate
    from (and larger than) the training cache's."""
    try:
        return max(1, int(os.environ.get("HETU_STEP_CACHE_SERVE_MAX",
                                         "32")))
    except ValueError:
        return 32


def lookup_or_build_serve(iex, bucket, infer_fn):
    """Return a jitted serving step for ``(iex, bucket)``: a cached one
    when a structurally identical build exists (cross-rebuild reuse),
    else a fresh ``jax.jit`` (stored for the next build).  Feeds are
    DONATED (``infer_fn(params, feeds)`` — params are the read-only
    weights and are never donated)."""
    import jax
    from ..metrics import record_serve
    donate = (1,) if iex.donate else ()

    def build():
        # the compile-once evidence: recorded HERE, on real builds only
        # — a cross-rebuild cache hit below builds nothing and must not
        # inflate the counter the acceptance check compares to the
        # number of distinct buckets used
        record_serve("serve_bucket_compiles")
        return jax.jit(infer_fn, donate_argnums=donate)

    if not enabled():
        return build()
    sig = serve_signature(iex, bucket)
    if sig is None:
        record_step_cache("step_cache_serve_uncachable")
        return build()
    with _LOCK:
        hit = _SERVE_CACHE.get(sig)
        if hit is not None:
            _SERVE_CACHE.move_to_end(sig)
            record_step_cache("step_cache_serve_hit")
            return hit
    fn = build()
    with _LOCK:
        record_step_cache("step_cache_serve_miss")
        _SERVE_CACHE[sig] = fn
        while len(_SERVE_CACHE) > _max_serve_entries():
            _SERVE_CACHE.popitem(last=False)
    return fn


def clear():
    """Drop every cached step (tests; frees the pinned builder executors)."""
    with _LOCK:
        _CACHE.clear()
        _SERVE_CACHE.clear()


__all__ = ["signature", "serve_signature", "lookup_or_build",
           "lookup_or_build_serve", "clear", "enabled"]
