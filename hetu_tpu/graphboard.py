"""Dataflow-graph visualization (reference ``python/graphboard/graph2fig.py``).

``to_dot(fetches)`` emits Graphviz DOT text; ``graph2fig(fetches, path)``
renders a layered matplotlib figure (no graphviz dependency needed).
"""
from __future__ import annotations

from .graph.node import PlaceholderOp, topo_sort


def _label(node):
    if isinstance(node, PlaceholderOp):
        kind = "var" if node.is_variable else "feed"
        return f"{node.name}\\n[{kind}]"
    return f"{node.op_type}\\n{node.name}"


def to_dot(fetches, name="hetu_graph"):
    """Graphviz DOT text for the graph reaching ``fetches``."""
    topo = topo_sort([f for f in fetches if f is not None])
    lines = [f"digraph {name} {{", "  rankdir=TB;"]
    for n in topo:
        shape = "box" if isinstance(n, PlaceholderOp) else "ellipse"
        lines.append(f'  n{n.id} [label="{_label(n)}" shape={shape}];')
    for n in topo:
        for i in n.inputs:
            lines.append(f"  n{i.id} -> n{n.id};")
    lines.append("}")
    return "\n".join(lines)


def _layers(topo):
    depth = {}
    for n in topo:
        depth[n] = 1 + max((depth[i] for i in n.inputs), default=-1)
    layers = {}
    for n, d in depth.items():
        layers.setdefault(d, []).append(n)
    return layers


def graph2fig(fetches, path=None, figsize=None):
    """Render the graph as a layered figure; save to ``path`` if given."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    topo = topo_sort([f for f in fetches if f is not None])
    layers = _layers(topo)
    pos = {}
    for d, nodes in layers.items():
        for i, n in enumerate(sorted(nodes, key=lambda x: x.id)):
            pos[n] = (i - (len(nodes) - 1) / 2.0, -d)
    depth = len(layers)
    width = max(len(v) for v in layers.values())
    fig, ax = plt.subplots(
        figsize=figsize or (max(6, width * 2.2), max(4, depth * 0.9)))
    for n in topo:
        x, y = pos[n]
        for i in n.inputs:
            xi, yi = pos[i]
            ax.annotate("", xy=(x, y + 0.18), xytext=(xi, yi - 0.18),
                        arrowprops=dict(arrowstyle="->", lw=0.7,
                                        color="#888888"))
    for n in topo:
        x, y = pos[n]
        is_ph = isinstance(n, PlaceholderOp)
        ax.text(x, y, _label(n).replace("\\n", "\n"),
                ha="center", va="center", fontsize=7,
                bbox=dict(boxstyle="round,pad=0.3" if not is_ph
                          else "square,pad=0.3",
                          fc="#cfe3ff" if is_ph else "#e8f5e9",
                          ec="#555555", lw=0.6))
    ax.set_axis_off()
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
        plt.close(fig)
        return path
    return fig


__all__ = ["to_dot", "graph2fig"]
