"""Initializers (reference ``python/hetu/initializers.py`` — nine init classes,
``zeros``/``ones``/``xavier_*``/``he_*``/``lecun_*`` Variable factories and
``Gen*`` closures).  TPU-native: inits are pure functions of a
``jax.random`` key — fully deterministic per-variable (vs curand global
state); the executor folds a per-variable index into the master seed.
"""
from __future__ import annotations

import numpy as np

from .graph.node import Variable


class BaseInit:
    def __call__(self, shape, name=None, trainable=True, ctx=None, is_embed=False):
        """Variable factory — layers call ``initializer(shape=..., name=...)``
        (reference layers/linear.py:26); returns a Variable node."""
        return Variable(name or "var", initializer=self, trainable=trainable,
                        shape=shape, is_embed=is_embed)

    def materialize(self, shape, key):
        """Pure init used by the executor: deterministic in ``key``."""
        import jax
        if key is None:
            key = jax.random.key(np.random.randint(0, 2**31 - 1))
        return np.asarray(self.init(shape, key), np.float32)

    def init(self, shape, key):
        raise NotImplementedError


class ConstantInit(BaseInit):
    def __init__(self, constant=0.0):
        self.constant = constant

    def init(self, shape, key):
        return np.full(shape, self.constant, np.float32)


class ZerosInit(ConstantInit):
    def __init__(self):
        super().__init__(0.0)


class OnesInit(ConstantInit):
    def __init__(self):
        super().__init__(1.0)


class UniformInit(BaseInit):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def init(self, shape, key):
        import jax
        return jax.random.uniform(key, shape, minval=self.low, maxval=self.high)


class NormalInit(BaseInit):
    def __init__(self, mean=0.0, stddev=1.0):
        self.mean, self.stddev = mean, stddev

    def init(self, shape, key):
        import jax
        return self.mean + self.stddev * jax.random.normal(key, shape)


class TruncatedNormalInit(BaseInit):
    def __init__(self, mean=0.0, stddev=1.0):
        self.mean, self.stddev = mean, stddev

    def init(self, shape, key):
        import jax
        return self.mean + self.stddev * jax.random.truncated_normal(
            key, -2.0, 2.0, shape)


class OrthogonalInit(BaseInit):
    """Orthogonal init (QR of a normal matrix) — the canonical recurrent
    w_hh initializer (Saxe et al.)."""

    def __init__(self, gain=1.0):
        self.gain = gain

    def init(self, shape, key):
        import jax
        rows, cols = shape[0], int(np.prod(shape[1:]))
        a = jax.random.normal(key, (max(rows, cols), min(rows, cols)))
        q, r = np.linalg.qr(np.asarray(a))
        q = q * np.sign(np.diag(r))  # deterministic sign convention
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape) \
            .astype(np.float32)


def _fans(shape, mode):
    shape = tuple(shape)
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) >= 3:  # conv OIHW
        rf = int(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * rf, shape[0] * rf
    else:
        fan_in = fan_out = int(np.prod(shape)) if shape else 1
    return {"fan_in": fan_in, "fan_out": fan_out,
            "avg": (fan_in + fan_out) / 2.0}[mode]


class GeneralXavierUniformInit(UniformInit):
    def __init__(self, gain=1.0, mode="avg"):
        super().__init__()
        self.gain, self.mode = gain, mode

    def init(self, shape, key):
        limit = float(np.sqrt(3.0 * self.gain / _fans(shape, self.mode)))
        self.low, self.high = -limit, limit
        return super().init(shape, key)


class XavierUniformInit(GeneralXavierUniformInit):
    def __init__(self):
        super().__init__(1.0, "avg")


class HeUniformInit(GeneralXavierUniformInit):
    def __init__(self):
        super().__init__(2.0, "fan_in")


class LecunUniformInit(GeneralXavierUniformInit):
    def __init__(self):
        super().__init__(1.0, "fan_in")


class GeneralXavierNormalInit(NormalInit):
    def __init__(self, gain=1.0, mode="avg"):
        super().__init__()
        self.gain, self.mode = gain, mode

    def init(self, shape, key):
        self.stddev = float(np.sqrt(self.gain / _fans(shape, self.mode)))
        return super().init(shape, key)


class XavierNormalInit(GeneralXavierNormalInit):
    def __init__(self):
        super().__init__(1.0, "avg")


class HeNormalInit(GeneralXavierNormalInit):
    def __init__(self):
        super().__init__(2.0, "fan_in")


class LecunNormalInit(GeneralXavierNormalInit):
    def __init__(self):
        super().__init__(1.0, "fan_in")


# -- Variable factories (reference initializers.py:214-311) -----------------

def _make(init, shape, name, trainable, is_embed=False):
    return init(shape, name=name, trainable=trainable, is_embed=is_embed)


def orthogonal(shape, gain=1.0, name=None, trainable=True, ctx=None):
    return _make(OrthogonalInit(gain), shape, name, trainable)


def zeros(shape, name=None, trainable=True, ctx=None):
    return _make(ZerosInit(), shape, name, trainable)


def ones(shape, name=None, trainable=True, ctx=None):
    return _make(OnesInit(), shape, name, trainable)


def constant(shape, fill_value=0.0, name=None, trainable=True, ctx=None):
    return _make(ConstantInit(fill_value), shape, name, trainable)


def truncated_normal(shape, mean=0.0, stddev=1.0, name=None, trainable=True, ctx=None):
    return _make(TruncatedNormalInit(mean, stddev), shape, name, trainable)


def random_normal(shape, mean=0.0, stddev=1.0, name=None, trainable=True, ctx=None):
    return _make(NormalInit(mean, stddev), shape, name, trainable)


def random_uniform(shape, minval=-1.0, maxval=1.0, name=None, trainable=True, ctx=None):
    return _make(UniformInit(minval, maxval), shape, name, trainable)


def general_xavier_normal(shape, gain, mode, name=None, trainable=True, ctx=None):
    return _make(GeneralXavierNormalInit(gain, mode), shape, name, trainable)


def general_xavier_uniform(shape, gain, mode, name=None, trainable=True, ctx=None):
    return _make(GeneralXavierUniformInit(gain, mode), shape, name, trainable)


def xavier_normal(shape, name=None, trainable=True, ctx=None):
    return _make(XavierNormalInit(), shape, name, trainable)


def xavier_uniform(shape, name=None, trainable=True, ctx=None):
    return _make(XavierUniformInit(), shape, name, trainable)


def he_normal(shape, name=None, trainable=True, ctx=None):
    return _make(HeNormalInit(), shape, name, trainable)


def he_uniform(shape, name=None, trainable=True, ctx=None):
    return _make(HeUniformInit(), shape, name, trainable)


def lecun_normal(shape, name=None, trainable=True, ctx=None):
    return _make(LecunNormalInit(), shape, name, trainable)


def lecun_uniform(shape, name=None, trainable=True, ctx=None):
    return _make(LecunUniformInit(), shape, name, trainable)


# -- Gen* closures (reference initializers.py:314-360) ----------------------

def GenZeros():
    return ZerosInit()


def GenOnes():
    return OnesInit()


def GenConstant(fill_value=0.0):
    return ConstantInit(fill_value)


def GenTruncatedNormal(mean=0.0, stddev=1.0):
    return TruncatedNormalInit(mean, stddev)


def GenNormal(mean=0.0, stddev=1.0):
    return NormalInit(mean, stddev)


def GenUniform(minval=-1.0, maxval=1.0):
    return UniformInit(minval, maxval)


def GenGeneralXavierNormal(gain, mode):
    return GeneralXavierNormalInit(gain, mode)


def GenGeneralXavierUniform(gain, mode):
    return GeneralXavierUniformInit(gain, mode)


def GenXavierNormal():
    return XavierNormalInit()


def GenXavierUniform():
    return XavierUniformInit()


def GenHeNormal():
    return HeNormalInit()


def GenHeUniform():
    return HeUniformInit()


def GenLecunNormal():
    return LecunNormalInit()


def GenLecunUniform():
    return LecunUniformInit()
