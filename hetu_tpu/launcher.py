"""Cluster launcher (reference ``bin/heturun`` → ``python/runner.py:150-255``).

The reference forks PS scheduler/server processes and mpirun's one worker
per GPU over SSH. On TPU the runtime owns topology: every host in a pod
slice runs the SAME program and ``jax.distributed.initialize`` wires the
mesh over ICI/DCN. So the launcher's job shrinks to:

* single host: exec the script (optionally with a virtual device count);
* multi host: spawn one process per host over ssh with
  ``coordinator/process_id/num_processes`` env, or export the settings for
  an external scheduler (GKE/xmanager-style);
* in-process: :func:`init_distributed` for scripts that want the reference's
  ``worker_init()`` call-shape.

CLI: ``python -m hetu_tpu.launcher -c cluster.yml train.py [args...]``.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

from .context import DistConfig


def init_distributed(coordinator=None, num_processes=None, process_id=None):
    """Initialize multi-host JAX (the reference's worker_init + MPI_Init).

    No-ops on a single host so scripts are portable (reference scripts call
    ``ht.worker_init()`` unconditionally, launcher.py:41-57).
    """
    import jax
    if num_processes is None:
        num_processes = int(os.environ.get("HETU_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator
        or os.environ.get("HETU_COORDINATOR", "localhost:8476"),
        num_processes=num_processes,
        process_id=process_id
        if process_id is not None
        else int(os.environ.get("HETU_PROCESS_ID", "0")))


def _host_env(config, rank, coordinator_port=8476):
    env = dict(os.environ)
    env["HETU_COORDINATOR"] = f"{config.chief}:{coordinator_port}"
    env["HETU_NUM_PROCESSES"] = str(config.num_hosts)
    env["HETU_PROCESS_ID"] = str(rank)
    return env


def launch(config, script, script_args=(), local_devices=None, ssh=True,
           coordinator_port=8476):
    """Run ``script`` on every host in the cluster config.

    Local host runs in-process-group (inherits stdio); remote hosts via
    ``ssh host python script`` with the coordination env exported on the
    command line (the reference pushes env the same way, runner.py:203-255).
    Returns the list of Popen handles.
    """
    procs = []
    for rank, host in enumerate(config.hosts):
        env = _host_env(config, rank, coordinator_port=coordinator_port)
        if local_devices:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count="
                                f"{local_devices}").strip()
        cmd = [sys.executable, script, *script_args]
        if host in ("localhost", "127.0.0.1") or not ssh:
            procs.append(subprocess.Popen(cmd, env=env))
        else:
            import shlex
            exports = " ".join(
                f"{k}={shlex.quote(env[k])}" for k in
                ("HETU_COORDINATOR", "HETU_NUM_PROCESSES",
                 "HETU_PROCESS_ID", "XLA_FLAGS") if env.get(k))
            remote_cmd = " ".join(shlex.quote(a) for a in cmd)
            procs.append(subprocess.Popen(
                ["ssh", host,
                 f"cd {shlex.quote(os.getcwd())} && {exports} {remote_cmd}"]))
    return procs


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="heturun", description="hetu_tpu cluster launcher")
    p.add_argument("-c", "--config", default=None,
                   help="cluster yaml (reference DistConfig format)")
    p.add_argument("-n", "--num-hosts", type=int, default=None,
                   help="override host count (localhost processes)")
    p.add_argument("--local-devices", type=int, default=None,
                   help="virtual device count per process (CPU testing)")
    p.add_argument("--no-ssh", action="store_true",
                   help="spawn all ranks locally (simulation)")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    if args.config:
        config = DistConfig(file=args.config)
    else:
        n = args.num_hosts or 1
        config = DistConfig(num_hosts=n, hosts=["localhost"] * n)
    procs = launch(config, args.script, args.script_args,
                   local_devices=args.local_devices,
                   ssh=not args.no_ssh)
    rc = 0
    for pr in procs:
        rc = pr.wait() or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
