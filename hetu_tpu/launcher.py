"""Cluster launcher (reference ``bin/heturun`` → ``python/runner.py:150-255``).

The reference forks PS scheduler/server processes and mpirun's one worker
per GPU over SSH. On TPU the runtime owns topology: every host in a pod
slice runs the SAME program and ``jax.distributed.initialize`` wires the
mesh over ICI/DCN. So the launcher's job shrinks to:

* single host: exec the script (optionally with a virtual device count);
* multi host: spawn one process per host over ssh with
  ``coordinator/process_id/num_processes`` env, or export the settings for
  an external scheduler (GKE/xmanager-style);
* in-process: :func:`init_distributed` for scripts that want the reference's
  ``worker_init()`` call-shape.

Fault tolerance: :func:`monitor` polls EVERY rank's handle (a remote
rank's early death can no longer hide behind a serial ``wait()`` on rank
0) and, on a failed rank, kills the rest — SPMD cannot continue partial.
``--supervise`` adds the recovery loop: relaunch the whole job with
exponential backoff and a bounded restart budget, resuming from the
latest auto-checkpoint (``--ckpt-dir`` exports ``HETU_AUTO_SAVE_DIR`` so
workers auto-save and ``Executor.resume`` on restart).  A ``HETU_CHAOS``
schedule with ``kill:proc@rank<r>:after<ms>`` faults is honored inside
the monitor loop, making launcher-level failures reproducible tests.

CLI: ``python -m hetu_tpu.launcher -c cluster.yml train.py [args...]``.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

from . import chaos as _chaos
from .context import DistConfig
from .metrics import record_fault


def init_distributed(coordinator=None, num_processes=None, process_id=None):
    """Initialize multi-host JAX (the reference's worker_init + MPI_Init).

    No-ops on a single host so scripts are portable (reference scripts call
    ``ht.worker_init()`` unconditionally, launcher.py:41-57).
    """
    import jax
    if num_processes is None:
        num_processes = int(os.environ.get("HETU_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator
        or os.environ.get("HETU_COORDINATOR", "localhost:8476"),
        num_processes=num_processes,
        process_id=process_id
        if process_id is not None
        else int(os.environ.get("HETU_PROCESS_ID", "0")))


def _host_env(config, rank, coordinator_port=8476):
    env = dict(os.environ)
    env["HETU_COORDINATOR"] = f"{config.chief}:{coordinator_port}"
    env["HETU_NUM_PROCESSES"] = str(config.num_hosts)
    env["HETU_PROCESS_ID"] = str(rank)
    return env


def launch(config, script, script_args=(), local_devices=None, ssh=True,
           coordinator_port=8476):
    """Run ``script`` on every host in the cluster config.

    Local host runs in-process-group (inherits stdio); remote hosts via
    ``ssh host python script`` with the coordination env exported on the
    command line (the reference pushes env the same way, runner.py:203-255).
    Returns the list of Popen handles.
    """
    procs = []
    for rank, host in enumerate(config.hosts):
        env = _host_env(config, rank, coordinator_port=coordinator_port)
        if local_devices:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count="
                                f"{local_devices}").strip()
        cmd = [sys.executable, script, *script_args]
        if host in ("localhost", "127.0.0.1") or not ssh:
            procs.append(subprocess.Popen(cmd, env=env))
        else:
            import shlex
            exports = " ".join(
                f"{k}={shlex.quote(env[k])}" for k in
                ("HETU_COORDINATOR", "HETU_NUM_PROCESSES",
                 "HETU_PROCESS_ID", "XLA_FLAGS",
                 # fault-tolerance knobs must reach remote ranks too —
                 # otherwise --supervise --ckpt-dir silently restarts a
                 # real cluster from scratch instead of resuming
                 "HETU_AUTO_SAVE_DIR", "HETU_AUTO_SAVE_EVERY",
                 "HETU_AUTO_SAVE_KEEP", "HETU_AUTO_RESUME", "HETU_CHAOS",
                 "HETU_HEARTBEAT_MS", "HETU_MAX_FRAME_MB")
                if env.get(k))
            remote_cmd = " ".join(shlex.quote(a) for a in cmd)
            # -tt forces a tty so killing the LOCAL ssh client hangs up
            # the remote session and the remote python dies with it —
            # monitor()'s kill-the-remaining-ranks contract must reach
            # the actual remote processes, not just their ssh clients
            procs.append(subprocess.Popen(
                ["ssh", "-tt", host,
                 f"cd {shlex.quote(os.getcwd())} && {exports} {remote_cmd}"]))
    return procs


def monitor(procs, poll_s=0.2, chaos=None, log=None):
    """Watch every rank's Popen until the job resolves.

    Polls ALL handles (the old serial ``wait()`` in rank order could
    block forever on rank 0 while rank 3 was already dead).  The first
    nonzero/ signal exit fails the job: the remaining ranks are killed —
    an SPMD program cannot continue with a partial world — and that exit
    code is returned.  All-zero exits return 0.

    ``chaos``: an active :class:`~hetu_tpu.chaos.ChaosInjector` whose
    ``kill:proc@rank<r>:after<ms>`` faults are fired here.
    """
    t0 = time.monotonic()
    live = dict(enumerate(procs))
    while live:
        if chaos is not None:
            for r in chaos.due_proc_kills((time.monotonic() - t0) * 1e3):
                p = live.get(r)
                if p is not None and p.poll() is None:
                    if log:
                        log(f"chaos: killing rank {r}")
                    p.kill()
        for r, p in sorted(live.items()):
            rc = p.poll()
            if rc is None:
                continue
            del live[r]
            if rc != 0:
                if log:
                    log(f"rank {r} exited rc={rc}; killing "
                        f"{len(live)} remaining rank(s)")
                for q in live.values():
                    if q.poll() is None:
                        q.kill()
                for q in live.values():
                    q.wait()
                return rc
        if live:
            time.sleep(poll_s)
    return 0


def supervise(config, script, script_args=(), local_devices=None, ssh=True,
              coordinator_port=8476, max_restarts=3, backoff_s=1.0,
              poll_s=0.2, chaos=None, log=None):
    """Supervising launcher: launch → monitor → (on failure) kill, back
    off exponentially, relaunch the whole job — relaunched workers
    resume from the latest complete auto-checkpoint (with
    ``HETU_AUTO_SAVE_DIR`` + ``HETU_AUTO_RESUME=1`` exported — as
    ``main`` does for ``--supervise --ckpt-dir`` — every Executor
    auto-resumes at construction; scripts may also call
    ``Executor.resume`` explicitly).  The restart budget is bounded;
    once exhausted, the first nonzero exit code of the final attempt
    propagates.
    """
    if chaos is None:
        chaos = _chaos.active() or _chaos.install_from_env()
    log = log or (lambda msg: print(f"[heturun] {msg}",
                                    file=sys.stderr, flush=True))
    attempt = 0
    while True:
        procs = launch(config, script, script_args,
                       local_devices=local_devices, ssh=ssh,
                       coordinator_port=coordinator_port)
        rc = monitor(procs, poll_s=poll_s, chaos=chaos, log=log)
        if rc == 0:
            if attempt:
                log(f"job recovered after {attempt} restart(s)")
            return 0
        if attempt >= max_restarts:
            log(f"restart budget ({max_restarts}) exhausted; "
                f"propagating rc={rc}")
            return rc
        delay = backoff_s * (2 ** attempt)
        attempt += 1
        record_fault("supervisor_restart")
        log(f"job failed rc={rc}; restart {attempt}/{max_restarts} in "
            f"{delay:.1f}s (workers resume from the latest checkpoint)")
        time.sleep(delay)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="heturun", description="hetu_tpu cluster launcher")
    p.add_argument("-c", "--config", default=None,
                   help="cluster yaml (reference DistConfig format)")
    p.add_argument("-n", "--num-hosts", type=int, default=None,
                   help="override host count (localhost processes)")
    p.add_argument("--local-devices", type=int, default=None,
                   help="virtual device count per process (CPU testing)")
    p.add_argument("--no-ssh", action="store_true",
                   help="spawn all ranks locally (simulation)")
    p.add_argument("--supervise", action="store_true",
                   help="monitor ranks and relaunch the whole job from "
                        "the latest checkpoint on a rank failure")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="supervision restart budget (default 3)")
    p.add_argument("--restart-backoff", type=float, default=1.0,
                   help="base seconds for exponential restart backoff")
    p.add_argument("--ckpt-dir", default=None,
                   help="exported to workers as HETU_AUTO_SAVE_DIR: "
                        "auto-save destination and resume source (also "
                        "defaults HETU_AUTO_SAVE_EVERY to 100 steps "
                        "unless the env already sets a cadence)")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    if args.config:
        config = DistConfig(file=args.config)
    else:
        n = args.num_hosts or 1
        config = DistConfig(num_hosts=n, hosts=["localhost"] * n)
    if args.ckpt_dir:
        # _host_env copies os.environ, so every rank inherits it
        os.environ["HETU_AUTO_SAVE_DIR"] = args.ckpt_dir
        # a dir with no cadence would never write a checkpoint (Executor
        # defaults auto_save_every to 0 = off) and every supervised
        # relaunch would silently restart from step 0 — default the
        # cadence too; workers/env can still override it
        os.environ.setdefault("HETU_AUTO_SAVE_EVERY", "100")
        if args.supervise:
            # relaunched workers must RESUME, not retrain: executors
            # built under the supervisor restore the newest complete
            # checkpoint at construction (no script changes needed)
            os.environ.setdefault("HETU_AUTO_RESUME", "1")
    if args.supervise:
        return supervise(config, args.script, args.script_args,
                         local_devices=args.local_devices,
                         ssh=not args.no_ssh,
                         max_restarts=args.max_restarts,
                         backoff_s=args.restart_backoff)
    procs = launch(config, args.script, args.script_args,
                   local_devices=args.local_devices,
                   ssh=not args.no_ssh)
    return monitor(procs, chaos=_chaos.active() or _chaos.install_from_env())


if __name__ == "__main__":
    sys.exit(main())
