"""Cluster launcher (reference ``bin/heturun`` → ``python/runner.py:150-255``).

The reference forks PS scheduler/server processes and mpirun's one worker
per GPU over SSH. On TPU the runtime owns topology: every host in a pod
slice runs the SAME program and ``jax.distributed.initialize`` wires the
mesh over ICI/DCN. So the launcher's job shrinks to:

* single host: exec the script (optionally with a virtual device count);
* multi host: spawn one process per host over ssh with
  ``coordinator/process_id/num_processes`` env, or export the settings for
  an external scheduler (GKE/xmanager-style);
* in-process: :func:`init_distributed` for scripts that want the reference's
  ``worker_init()`` call-shape.

Fault tolerance: :func:`monitor` polls EVERY rank's handle (a remote
rank's early death can no longer hide behind a serial ``wait()`` on rank
0) and, on a failed rank, kills the rest — SPMD cannot continue partial.
``--supervise`` adds the recovery loop: relaunch the whole job with
exponential backoff and a bounded restart budget, resuming from the
latest auto-checkpoint (``--ckpt-dir`` exports ``HETU_AUTO_SAVE_DIR`` so
workers auto-save and ``Executor.resume`` on restart).  A ``HETU_CHAOS``
schedule with ``kill:proc@rank<r>:after<ms>`` faults is honored inside
the monitor loop, making launcher-level failures reproducible tests
(the deterministic ``kill:proc@rank<r>:step<n>`` form fires on the
executor's step clock against ``register_proc``'d in-process handles
instead — the elastic harness's clock, see ``parallel/elastic.py``;
this wall-clock monitor loop has no step counter to schedule against).

Elastic note (ISSUE 12): the supervisor restart budget is the FLOOR
under elastic training — when an :class:`ElasticController` refuses a
shrink below ``min_dp``, recovery falls back to this module's
relaunch-from-checkpoint path; post-resize checkpoints restore at any
dp (the executor's load transcodes ZeRO moment slabs across world
sizes), so a supervised relaunch after a resize resumes with real
moments.

PS replication (``--ps-replication 2`` → ``HETU_PS_REPLICATION``)
changes the failure policy: a dead rank's PS shard keeps serving from
its live backup, so ``--standby`` respawns just that rank as a standby
(bounded by ``--standby-budget``) instead of killing the job — the
survivors' shard routers fail over in one RPC timeout and the executors'
re-replication tick (``HETU_PS_REREPLICATE_EVERY``) re-attaches the
standby as the fresh backup.

CLI: ``python -m hetu_tpu.launcher -c cluster.yml train.py [args...]``.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

from . import chaos as _chaos
from .context import DistConfig
from .metrics import record_fault


def init_distributed(coordinator=None, num_processes=None, process_id=None):
    """Initialize multi-host JAX (the reference's worker_init + MPI_Init).

    No-ops on a single host so scripts are portable (reference scripts call
    ``ht.worker_init()`` unconditionally, launcher.py:41-57).
    """
    import jax
    if num_processes is None:
        num_processes = int(os.environ.get("HETU_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator
        or os.environ.get("HETU_COORDINATOR", "localhost:8476"),
        num_processes=num_processes,
        process_id=process_id
        if process_id is not None
        else int(os.environ.get("HETU_PROCESS_ID", "0")))


def _host_env(config, rank, coordinator_port=8476):
    env = dict(os.environ)
    env["HETU_COORDINATOR"] = f"{config.chief}:{coordinator_port}"
    env["HETU_NUM_PROCESSES"] = str(config.num_hosts)
    env["HETU_PROCESS_ID"] = str(rank)
    return env


def _launch_rank(config, rank, script, script_args=(), local_devices=None,
                 ssh=True, coordinator_port=8476, extra_env=None):
    """Spawn ONE rank's process (also the standby-respawn entry point:
    a replicated-PS cluster relaunches a dead rank solo while the
    survivors keep training against the promoted replicas)."""
    host = config.hosts[rank]
    env = _host_env(config, rank, coordinator_port=coordinator_port)
    if extra_env:
        env.update(extra_env)
    if local_devices:
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{local_devices}").strip()
    cmd = [sys.executable, script, *script_args]
    if host in ("localhost", "127.0.0.1") or not ssh:
        return subprocess.Popen(cmd, env=env)
    import shlex
    exports = " ".join(
        f"{k}={shlex.quote(env[k])}" for k in
        ("HETU_COORDINATOR", "HETU_NUM_PROCESSES",
         "HETU_PROCESS_ID", "XLA_FLAGS",
         # fault-tolerance knobs must reach remote ranks too —
         # otherwise --supervise --ckpt-dir silently restarts a
         # real cluster from scratch instead of resuming
         "HETU_AUTO_SAVE_DIR", "HETU_AUTO_SAVE_EVERY",
         "HETU_AUTO_SAVE_KEEP", "HETU_AUTO_RESUME", "HETU_CHAOS",
         "HETU_HEARTBEAT_MS", "HETU_MAX_FRAME_MB",
         # PS replication knobs: every rank must agree on the topology
         "HETU_PS_REPLICATION", "HETU_RPC_BACKOFF_MS",
         "HETU_PS_REREPLICATE_EVERY", "HETU_PS_STANDBY")
        if env.get(k))
    remote_cmd = " ".join(shlex.quote(a) for a in cmd)
    # -tt forces a tty so killing the LOCAL ssh client hangs up
    # the remote session and the remote python dies with it —
    # monitor()'s kill-the-remaining-ranks contract must reach
    # the actual remote processes, not just their ssh clients
    return subprocess.Popen(
        ["ssh", "-tt", host,
         f"cd {shlex.quote(os.getcwd())} && {exports} {remote_cmd}"])


def launch(config, script, script_args=(), local_devices=None, ssh=True,
           coordinator_port=8476):
    """Run ``script`` on every host in the cluster config.

    Local host runs in-process-group (inherits stdio); remote hosts via
    ``ssh host python script`` with the coordination env exported on the
    command line (the reference pushes env the same way, runner.py:203-255).
    Returns the list of Popen handles.
    """
    return [_launch_rank(config, rank, script, script_args,
                         local_devices=local_devices, ssh=ssh,
                         coordinator_port=coordinator_port)
            for rank in range(len(config.hosts))]


def monitor(procs, poll_s=0.2, chaos=None, log=None, standby=None,
            standby_budget=3):
    """Watch every rank's Popen until the job resolves.

    Polls ALL handles (the old serial ``wait()`` in rank order could
    block forever on rank 0 while rank 3 was already dead).  The first
    nonzero/ signal exit fails the job: the remaining ranks are killed —
    an SPMD program cannot continue with a partial world — and that exit
    code is returned.  All-zero exits return 0.

    ``standby``: a ``rank -> Popen`` respawner (``--standby``, PS
    replication deployments).  A dead rank then does NOT fail the job:
    the survivors' shard routers have already failed over to the
    replicas, so the rank is relaunched solo as a standby — the
    executors' re-replication tick re-attaches it as the fresh backup.
    At most ``standby_budget`` respawns; past that, normal kill-all.

    ``chaos``: an active :class:`~hetu_tpu.chaos.ChaosInjector` whose
    ``kill:proc@rank<r>:after<ms>`` faults are fired here.
    """
    t0 = time.monotonic()
    live = dict(enumerate(procs))
    spawned = 0
    while live:
        if chaos is not None:
            for r in chaos.due_proc_kills((time.monotonic() - t0) * 1e3):
                p = live.get(r)
                if p is not None and p.poll() is None:
                    if log:
                        log(f"chaos: killing rank {r}")
                    p.kill()
        for r, p in sorted(live.items()):
            rc = p.poll()
            if rc is None:
                continue
            del live[r]
            if rc != 0:
                if standby is not None and spawned < standby_budget:
                    spawned += 1
                    record_fault("standby_spawn")
                    if log:
                        log(f"rank {r} exited rc={rc}; spawning standby "
                            f"({spawned}/{standby_budget}) — survivors "
                            f"keep serving from the promoted replicas")
                    live[r] = standby(r)
                    continue
                if log:
                    log(f"rank {r} exited rc={rc}; killing "
                        f"{len(live)} remaining rank(s)")
                for q in live.values():
                    if q.poll() is None:
                        q.kill()
                for q in live.values():
                    q.wait()
                return rc
        if live:
            time.sleep(poll_s)
    return 0


def supervise(config, script, script_args=(), local_devices=None, ssh=True,
              coordinator_port=8476, max_restarts=3, backoff_s=1.0,
              poll_s=0.2, chaos=None, log=None, standby=False,
              standby_budget=3):
    """Supervising launcher: launch → monitor → (on failure) kill, back
    off exponentially, relaunch the whole job — relaunched workers
    resume from the latest complete auto-checkpoint (with
    ``HETU_AUTO_SAVE_DIR`` + ``HETU_AUTO_RESUME=1`` exported — as
    ``main`` does for ``--supervise --ckpt-dir`` — every Executor
    auto-resumes at construction; scripts may also call
    ``Executor.resume`` explicitly).  The restart budget is bounded;
    once exhausted, the first nonzero exit code of the final attempt
    propagates.
    """
    if chaos is None:
        chaos = _chaos.active() or _chaos.install_from_env()
    log = log or (lambda msg: print(f"[heturun] {msg}",
                                    file=sys.stderr, flush=True))
    attempt = 0
    respawn = None
    if standby:
        def respawn(rank):
            # the replacement announces itself as a STANDBY: its server
            # holds its shards but serves nothing until re-replication
            # re-attaches it (a promoted ex-backup is the live truth)
            return _launch_rank(config, rank, script, script_args,
                                local_devices=local_devices, ssh=ssh,
                                coordinator_port=coordinator_port,
                                extra_env={"HETU_PS_STANDBY": "1"})
    while True:
        procs = launch(config, script, script_args,
                       local_devices=local_devices, ssh=ssh,
                       coordinator_port=coordinator_port)
        rc = monitor(procs, poll_s=poll_s, chaos=chaos, log=log,
                     standby=respawn, standby_budget=standby_budget)
        if rc == 0:
            if attempt:
                log(f"job recovered after {attempt} restart(s)")
            return 0
        if attempt >= max_restarts:
            log(f"restart budget ({max_restarts}) exhausted; "
                f"propagating rc={rc}")
            return rc
        delay = backoff_s * (2 ** attempt)
        attempt += 1
        record_fault("supervisor_restart")
        log(f"job failed rc={rc}; restart {attempt}/{max_restarts} in "
            f"{delay:.1f}s (workers resume from the latest checkpoint)")
        time.sleep(delay)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="heturun", description="hetu_tpu cluster launcher")
    p.add_argument("-c", "--config", default=None,
                   help="cluster yaml (reference DistConfig format)")
    p.add_argument("-n", "--num-hosts", type=int, default=None,
                   help="override host count (localhost processes)")
    p.add_argument("--local-devices", type=int, default=None,
                   help="virtual device count per process (CPU testing)")
    p.add_argument("--no-ssh", action="store_true",
                   help="spawn all ranks locally (simulation)")
    p.add_argument("--supervise", action="store_true",
                   help="monitor ranks and relaunch the whole job from "
                        "the latest checkpoint on a rank failure")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="supervision restart budget (default 3)")
    p.add_argument("--restart-backoff", type=float, default=1.0,
                   help="base seconds for exponential restart backoff")
    p.add_argument("--ckpt-dir", default=None,
                   help="exported to workers as HETU_AUTO_SAVE_DIR: "
                        "auto-save destination and resume source (also "
                        "defaults HETU_AUTO_SAVE_EVERY to 100 steps "
                        "unless the env already sets a cadence)")
    p.add_argument("--ps-replication", type=int, default=None,
                   help="exported to workers as HETU_PS_REPLICATION: 2 "
                        "keeps a live backup of every PS shard on the "
                        "next rank (failover instead of restart)")
    p.add_argument("--standby", action="store_true",
                   help="with PS replication: respawn a dead rank solo "
                        "as a standby instead of failing the whole job "
                        "(survivors serve from the promoted replicas; "
                        "re-replication re-attaches the standby)")
    p.add_argument("--standby-budget", type=int, default=3,
                   help="max solo respawns before falling back to the "
                        "kill-all policy (default 3)")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    if args.config:
        config = DistConfig(file=args.config)
    else:
        n = args.num_hosts or 1
        config = DistConfig(num_hosts=n, hosts=["localhost"] * n)
    if args.ps_replication is not None:
        # _host_env copies os.environ, so every rank inherits the topology
        os.environ["HETU_PS_REPLICATION"] = str(args.ps_replication)
        if args.standby:
            # a respawned standby must try to re-attach by itself even if
            # the training script never touches the knob
            os.environ.setdefault("HETU_PS_REREPLICATE_EVERY", "10")
    if args.ckpt_dir:
        # _host_env copies os.environ, so every rank inherits it
        os.environ["HETU_AUTO_SAVE_DIR"] = args.ckpt_dir
        # a dir with no cadence would never write a checkpoint (Executor
        # defaults auto_save_every to 0 = off) and every supervised
        # relaunch would silently restart from step 0 — default the
        # cadence too; workers/env can still override it
        os.environ.setdefault("HETU_AUTO_SAVE_EVERY", "100")
        if args.supervise:
            # relaunched workers must RESUME, not retrain: executors
            # built under the supervisor restore the newest complete
            # checkpoint at construction (no script changes needed)
            os.environ.setdefault("HETU_AUTO_RESUME", "1")
    if args.supervise:
        return supervise(config, args.script, args.script_args,
                         local_devices=args.local_devices,
                         ssh=not args.no_ssh,
                         max_restarts=args.max_restarts,
                         backoff_s=args.restart_backoff,
                         standby=args.standby,
                         standby_budget=args.standby_budget)
    procs = launch(config, args.script, args.script_args,
                   local_devices=args.local_devices,
                   ssh=not args.no_ssh)
    respawn = None
    if args.standby:
        def respawn(rank):
            return _launch_rank(config, rank, args.script, args.script_args,
                                local_devices=args.local_devices,
                                ssh=not args.no_ssh,
                                extra_env={"HETU_PS_STANDBY": "1"})
    return monitor(procs,
                   chaos=_chaos.active() or _chaos.install_from_env(),
                   standby=respawn, standby_budget=args.standby_budget)


if __name__ == "__main__":
    sys.exit(main())
