"""Layer objects (reference ``python/hetu/layers/``). Thin graph-building
classes over the op library; MoE layers/gates live in ``moe_layer.py``."""
from .base import BaseLayer
from .core import (Linear, Conv2d, BatchNorm, LayerNorm, Embedding, DropOut,
                   MaxPool2d, AvgPool2d, Relu, Reshape, Identity, Sequence,
                   Concatenate, ConcatenateLayers, SumLayers, Slice,
                   RNN, LSTM, GRU)
from .moe_layer import Expert, MoELayer, SparseMoELayer, BalancedMoELayer
from .gates import (TopKGate, TopKGateSparse, HashGate, KTop1Gate,
                    SAMGate, BalanceAssignmentGate)
from .attention import MultiHeadAttention
