"""Multi-head attention layer (new capability; the reference composes this
per-model in ``examples/transformers/*/hetu_bert.py``).

DESIGN NOTE — dropout placement: the reference (and HF) drop attention
*probabilities* inside the softmax (``hetu_bert.py`` attention_probs
dropout).  Here ``dropout`` applies to the attention *output* (after the
o-projection) instead: per-probability dropout is incompatible with the
flash kernel's blockwise online softmax (probabilities never materialise),
and output dropout is the standard flash-attention-era substitute with the
same regularisation strength at equal rate.  Configs named ``attn_pdrop`` /
``attention_probs_dropout_prob`` are therefore REINTERPRETED as
output-dropout rates — loss curves match the reference in expectation, not
step-bitwise, whenever these rates are nonzero."""
from __future__ import annotations

from .base import BaseLayer
from .core import Linear, DropOut
from .. import ops
from ..ops.attention import sdpa_op


class MultiHeadAttention(BaseLayer):
    def __init__(self, hidden_size, num_heads, dropout=0.0, causal=False,
                 context_parallel=None, name="mha"):
        assert hidden_size % num_heads == 0
        assert context_parallel in (None, "ring", "ulysses")
        self.h = num_heads
        self.dk = hidden_size // num_heads
        self.hidden = hidden_size
        self.causal = causal
        self.context_parallel = context_parallel
        self.q = Linear(hidden_size, hidden_size, name=name + ".q")
        self.k = Linear(hidden_size, hidden_size, name=name + ".k")
        self.v = Linear(hidden_size, hidden_size, name=name + ".v")
        self.o = Linear(hidden_size, hidden_size, name=name + ".o")
        self.drop = DropOut(dropout) if dropout else None

    def _split(self, x, batch, seq):
        x = ops.array_reshape_op(x, output_shape=(batch, seq, self.h, self.dk))
        return ops.transpose_op(x, perm=(0, 2, 1, 3))

    def __call__(self, x, batch, seq, kv=None, kv_seq=None, mask=None,
                 bias=None, scale=None):
        """x: (batch*seq, hidden) (reference models flatten); returns same.

        ``kv``: optional (batch*kv_seq, hidden) memory for cross-attention
        (encoder-decoder); ``mask``: optional validity mask node
        broadcastable to (B, H, S_q, S_k) — a (B, 1, 1, S_k) padding mask
        rides the flash kernel's O(S) key-mask strip path, and under
        context parallelism shards over the ring/ulysses schedule; a FULL
        per-query mask (XLNet-style permutation masks) shards its query
        dim over the ring like the bias does (swin stores its shift mask
        (nW, 1, w², w²) and tiles it to the window batch with an
        on-graph Repeat before calling here); ``bias``: optional
        additive logit bias node (T5 relative position bias),
        broadcastable to (B, H, S_q, S_k) — biased attention runs the
        flash kernel on TPU both locally and through the cp ring.

        Sequence lengths need NOT be 128-multiples: the dispatcher
        buckets ragged lengths into the kernel (pad → mask → unpad), so
        ``seq = 384 + r`` stays on the fast path; any genuine fallback
        is counted in ``hetu_tpu.metrics.flash_fallback_counts()``.
        """
        from ..ops.attention import (ring_attention_op, ulysses_attention_op,
                                     ring_attention_masked_op,
                                     ulysses_attention_masked_op,
                                     sdpa_bias_op, sdpa_masked_op,
                                     sdpa_masked_bias_op)
        kv = x if kv is None else kv
        kv_seq = seq if kv_seq is None else kv_seq
        q = self._split(self.q(x), batch, seq)
        k = self._split(self.k(kv), batch, kv_seq)
        v = self._split(self.v(kv), batch, kv_seq)
        cp_attn = {"ring": ring_attention_op,
                   "ulysses": ulysses_attention_op}.get(self.context_parallel)
        cp_masked = {"ring": ring_attention_masked_op,
                     "ulysses": ulysses_attention_masked_op
                     }.get(self.context_parallel)
        if self.context_parallel is not None and cp_attn is None:
            raise ValueError(
                f"unknown context_parallel mode {self.context_parallel!r}")
        if cp_attn is not None and kv_seq != seq:
            # unequal-length cross-attention stays LOCAL (the T5 design,
            # models/t5.py:40): the cp schedules slice key columns by the
            # QUERY chunk size, which is only meaningful for matched
            # lengths — routing it onto the ring would be silently wrong
            cp_attn = cp_masked = None
        if mask is not None:
            if cp_masked is not None:
                # key-padding AND full per-query masks (plus optional
                # bias) shard over the cp schedule
                o = (cp_masked(q, k, v, mask, bias, causal=self.causal,
                               scale=scale) if bias is not None else
                     cp_masked(q, k, v, mask, causal=self.causal,
                               scale=scale))
            elif bias is not None:
                o = sdpa_masked_bias_op(q, k, v, mask, bias,
                                        causal=self.causal, scale=scale)
            else:
                o = sdpa_masked_op(q, k, v, mask, causal=self.causal,
                                   scale=scale)
        elif bias is not None:
            # T5 + context parallelism: the bias node becomes the schedule's
            # 4th input (ring-sliced / head-sharded)
            o = (cp_attn(q, k, v, bias, causal=self.causal, scale=scale)
                 if cp_attn is not None else
                 sdpa_bias_op(q, k, v, bias, causal=self.causal, scale=scale))
        elif cp_attn is not None:
            o = cp_attn(q, k, v, causal=self.causal, scale=scale)
        else:
            o = sdpa_op(q, k, v, causal=self.causal, scale=scale)
        o = ops.transpose_op(o, perm=(0, 2, 1, 3))
        o = ops.array_reshape_op(o, output_shape=(batch * seq, self.hidden))
        o = self.o(o)
        if self.drop is not None:
            o = self.drop(o)
        return o
