"""Layer base (reference ``layers/base.py``)."""


class BaseLayer:
    def __call__(self, *args, **kwargs):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}()"
