"""Core layers (reference ``layers/``: linear.py, conv.py, normalization.py,
pooling.py, dropout.py, embedding.py, sequence.py, reshape.py, identity.py,
concatenate.py, slice.py, sum.py)."""
from __future__ import annotations

from .base import BaseLayer
from ..graph.node import Op
from .. import initializers as init
from .. import ops


class _TransposedInit:
    """Initialize with the transposed (logical) shape, store transposed —
    keeps fan_in/fan_out semantics for weight_transpose layers."""

    def __init__(self, inner):
        self.inner = inner

    def __call__(self, shape, name=None, trainable=True, ctx=None,
                 is_embed=False):
        from ..graph.node import Variable
        return Variable(name or "var", initializer=self, trainable=trainable,
                        shape=shape, is_embed=is_embed)

    def materialize(self, shape, key):
        return self.inner.materialize(tuple(shape)[::-1], key).T


def _resolve_activation(activation):
    if isinstance(activation, str):
        table = {"relu": ops.relu_op, "gelu": ops.gelu_op,
                 "tanh": ops.tanh_op, "sigmoid": ops.sigmoid_op}
        if activation not in table:
            raise NotImplementedError(activation)
        return table[activation]
    return activation


class Linear(BaseLayer):
    def __init__(self, in_features, out_features, initializer=None, bias=True,
                 activation=None, weight_transpose=False, name="linear"):
        initializer = initializer or init.GenXavierUniform()
        self.in_features, self.out_features = in_features, out_features
        self.bias = bias
        self.activation = _resolve_activation(activation)
        self.weight_transpose = weight_transpose
        self.name = name
        if isinstance(initializer, Op):
            self.weight_var = initializer  # user-supplied weight node
        else:
            if weight_transpose:
                # materialize with logical (in, out) shape so fan_in/fan_out
                # mode initializers (He/Lecun) see the true fans, then store
                # transposed
                initializer = _TransposedInit(initializer)
                wshape = (out_features, in_features)
            else:
                wshape = (in_features, out_features)
            self.weight_var = initializer(shape=wshape, name=name + ".weight")
        if bias:
            self.bias_var = init.zeros(shape=(out_features,), name=name + ".bias")

    def __call__(self, x):
        if self.bias:
            x = ops.linear_op(x, self.weight_var, self.bias_var,
                              trans_B=self.weight_transpose)
        else:
            x = ops.matmul_op(x, self.weight_var, trans_B=self.weight_transpose)
        if self.activation is not None:
            x = self.activation(x)
        return x


class Conv2d(BaseLayer):
    def __init__(self, in_channel, out_channel, kernel_size, stride=1,
                 padding=0, initializer=None, bias=True, activation=None,
                 name="conv2d"):
        initializer = initializer or init.GenXavierUniform()
        ksize = kernel_size if isinstance(kernel_size, tuple) \
            else (kernel_size, kernel_size)
        self.stride, self.padding = stride, padding
        self.bias = bias
        self.activation = _resolve_activation(activation)
        self.weight_var = initializer(
            shape=(out_channel, in_channel) + ksize, name=name + ".weight")
        if bias:
            self.bias_var = init.zeros(shape=(out_channel,), name=name + ".bias")

    def __call__(self, x):
        if self.bias:
            x = ops.conv2d_add_bias_op(x, self.weight_var, self.bias_var,
                                       padding=self.padding, stride=self.stride)
        else:
            x = ops.conv2d_op(x, self.weight_var,
                              padding=self.padding, stride=self.stride)
        if self.activation is not None:
            x = self.activation(x)
        return x


class BatchNorm(BaseLayer):
    def __init__(self, num_channels, momentum=0.1, eps=1e-5, name="batchnorm"):
        self.scale_var = init.ones(shape=(num_channels,), name=name + ".scale")
        self.bias_var = init.zeros(shape=(num_channels,), name=name + ".bias")
        self.momentum, self.eps, self.name = momentum, eps, name

    def __call__(self, x):
        return ops.batch_normalization_op(x, self.scale_var, self.bias_var,
                                          momentum=self.momentum, eps=self.eps,
                                          name=self.name)


class LayerNorm(BaseLayer):
    def __init__(self, num_channels, eps=1e-5, name="layernorm"):
        self.scale_var = init.ones(shape=(num_channels,), name=name + ".scale")
        self.bias_var = init.zeros(shape=(num_channels,), name=name + ".bias")
        self.eps = eps

    def __call__(self, x):
        return ops.layer_normalization_op(x, self.scale_var, self.bias_var,
                                          eps=self.eps)


class RMSNorm(BaseLayer):
    """Root-mean-square norm (T5LayerNorm: no mean subtraction, no bias)."""

    def __init__(self, num_channels, eps=1e-6, name="rmsnorm"):
        self.scale_var = init.ones(shape=(num_channels,), name=name + ".scale")
        self.eps = eps

    def __call__(self, x):
        ms = ops.reduce_mean_op(ops.mul_op(x, x), [-1], keepdims=True)
        normed = ops.mul_op(x, ops.broadcastto_op(
            ops.rsqrt_op(ms + self.eps), x))
        return ops.mul_op(normed, ops.broadcastto_op(self.scale_var, normed))


class Embedding(BaseLayer):
    def __init__(self, num_embeddings, embedding_dim, initializer=None,
                 name="embedding", ctx=None):
        initializer = initializer or init.GenXavierNormal()
        self.embedding_table = initializer(
            shape=(num_embeddings, embedding_dim), name=name + ".weight",
            is_embed=True)

    def __call__(self, x):
        return ops.embedding_lookup_op(self.embedding_table, x)


class DropOut(BaseLayer):
    def __init__(self, p=0.5):
        self.keep_prob = 1.0 - p

    def __call__(self, x):
        return ops.dropout_op(x, self.keep_prob)


class MaxPool2d(BaseLayer):
    def __init__(self, kernel_size, stride=1, padding=0):
        self.k, self.s, self.p = kernel_size, stride, padding

    def __call__(self, x):
        return ops.max_pool2d_op(x, self.k, self.k, self.p, self.s)


class AvgPool2d(BaseLayer):
    def __init__(self, kernel_size, stride=1, padding=0):
        self.k, self.s, self.p = kernel_size, stride, padding

    def __call__(self, x):
        return ops.avg_pool2d_op(x, self.k, self.k, self.p, self.s)


class Relu(BaseLayer):
    def __call__(self, x):
        return ops.relu_op(x)


class Reshape(BaseLayer):
    def __init__(self, shape):
        self.shape = shape

    def __call__(self, x):
        return ops.array_reshape_op(x, output_shape=self.shape)


class Identity(BaseLayer):
    def __call__(self, x):
        return x


class Sequence(BaseLayer):
    def __init__(self, *layers):
        self.layers = layers

    def __call__(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class Concatenate(BaseLayer):
    def __init__(self, axis=0):
        self.axis = axis

    def __call__(self, xs):
        return ops.concatenate_op(list(xs), axis=self.axis)


class ConcatenateLayers(BaseLayer):
    def __init__(self, layers, axis=0):
        self.layers, self.axis = layers, axis

    def __call__(self, x):
        return ops.concatenate_op([l(x) for l in self.layers], axis=self.axis)


class SumLayers(BaseLayer):
    def __init__(self, layers):
        self.layers = layers

    def __call__(self, x):
        outs = [l(x) for l in self.layers]
        return outs[0] if len(outs) == 1 else ops.sum_op(outs)


class Slice(BaseLayer):
    def __init__(self, begin, size):
        self.begin, self.size = begin, size

    def __call__(self, x):
        return ops.slice_op(x, begin=self.begin, size=self.size)


class RNN(BaseLayer):
    """Vanilla RNN layer over (batch, time, features) via one scanned loop."""

    def __init__(self, in_dim, hidden, activation="tanh", name="rnn"):
        from .. import initializers as init
        from ..ops.rnn import rnn_op
        self._op = rnn_op
        self.activation = activation
        self.w_ih = init.xavier_uniform((in_dim, hidden), name=f"{name}.w_ih")
        self.w_hh = init.orthogonal((hidden, hidden), name=f"{name}.w_hh")
        self.b = init.zeros((hidden,), name=f"{name}.b")

    def __call__(self, x):
        return self._op(x, self.w_ih, self.w_hh, self.b,
                        activation=self.activation)


class LSTM(BaseLayer):
    """LSTM layer (i,f,g,o gates packed 4H) scanned over time."""

    def __init__(self, in_dim, hidden, name="lstm"):
        from .. import initializers as init
        from ..ops.rnn import lstm_op
        self._op = lstm_op
        self.w_ih = init.xavier_uniform((in_dim, 4 * hidden),
                                        name=f"{name}.w_ih")
        self.w_hh = init.xavier_uniform((hidden, 4 * hidden),
                                        name=f"{name}.w_hh")
        self.b = init.zeros((4 * hidden,), name=f"{name}.b")

    def __call__(self, x):
        return self._op(x, self.w_ih, self.w_hh, self.b)


class GRU(BaseLayer):
    """GRU layer (r,z,n gates packed 3H) scanned over time."""

    def __init__(self, in_dim, hidden, name="gru"):
        from .. import initializers as init
        from ..ops.rnn import gru_op
        self._op = gru_op
        self.w_ih = init.xavier_uniform((in_dim, 3 * hidden),
                                        name=f"{name}.w_ih")
        self.w_hh = init.xavier_uniform((hidden, 3 * hidden),
                                        name=f"{name}.w_hh")
        self.b = init.zeros((3 * hidden,), name=f"{name}.b")

    def __call__(self, x):
        return self._op(x, self.w_ih, self.w_hh, self.b)
