"""MoE gates (reference ``layers/TopGate.py`` topkgating:14 (GShard top-1/2 w/
capacity + balance_loss), ``HashGate.py``, ``KTop1Gate.py``, ``SAMGate.py``,
``BalanceGate.py``)."""
from __future__ import annotations

import math

from .base import BaseLayer
from .. import initializers as init
from .. import ops
from ..ops.moe import topk_gate_op, hash_dispatch_op, balance_assignment_op


class TopKGate(BaseLayer):
    """GShard-style top-1/top-2 gate with capacity + aux balance loss.

    ``__call__(x)`` with x:(tokens, d) → (dispatch, combine, aux_loss).
    """

    def __init__(self, embed_dim, num_tokens, num_experts, k=1,
                 capacity_factor=1.0, name="topk_gate"):
        assert k in (1, 2)
        self.num_experts = num_experts
        self.k = k
        self.capacity = max(1, int(math.ceil(
            k * capacity_factor * num_tokens / num_experts)))
        self.wg = init.xavier_uniform(shape=(embed_dim, num_experts),
                                      name=name + ".wg")

    def __call__(self, x):
        logits = ops.matmul_op(x, self.wg)
        return topk_gate_op(logits, k=self.k, capacity=self.capacity)


class TopKGateSparse(TopKGate):
    """TopKGate emitting index maps for the Pallas row-gather dispatch
    (O(s·m) memory — use for large expert pools where the dense (s, e, c)
    one-hot tensors of :class:`TopKGate` dominate memory).

    ``__call__(x)`` → (token_of_slot, slot_of_token, k_of_slot, gate_w, aux).
    """

    def __call__(self, x):
        from ..ops.moe import topk_gate_sparse_op
        logits = ops.matmul_op(x, self.wg)
        return topk_gate_sparse_op(logits, k=self.k, capacity=self.capacity)


class HashGate(BaseLayer):
    """Token-id hash routing (no learned params, reference HashGate.py)."""

    def __init__(self, num_tokens, num_experts, capacity_factor=1.0,
                 name="hash_gate"):
        self.num_experts = num_experts
        self.capacity = max(1, int(math.ceil(
            capacity_factor * num_tokens / num_experts)))

    def __call__(self, token_ids):
        dispatch = hash_dispatch_op(token_ids, self.num_experts, self.capacity)
        return dispatch, dispatch, None  # combine == dispatch (weight 1)


class KTop1Gate(BaseLayer):
    """Experts split into k prototype groups; every token routes top-1 in
    EACH group (reference ``KTop1Gate.py`` ktop1gating:14).  Returns
    (dispatch, combine, aux_loss)."""

    def __init__(self, embed_dim, num_tokens, num_experts, k=2,
                 capacity_factor=1.0, name="ktop1_gate"):
        assert num_experts % k == 0
        self.k = k
        self.capacity = k * max(1, int(math.ceil(
            capacity_factor * num_tokens / num_experts)))
        self.wg = init.xavier_uniform(shape=(embed_dim, num_experts),
                                      name=name + ".wg")

    def __call__(self, x):
        logits = ops.matmul_op(x, self.wg)
        from ..ops.moe import ktop1_gate_op
        return ktop1_gate_op(logits, k=self.k, capacity=self.capacity)


class SAMGate(BaseLayer):
    """Switch-and-Mix gate (reference ``SAMGate.py`` samgating:22): pick the
    expert GROUP (node) with max summed prob, route top-k within it; returns
    (dispatch, combine, aux_loss) where aux_loss = balance + alignment hinge
    (SamMax.cu semantics).  ``num_local_devices`` is the experts-per-group
    size, matching the reference's ``num_local_gpus``."""

    def __init__(self, embed_dim, num_tokens, num_experts, k=1,
                 capacity_factor=1.0, num_local_devices=8, align_weight=1.0,
                 name="sam_gate"):
        assert num_experts % num_local_devices == 0
        self.k = k
        self.group_size = num_local_devices
        self.align_weight = align_weight
        self.capacity = k * max(1, int(math.ceil(
            capacity_factor * num_tokens / num_experts)))
        self.wg = init.xavier_uniform(shape=(embed_dim, num_experts),
                                      name=name + ".wg")

    def __call__(self, x):
        logits = ops.matmul_op(x, self.wg)
        from ..ops.moe import sam_gate_op
        dispatch, combine, aux, align = sam_gate_op(
            logits, k=self.k, capacity=self.capacity,
            group_size=self.group_size)
        return dispatch, combine, aux + align * self.align_weight


class BalanceAssignmentGate(BaseLayer):
    """BASE layer (reference BalanceGate.py + BalanceAssignment.cu): balanced
    linear assignment of tokens to experts (equal load by construction)."""

    def __init__(self, embed_dim, num_tokens, num_experts, name="balance_gate"):
        self.num_experts = num_experts
        self.num_tokens = num_tokens
        self.we = init.xavier_uniform(shape=(embed_dim, num_experts),
                                      name=name + ".we")

    def __call__(self, x):
        scores = ops.matmul_op(x, self.we)
        return balance_assignment_op(scores)
