"""MoE layer (reference ``layers/moe_layer.py:45`` MoELayer + Expert:7 and the
BASE-layer BalanceAssignment variant:90-133).

TPU-native: expert FFN weights are STACKED along a leading expert axis
(E, d, h) and applied with one batched einsum, so the expert dimension can be
sharded over the 'ep' mesh axis — XLA then emits the token all_to_all that
the reference built from AllToAll.cu + LayoutTransform.cu.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec

from .base import BaseLayer
from .. import initializers as init
from .. import ops
from ..ops.matmul import einsum_op
from ..ops.moe import layout_transform_op, reverse_layout_transform_op


class Expert(BaseLayer):
    """Stacked per-expert 2-layer FFN. Input (E, C, d) → (E, C, d)."""

    def __init__(self, num_experts, embed_dim, hidden_dim=None,
                 activation="relu", name="expert"):
        hidden_dim = hidden_dim or 4 * embed_dim
        self.w1 = init.he_uniform(shape=(num_experts, embed_dim, hidden_dim),
                                  name=name + ".w1")
        self.b1 = init.zeros(shape=(num_experts, 1, hidden_dim),
                             name=name + ".b1")
        self.w2 = init.he_uniform(shape=(num_experts, hidden_dim, embed_dim),
                                  name=name + ".w2")
        self.b2 = init.zeros(shape=(num_experts, 1, embed_dim),
                             name=name + ".b2")
        self.act = {"relu": ops.relu_op, "gelu": ops.gelu_op}[activation]
        # Expert-parallel sharding: expert axis over 'ep'
        for v in (self.w1, self.b1, self.w2, self.b2):
            v.sharding = PartitionSpec("ep")

    def __call__(self, x):
        h = self.act(einsum_op("ecd,edh->ech", x, self.w1) + self.b1)
        return einsum_op("ech,ehd->ecd", h, self.w2) + self.b2


class MoELayer(BaseLayer):
    """gate → dispatch (einsum / a2a) → experts → combine.

    ``__call__(x)`` with x:(tokens, d) → (output (tokens, d), aux_loss|None).
    """

    def __init__(self, gate, experts, name="moe"):
        self.gate = gate
        self.experts = experts
        self.name = name

    def __call__(self, x):
        dispatch, combine, aux = self.gate(x)
        expert_in = layout_transform_op(dispatch, x)        # (E, C, d)
        # annotate EP sharding so SPMD inserts the all_to_all over ICI
        expert_in.sharding = PartitionSpec("ep")
        expert_out = self.experts(expert_in)                # (E, C, d)
        expert_out.sharding = PartitionSpec("ep")
        y = reverse_layout_transform_op(combine, expert_out)  # (tokens, d)
        return y, aux


class SparseMoELayer(BaseLayer):
    """MoE layer on the Pallas row-gather dispatch path (see
    :mod:`hetu_tpu.ops.pallas.moe_dispatch`): no (s, e, c) one-hot tensors,
    so memory stays O(s·d) + O(e·c·d) for any expert count.

    ``gate`` must be a :class:`~hetu_tpu.layers.gates.TopKGateSparse` —
    expert count and capacity are read from it (single source of truth).
    """

    def __init__(self, gate, experts, embed_dim, name="sparse_moe"):
        self.gate = gate
        self.experts = experts
        self.embed_dim = embed_dim

    @property
    def num_experts(self):
        return self.gate.num_experts

    @property
    def capacity(self):
        return self.gate.capacity

    def __call__(self, x):
        from ..ops.moe import sparse_dispatch_op, sparse_combine_op
        tos, sot, kos, gate_w, aux = self.gate(x)
        flat = sparse_dispatch_op(x, tos, sot)              # (E*C, d)
        expert_in = ops.array_reshape_op(
            flat, output_shape=(self.num_experts, self.capacity,
                                self.embed_dim))
        expert_in.sharding = PartitionSpec("ep")
        expert_out = self.experts(expert_in)                # (E, C, d)
        expert_out.sharding = PartitionSpec("ep")
        out_flat = ops.array_reshape_op(
            expert_out, output_shape=(self.num_experts * self.capacity,
                                      self.embed_dim))
        y = sparse_combine_op(out_flat, gate_w, sot, tos, kos)
        return y, aux


class BalancedMoELayer(BaseLayer):
    """BASE-layer variant (reference moe_layer.py:90-133): balanced-assignment
    permutation instead of capacity gating — every expert gets exactly
    tokens/E tokens, no drops.  Needs the static token count (XLA static
    shapes), matching the reference gates' ``num_tokens`` argument."""

    def __init__(self, gate, experts, num_experts, num_tokens, embed_dim,
                 name="base_moe"):
        assert num_tokens % num_experts == 0
        self.gate = gate
        self.experts = experts
        self.num_experts = num_experts
        self.num_tokens = num_tokens
        self.embed_dim = embed_dim

    def __call__(self, x):
        # slot→token permutation from the balanced-assignment gate
        assign = self.gate(x)                      # (tokens,)
        gathered = ops.indexing_op(x, assign)      # (tokens, d) expert-grouped
        cap = self.num_tokens // self.num_experts
        expert_in = ops.array_reshape_op(
            gathered, output_shape=(self.num_experts, cap, self.embed_dim))
        expert_in.sharding = PartitionSpec("ep")
        expert_out = self.experts(expert_in)
        expert_out.sharding = PartitionSpec("ep")
        flat = ops.array_reshape_op(
            expert_out, output_shape=(self.num_tokens, self.embed_dim))
        # inverse permutation: scatter rows back to original token order
        return ops.scatter1d_grad_op(flat, assign, size=self.num_tokens), None
